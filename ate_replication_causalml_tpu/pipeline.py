"""L5 driver — the notebook-equivalent experiment pipeline.

Replicates ``ate_replication.Rmd`` end to end (SURVEY.md §2.2, §3.1):
data ingest → prep (z-score, rename to W/Y, na.omit) → bias injection →
RCT oracle → the full estimator sweep in notebook order → uniform result
table → the three comparison figures.

What the notebook lacks, the driver adds (SURVEY.md §5):

* **Checkpoint/resume** — every estimator's result row is appended to
  ``results.jsonl`` the moment it finishes; re-running with the same
  output directory skips completed estimators (the notebook recomputes
  everything, §5.4).
* **Observability** — per-estimator wall-clock seconds recorded with
  each row (the north star is a wall-clock metric, §5.1).
* **Config as data** — every notebook global and call-site constant
  lives in :class:`SweepConfig` (§5.6).
* **Graceful degradation** (ISSUE 3) — each stage runs under an
  isolation policy: a failing estimator becomes a ``status="failed"``
  row (error, attempts, seconds) instead of aborting the sweep; resume
  retries failed and unresumable rows; reports and figures render
  partial sweeps with failures annotated; a finite-value guard keeps
  NaN/Inf point estimates out of the result set. The ``ATE_TPU_CHAOS``
  fault injector (resilience/chaos.py) exercises all of it on demand.

CLI::

    python -m ate_replication_causalml_tpu.pipeline --out results/ \
        [--csv socialpresswgeooneperhh_NEIGH.csv] [--quick] [--no-plots]
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Callable, Iterable

import jax
import numpy as np

from ate_replication_causalml_tpu import __version__
from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.data.pipeline import (
    PrepConfig,
    inject_bias,
    load_raw_csv,
    prepare_dataset,
)
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like
from ate_replication_causalml_tpu.estimators import (
    EstimatorResult,
    ResultTable,
    ate_condmean_lasso,
    ate_condmean_ols,
    ate_lasso,
    belloni,
    causal_forest_report,
    double_ml,
    doubly_robust,
    doubly_robust_glm,
    logistic_propensity,
    naive_ate,
    prop_score_lasso,
    prop_score_ols,
    prop_score_weight,
    residual_balance_ate,
)
from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.models.forest import rf_oob_propensity
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import (
    ChaosSpecError,
    NonFiniteResult,
)
from ate_replication_causalml_tpu.utils.profiling import StageTimer, xla_trace


# The sweep's result-row manifest, in notebook order (Rmd:128-272) —
# ``run_sweep``'s ``report.results`` contains exactly these methods (the
# oracle rides separately in ``report.oracle``). External contracts
# (the driver's multichip dryrun, tests) assert against THIS tuple, not
# a hard-coded row count, so adding or removing a sweep stage updates
# every consumer in one place.
SWEEP_METHODS = (
    "naive",
    "Direct Method",
    "Propensity_Weighting",
    "Propensity_Regression",
    "Propensity_Weighting_LASSOPS",
    "Single-equation LASSO",
    "Usual LASSO",
    "Doubly Robust with Random Forest PS",
    "Doubly Robust with logistic regression PS",
    "Belloni et.al",
    "Double Machine Learning",
    "residual_balancing",
    "Causal Forest(GRF)",
)


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """Every constant the notebook hardcodes, in one place.

    Tree counts are the notebook's call-site values
    (``ate_replication.Rmd:217, 232, 255``); ``quick()`` scales them down
    for smoke runs.
    """

    prep: PrepConfig = PrepConfig()
    synthetic_pool: int = 120_000   # raw rows generated when no CSV is given
    synthetic_seed: int = 0
    true_ate: float = 0.095         # synthetic generator's target (oracle ≈ this)
    dr_trees: int = 2500            # doubly_robust(..., 2500), Rmd:217
    dml_trees: int = 2000           # double_ml(..., num_tree = 2000), Rmd:232
    cf_trees: int = 2000            # grf num.trees, Rmd:255
    cf_nuisance_trees: int = 500
    forest_depth: int = 9
    balance_iters: int = 12_000     # ADMM budget; 4k leaves ~3e-3 residual at 50k rows
    seed: int = 0                   # jax.random seed for the TPU fast path
    # Parallel-axis composition: with >1 device the sweep shards forest
    # trees / little-bag groups over a tree-axis mesh and CV folds over
    # a fold-axis mesh (SURVEY.md §2.4). False forces single-device.
    use_mesh: bool = True
    # Stage isolation policy (ISSUE 3): "degrade" records a failing
    # estimator as a status="failed" checkpoint/report row and keeps the
    # sweep going (resume retries it); "raise" aborts on first failure.
    fail_policy: str = "degrade"

    def quick(self) -> "SweepConfig":
        return dataclasses.replace(
            self,
            prep=dataclasses.replace(self.prep, n_obs=8_000),
            synthetic_pool=20_000,
            dr_trees=250, dml_trees=200, cf_trees=200, cf_nuisance_trees=100,
            forest_depth=7, balance_iters=4_000,
        )


@dataclasses.dataclass
class SweepReport:
    """Everything the notebook run produces."""

    oracle: EstimatorResult
    results: ResultTable
    n_dropped: int
    n_biased: int
    incorrect_cf_ate: float | None = None
    incorrect_cf_se: float | None = None
    timings_s: dict = dataclasses.field(default_factory=dict)
    figure_paths: list = dataclasses.field(default_factory=list)
    #: method -> {"error", "attempts", "seconds"} for stages the
    #: isolation policy degraded instead of aborting on.
    failures: dict = dataclasses.field(default_factory=dict)


def _jsonsafe(obj):
    """NaN/Inf → None, recursively — report.json and results.jsonl must
    stay valid for strict parsers (the no-SE LASSO rows carry se=NaN)."""
    import math as _m

    if isinstance(obj, float):
        return None if not _m.isfinite(obj) else obj
    if isinstance(obj, dict):
        return {k: _jsonsafe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonsafe(v) for v in obj]
    return obj


class _Checkpoint:
    """Append-only JSONL of finished result rows, keyed by method name.

    The first record is a config fingerprint; a checkpoint written under
    a different config is set aside (renamed ``*.stale`` / ``*.stale.N``
    — never clobbering a prior set-aside) instead of being silently
    reused as current results.

    Torn lines (a kill mid-append, or chaos ``fs:torn_write``) are
    skipped and counted into ``checkpoint_torn_lines_total``. The
    journal stays append-only, so a torn line persists in the file and
    is re-counted on every subsequent resume of the same outdir — the
    metric reports the file's state, not newly lost data (the row
    itself is recomputed on the first resume after the tear).
    """

    def __init__(self, path: str | None, fingerprint: str, log=print):
        self.path = path
        self.done: dict[str, dict] = {}
        if path and os.path.exists(path):
            recs = []
            torn = 0
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        recs.append(json.loads(line))
                    except json.JSONDecodeError:
                        # A kill mid-append leaves a truncated last line;
                        # completed rows before it are still good. Torn
                        # lines are counted — silent data loss must show
                        # up in metrics.json, not only in a log scroll.
                        torn += 1
                        log(f"checkpoint {path}: skipping unparsable line")
            if torn:
                obs.counter(
                    "checkpoint_torn_lines_total",
                    "unparsable results.jsonl lines skipped on resume",
                ).inc(torn)
                obs.emit("checkpoint_torn_lines", status="warning",
                         path=path, lines=torn)
            header = next((r for r in recs if r.get("method") == "__config__"), None)
            if header is None or header.get("fingerprint") != fingerprint:
                stale = _unused_stale_path(path)
                os.replace(path, stale)
                log(f"checkpoint {path} was written under a different config; "
                    f"moved to {stale} and starting fresh")
            else:
                self.done = {r["method"]: r for r in recs if r["method"] != "__config__"}
        if path and not self.done and not os.path.exists(path):
            # Atomic header write: a kill here must leave either no
            # checkpoint or a valid one-line one, never a torn header
            # that would stale-cycle the next resume. Appends below
            # stay plain "a" — the reader already tolerates a
            # truncated LAST line, and atomicity per row would mean
            # rewriting the whole journal.
            obs.atomic_write_text(path, json.dumps({"method": "__config__",
                                                    "fingerprint": fingerprint}) + "\n")

    def get(self, method: str) -> dict | None:
        return self.done.get(method)

    def put(self, rec: dict) -> None:
        rec = _jsonsafe(rec)
        self.done[rec["method"]] = rec
        if self.path:
            line = json.dumps(rec) + "\n"
            inj = chaos.active()
            if inj is not None:
                # fs:torn_write chaos: persist this row torn, the way a
                # kill mid-append would. The in-memory copy above keeps
                # the CURRENT run correct; the reader's torn-line skip +
                # recompute-on-resume is the path under test.
                line = inj.torn_line(line, site=self.path)
            with open(self.path, "a") as f:
                f.write(line)


def _unused_stale_path(path: str) -> str:
    """First free ``path + ".stale"[.N]`` — a second config change must
    not clobber the results set aside by the first one."""
    stale = path + ".stale"
    n = 0
    while os.path.exists(stale):
        n += 1
        stale = f"{path}.stale.{n}"
    return stale


#: Keys a checkpoint row must carry to resume. ``seconds``/extras are
#: optional (legacy rows), but the statistical payload is not.
_REQUIRED_ROW_KEYS = ("method", "ate", "lower_ci", "upper_ci", "se")


def _row_resumable(rec: dict) -> tuple[bool, str]:
    """Whether a checkpoint row can be resumed as-is, else why not
    (hand-edited/legacy rows missing keys, rows whose ate is not a
    finite number, and ``status="failed"`` rows all fall through to a
    recompute instead of crashing the resume)."""
    for k in _REQUIRED_ROW_KEYS:
        if k not in rec:
            return False, f"missing key {k!r}"
    if rec.get("status", "ok") != "ok":
        return False, f"status={rec.get('status')!r}"
    ate = rec["ate"]
    if isinstance(ate, bool) or not isinstance(ate, (int, float)):
        return False, f"non-numeric ate {ate!r}"
    if not math.isfinite(ate):
        return False, f"non-finite ate {ate!r}"
    return True, ""


def build_frames(
    config: SweepConfig, csv_path: str | None = None
) -> tuple[CausalFrame, CausalFrame, int]:
    """Ingest → prep → bias injection: the notebook's df and df_mod."""
    if csv_path:
        raw = load_raw_csv(csv_path)
    else:
        raw = make_ggl_like(
            config.synthetic_pool, seed=config.synthetic_seed, true_ate=config.true_ate
        )
    df = prepare_dataset(raw, config.prep)
    df_mod, dropped = inject_bias(df, config.prep)
    return df, df_mod, len(dropped)


def run_sweep(
    config: SweepConfig = SweepConfig(),
    csv_path: str | None = None,
    outdir: str | None = None,
    plots: bool = True,
    log: Callable[[str], None] = print,
) -> SweepReport:
    """The full notebook run, checkpointed and timed.

    Telemetry (observability/): the whole run is a ``run_sweep`` span;
    every estimator stage is a child span whose status records whether
    it COMPUTED or RESUMED from the checkpoint — the distinction the
    round-3 stale-resume incident had to be reconstructed from prints.
    With an ``outdir``, ``metrics.json`` + ``events.jsonl`` + a
    Prometheus textfile land next to ``report.json`` (all written
    atomically). ``ATE_TPU_TELEMETRY=0`` disables all of it; estimator
    outputs are bit-identical either way.
    """
    # Cache counters must exist in metrics.json even when the embedding
    # process never enabled the persistent cache (idempotent).
    obs.install_jax_monitoring()
    try:
        with obs.span("run_sweep", out=outdir or "", csv=csv_path or "synthetic"):
            report = _run_sweep_impl(config, csv_path, outdir, plots, log)
        return report
    finally:
        # Export in a finally: a failing run is exactly the run whose
        # telemetry (retry events, partial stage counters) matters
        # most. Device-memory gauges first (TPU reports them; CPU has
        # none), then the exporter trio — metrics.json / events.jsonl /
        # metrics.prom — beside report.json, after the root span has
        # closed so the event log contains the complete run.
        if outdir:
            try:
                obs.record_device_memory(context="run_sweep")
                written = obs.write_run_artifacts(outdir)
                if written:
                    log(f"telemetry: {', '.join(written)}")
            except Exception as e:  # noqa: BLE001 — observer must not
                # replace the run's real exception (full disk, outdir
                # deleted mid-run) with an export error.
                log(f"telemetry export failed: {e!r}")


def _run_sweep_impl(
    config: SweepConfig,
    csv_path: str | None,
    outdir: str | None,
    plots: bool,
    log: Callable[[str], None],
) -> SweepReport:
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    # Arm chaos NOW, with fresh per-run fault budgets: a malformed
    # ATE_TPU_CHAOS spec must fail the run at config time
    # (ChaosSpecError), not surface as thirteen degraded stages — and a
    # second sweep in the same process must get full budgets, not the
    # remnants the previous run left.
    chaos.reset()
    chaos.active()
    # Resume is only valid for the same config + data source + device
    # topology (mesh and single-device runs are statistically equivalent
    # but not bit-identical) + framework version: estimator code changes
    # between versions silently resurface stale rows otherwise (observed
    # in round 3 — a QP-solver upgrade resumed the pre-upgrade numbers).
    mesh_devices = jax.device_count() if config.use_mesh else 1
    fingerprint = (
        f"{config!r}|csv={csv_path or 'synthetic'}|devices={mesh_devices}"
        f"|version={__version__}"
    )
    ckpt = _Checkpoint(
        os.path.join(outdir, "results.jsonl") if outdir else None,
        fingerprint, log=log,
    )

    df, df_mod, n_dropped = build_frames(config, csv_path)
    log(f"prepared df n={df.n}, dropped {n_dropped} -> df_mod n={df_mod.n} "
        f"(reference on real data: 41,062 dropped, BASELINE.md)")

    timer = StageTimer()
    report = SweepReport(
        oracle=None, results=ResultTable(), n_dropped=n_dropped, n_biased=df_mod.n,
        timings_s=timer.seconds,
    )
    # Deterministic per-stage keys (stable across resume: skipping a
    # completed stage must not shift the keys of later stages).
    import zlib

    root_key = jax.random.key(config.seed)

    def key_for(name: str) -> jax.Array:
        return jax.random.fold_in(root_key, zlib.crc32(name.encode()))

    # Parallel-axis composition (SURVEY.md §2.4): on a multi-device
    # mesh, forests shard trees over TREE_AXIS and every cv.glmnet
    # shards folds over FOLD_AXIS. Resume note: mesh vs single-device
    # runs produce statistically equivalent but not bit-identical
    # numbers, so the device count is part of the config fingerprint
    # (see above).
    tree_mesh = None
    fold_axis = None
    fold_ctx = None
    if mesh_devices > 1:
        from ate_replication_causalml_tpu.parallel.mesh import (
            FOLD_AXIS,
            TREE_AXIS,
            make_mesh,
            use_mesh,
        )

        tree_mesh = make_mesh((TREE_AXIS,))
        fold_axis = FOLD_AXIS
        fold_mesh = make_mesh((FOLD_AXIS,))
        fold_ctx = lambda: use_mesh(fold_mesh)
        log(f"mesh: {jax.device_count()} devices — tree + fold axes active")

    def with_folds(fn):
        """Run ``fn`` under the fold-axis mesh when one is active."""
        if fold_ctx is None:
            return fn()
        with fold_ctx():
            return fn()

    stage_c = obs.counter(
        "sweep_stage_total", "sweep stages by resume-vs-computed status"
    )

    def stage(method: str, fn: Callable[[], object]) -> EstimatorResult:
        """Run one estimator with timing + checkpointing + telemetry,
        under the config's isolation policy. ``fn`` returns an
        EstimatorResult, or (EstimatorResult, extras-dict) — extras ride
        the checkpoint record (read back via ``ckpt.get``). The stage
        span's status records whether the row was computed, resumed
        from the checkpoint, or failed-and-degraded.

        Degradation (``fail_policy="degrade"``): an exception (or a
        non-finite ATE — the finite-value guard) becomes a
        ``status="failed"`` row carrying the error, attempt count and
        seconds, in both the checkpoint and the report; the sweep
        continues. Resume retries failed rows — and rows a hand edit or
        format drift made unresumable (``_row_resumable``) — instead of
        crashing on them. ``KeyboardInterrupt``/``SystemExit`` always
        propagate: an operator's ^C is not an estimator failure."""
        cached = ckpt.get(method)
        with obs.span("sweep_stage", method=method) as sp:
            if cached is not None:
                resumable, why = _row_resumable(cached)
                if resumable:
                    sp.set_status("resumed")
                    stage_c.inc(1, method=method, status="resumed")
                    log(f"  [resume] {method}: ate={cached['ate']:.4f}")
                    nanf = lambda v: float("nan") if v is None else v
                    res = EstimatorResult(
                        method=cached["method"], ate=cached["ate"],
                        lower_ci=nanf(cached["lower_ci"]), upper_ci=nanf(cached["upper_ci"]),
                        se=nanf(cached["se"]),
                    )
                    timer.seconds[method] = cached.get("seconds", 0.0)
                    return res
                obs.emit("checkpoint_row_rejected", status="retrying",
                         method=method, reason=why)
                log(f"  [retry] {method}: checkpoint row not resumable "
                    f"({why}); recomputing")
            sp.set_status("computed")
            # The prior attempt count rides the same hand-editable row
            # _row_resumable guards, so tolerate garbage here too.
            prior = cached.get("attempts") if cached else 0
            attempts = (
                int(prior) + 1
                if isinstance(prior, (int, float)) and not isinstance(prior, bool)
                else 1
            )
            try:
                # xla_trace sanitizes the label itself (method names carry
                # spaces/parens/dots — e.g. ``Causal Forest(GRF)``).
                with timer.stage(method), xla_trace(method):
                    inj = chaos.active()
                    if inj is not None:
                        inj.maybe_fail_stage(method)
                    out = fn()
                res, extras = out if isinstance(out, tuple) else (out, {})
                if not math.isfinite(res.ate):
                    raise NonFiniteResult(
                        f"estimator returned ATE {res.ate!r} from finite "
                        f"inputs — refusing to record a garbage row"
                    )
            except (KeyboardInterrupt, SystemExit, ChaosSpecError):
                # ^C is not an estimator failure, and a malformed chaos
                # spec (env edited mid-run) is an operator error — both
                # must abort, never degrade.
                raise
            except Exception as e:
                if config.fail_policy != "degrade":
                    raise
                dt = timer.seconds.get(method, 0.0)
                err = f"{type(e).__name__}: {e}"
                sp.set_status("failed")
                sp.set_attr("error", err)
                stage_c.inc(1, method=method, status="failed")
                obs.emit("sweep_stage_failed", status="error", method=method,
                         error=err, attempts=attempts)
                report.failures[method] = {
                    "error": err, "attempts": attempts, "seconds": round(dt, 3),
                }
                nan = float("nan")
                res = EstimatorResult(method=method, ate=nan, lower_ci=nan,
                                      upper_ci=nan, se=nan, status="failed")
                ckpt.put(dict(res.to_dict(), error=err, attempts=attempts,
                              seconds=round(dt, 3)))
                log(f"  [FAILED] {method}: {err} (attempt {attempts}, "
                    f"{dt:.1f}s) — degrading, sweep continues")
                return res
            dt = timer.seconds[method]
            sp.set_attr("seconds", round(dt, 3))
            stage_c.inc(1, method=method, status="computed")
            ckpt.put(dict(res.to_dict(), seconds=round(dt, 3),
                          attempts=attempts, **extras))
            log(f"  {method}: ate={res.ate:.4f} ci=[{res.lower_ci:.4f},{res.upper_ci:.4f}] "
                f"({dt:.1f}s)")
            return res

    # ── The sweep, in notebook order (Rmd:128-272) ────────────────────
    report.oracle = stage("oracle", lambda: naive_ate(df, method="oracle"))
    add = report.results.append

    add(stage("naive", lambda: naive_ate(df_mod)))
    add(stage("Direct Method", lambda: ate_condmean_ols(df_mod)))

    # Shared logistic propensity (Rmd:164-168), fit lazily so a fully
    # checkpointed rerun never pays for it.
    _p_log = []

    def p_logistic():
        if not _p_log:
            _p_log.append(logistic_propensity(df_mod.x, df_mod.w))
        return _p_log[0]

    add(stage("Propensity_Weighting",
              lambda: prop_score_weight(df_mod, p_logistic())))
    add(stage("Propensity_Regression",
              lambda: prop_score_ols(df_mod, p_logistic())))
    add(stage("Propensity_Weighting_LASSOPS",
              lambda: with_folds(lambda: prop_score_weight(
                  df_mod, prop_score_lasso(df_mod, key=key_for("ps_lasso"),
                                           fold_axis=fold_axis),
                  method="Propensity_Weighting_LASSOPS"))))
    add(stage("Single-equation LASSO",
              lambda: with_folds(lambda: ate_condmean_lasso(
                  df_mod, key=key_for("seq_lasso"), fold_axis=fold_axis))))
    add(stage("Usual LASSO",
              lambda: with_folds(lambda: ate_lasso(
                  df_mod, key=key_for("usual_lasso"), fold_axis=fold_axis))))
    add(stage("Doubly Robust with Random Forest PS",
              lambda: doubly_robust(
                  df_mod,
                  lambda f: rf_oob_propensity(
                      f, key=key_for("dr_rf_prop"), n_trees=config.dr_trees,
                      depth=config.forest_depth, mesh=tree_mesh),
                  key=key_for("dr_rf"))))
    add(stage("Doubly Robust with logistic regression PS",
              lambda: doubly_robust_glm(df_mod, key=key_for("dr_glm"))))
    add(stage("Belloni et.al",
              lambda: with_folds(lambda: belloni(
                  df_mod, key=key_for("belloni"), fold_axis=fold_axis))))
    add(stage("Double Machine Learning",
              lambda: double_ml(df_mod, n_trees=config.dml_trees,
                                depth=config.forest_depth, key=key_for("dml"),
                                mesh=tree_mesh)))
    add(stage("residual_balancing",
              lambda: residual_balance_ate(df_mod, key=key_for("balance"),
                                           max_iters=config.balance_iters)))

    # Causal forest: the result row plus the notebook's 'incorrect' demo
    # (Rmd:258-262). The demo values ride the checkpoint record as stage
    # extras.
    def cf_fn():
        cf = causal_forest_report(
            df_mod, key=key_for("causal_forest"), n_trees=config.cf_trees,
            nuisance_trees=config.cf_nuisance_trees, mesh=tree_mesh)
        log(f"  Incorrect ATE: {cf.incorrect_ate:.3f} (SE: {cf.incorrect_se:.3f})"
            f"  [deliberate negative example, Rmd:262]")
        return cf.result, {"incorrect_ate": cf.incorrect_ate,
                           "incorrect_se": cf.incorrect_se}

    add(stage("Causal Forest(GRF)", cf_fn))
    cf_rec = ckpt.get("Causal Forest(GRF)") or {}
    report.incorrect_cf_ate = cf_rec.get("incorrect_ate")
    report.incorrect_cf_se = cf_rec.get("incorrect_se")

    # Producer-side manifest check: the stage literals above ARE the
    # sweep; this catches a stage added/reordered without updating
    # SWEEP_METHODS at the definition site, in every test path (review
    # r5: the tuple is otherwise a parallel transcription).
    assert [r.method for r in report.results] == list(SWEEP_METHODS), (
        [r.method for r in report.results]
    )

    if outdir:
        # Atomic (tmp + os.replace): a kill mid-write must not leave a
        # truncated report.json next to a valid results.jsonl.
        obs.atomic_write_json(
            os.path.join(outdir, "report.json"),
            _jsonsafe({
                "oracle": report.oracle.to_dict(),
                "results": [r.to_dict() for r in report.results],
                "n_dropped": report.n_dropped,
                "n_biased": report.n_biased,
                "incorrect_cf": [report.incorrect_cf_ate, report.incorrect_cf_se],
                "timings_s": {k: round(v, 3) for k, v in report.timings_s.items()},
                "failures": report.failures,
            }),
        )
    if plots and outdir:
        from ate_replication_causalml_tpu.viz import notebook_figures

        # A degraded oracle cannot anchor the reference band; the
        # figures render the partial sweep with failures annotated.
        oracle_fig = (
            report.oracle
            if report.oracle is not None and math.isfinite(report.oracle.ate)
            else None
        )
        report.figure_paths = notebook_figures(
            report.results, oracle_fig, outdir)
        log(f"figures: {report.figure_paths}")
    if outdir:
        log(f"report: {write_report_md(report, outdir, csv_path=csv_path)}")
    return report


def write_report_md(report: SweepReport, outdir: str,
                    csv_path: str | None = None) -> str:
    """Render the notebook-equivalent replication document
    (``results/REPORT.md``), mirroring ``ate_replication.md`` section by
    section — data prep counts, RCT oracle vs naive, the estimator
    comparison, the deliberate 'Incorrect ATE' demo line
    (``ate_replication.md:294``), and the three figures inline — so a
    reader can diff the two documents."""
    fmt = lambda v: "—" if v is None or (isinstance(v, float) and not np.isfinite(v)) else f"{v:.4f}"
    o = report.oracle
    lines = [
        "# ATE replication — TPU-native run",
        "",
        "Rendered by `ate_replication_causalml_tpu.pipeline` (the "
        "`ate_replication.md` equivalent; reference sections cited inline).",
        "",
        "## Data",
        "",
        f"* Source: `{csv_path}`" if csv_path else
        "* Source: synthetic GGL-like generator (real CSV unavailable — "
        "see RESULTS.md 'Real-dataset attempt'; fetch via "
        "`scripts/fetch_ggl.sh`)",
        f"* Rows after prep (sampled, scaled, na.omit): "
        f"{report.n_dropped + report.n_biased}",
        "* Bias injection (`ate_replication.Rmd:97-123`) dropped:",
        "",
        "```",
        f"## [1] {report.n_dropped}",
        "```",
        "",
        f"  (reference on the real data: `## [1] 41062`, "
        f"`ate_replication.md:118`)",
        f"* Biased sample `df_mod`: {report.n_biased} rows",
        "",
        "## RCT oracle vs naive on the biased sample",
        "",
        "| Method | ATE | 95% CI |",
        "|---|---|---|",
        f"| RCT (oracle) | {fmt(o.ate)} | [{fmt(o.lower_ci)}, {fmt(o.upper_ci)}] |",
    ]
    naive = next((r for r in report.results if r.method == "naive"), None)
    if naive is not None:
        lines.append(
            f"| naive (biased) | {fmt(naive.ate)} | "
            f"[{fmt(naive.lower_ci)}, {fmt(naive.upper_ci)}] |")
    lines += [
        "",
        "The naive estimate on the biased sample is far from the RCT "
        "answer — the injected selection bias every estimator below "
        "must remove (`ate_replication.md:157`).",
        "",
    ]
    figs = [os.path.basename(p) for p in report.figure_paths]
    if len(figs) >= 1:
        lines += [f"![oracle vs naive]({figs[0]})", ""]
    lines += [
        "## Estimator comparison (notebook order, `Rmd:128-272`)",
        "",
        "| Method | ATE | 95% CI | seconds |",
        "|---|---|---|---|",
    ]
    for r in report.results:
        if getattr(r, "status", "ok") != "ok":
            lines.append(f"| {r.method} | ✗ failed | — | — |")
            continue
        secs = report.timings_s.get(r.method)
        lines.append(
            f"| {r.method} | {fmt(r.ate)} | [{fmt(r.lower_ci)}, "
            f"{fmt(r.upper_ci)}] | {secs:.1f} |" if secs is not None else
            f"| {r.method} | {fmt(r.ate)} | [{fmt(r.lower_ci)}, "
            f"{fmt(r.upper_ci)}] | — |")
    if report.failures:
        lines += [
            "",
            "### Degraded stages",
            "",
            "The sweep's isolation policy recorded these estimators as "
            "failed and carried on (partial coverage, not an aborted "
            "run); re-running with the same output directory retries "
            "exactly these rows:",
            "",
            "| Method | error | attempts |",
            "|---|---|---|",
        ]
        # Raw exception text can carry '|' (shape errors) or backticks —
        # escape so one bad message cannot corrupt the table markup.
        esc = lambda s: str(s).replace("|", "\\|").replace("`", "'")
        for m, f in report.failures.items():
            lines.append(f"| {m} | `{esc(f.get('error', '?'))}` | {f.get('attempts', '?')} |")
    if len(figs) >= 2:
        lines += ["", f"![regression methods]({figs[1]})"]
    lines += [
        "",
        "## Causal forest: the deliberate negative example",
        "",
        "The mean of CATE predictions with SE = sqrt(mean per-point "
        "variance) is the WRONG way to aggregate "
        "(`ate_replication.Rmd:258-262`; printed as "
        "`Incorrect ATE: 0.083 (SE: 0.198)` on the real data, "
        "`ate_replication.md:294`):",
        "",
        "```",
    ]
    if report.incorrect_cf_ate is not None:
        lines.append(
            f"## Incorrect ATE: {report.incorrect_cf_ate:.3f} "
            f"(SE: {report.incorrect_cf_se:.3f})")
    lines += [
        "```",
        "",
        "The correct doubly-robust aggregation "
        "(`grf::estimate_average_effect` equivalent) is the "
        "`Causal Forest(GRF)` row above.",
        "",
    ]
    if len(figs) >= 3:
        lines += [f"![causal ML methods]({figs[2]})", ""]
    path = os.path.join(outdir, "REPORT.md")
    obs.atomic_write_text(path, "\n".join(lines))
    return path


def main(argv: Iterable[str] | None = None) -> SweepReport:
    import argparse

    from ate_replication_causalml_tpu.utils.compile_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="results", help="output directory")
    ap.add_argument("--csv", default=None,
                    help="path to socialpresswgeooneperhh_NEIGH.csv (else synthetic)")
    ap.add_argument("--quick", action="store_true", help="small smoke-run sizes")
    ap.add_argument("--no-plots", action="store_true")
    args = ap.parse_args(argv if argv is None else list(argv))

    config = SweepConfig()
    if args.quick:
        config = config.quick()
    report = run_sweep(config, csv_path=args.csv, outdir=args.out,
                       plots=not args.no_plots)
    print(repr(report.results))
    return report


if __name__ == "__main__":
    main()

"""L5 driver — the notebook-equivalent experiment pipeline.

Replicates ``ate_replication.Rmd`` end to end (SURVEY.md §2.2, §3.1):
data ingest → prep (z-score, rename to W/Y, na.omit) → bias injection →
RCT oracle → the full estimator sweep in notebook order → uniform result
table → the three comparison figures.

What the notebook lacks, the driver adds (SURVEY.md §5):

* **Checkpoint/resume** — every estimator's result row is appended to
  ``results.jsonl`` the moment it finishes; re-running with the same
  output directory skips completed estimators (the notebook recomputes
  everything, §5.4).
* **Observability** — per-estimator wall-clock seconds recorded with
  each row (the north star is a wall-clock metric, §5.1).
* **Config as data** — every notebook global and call-site constant
  lives in :class:`SweepConfig` (§5.6).
* **Graceful degradation** (ISSUE 3) — each stage runs under an
  isolation policy: a failing estimator becomes a ``status="failed"``
  row (error, attempts, seconds) instead of aborting the sweep; resume
  retries failed and unresumable rows; reports and figures render
  partial sweeps with failures annotated; a finite-value guard keeps
  NaN/Inf point estimates out of the result set. The ``ATE_TPU_CHAOS``
  fault injector (resilience/chaos.py) exercises all of it on demand.
* **Concurrent scheduling** (ISSUE 4) — the sweep is a DAG, not a
  list: stages declare the nuisance artifacts they consume (logistic
  propensity, LASSO PS path, fold masks, RF OOB propensity, the AIPW
  outcome-model mu pair) and a bounded worker pool
  (``scheduler/engine.py``) executes ready stages concurrently over a
  fit-once artifact cache, while journal/report/figure/log order stays
  the fixed notebook order and every row is bit-identical to the
  sequential sweep (per-stage fold-in keys make stage numerics
  order-independent). ``--sequential`` (or
  ``ATE_TPU_SWEEP_SEQUENTIAL=1``) is the single-threaded escape hatch;
  ``ATE_TPU_SWEEP_WORKERS`` bounds the pool; a background compile-
  prefetch lane primes the persistent compile cache for upcoming
  stages when that cache is enabled (``ATE_TPU_SWEEP_PREFETCH``
  overrides).

CLI::

    python -m ate_replication_causalml_tpu.pipeline --out results/ \
        [--csv socialpresswgeooneperhh_NEIGH.csv] [--quick] [--no-plots] \
        [--sequential] [--workers N]
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from typing import Callable, Iterable

import jax
import numpy as np

from ate_replication_causalml_tpu import __version__
from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.data.pipeline import (
    PrepConfig,
    inject_bias,
    load_raw_csv,
    prepare_dataset,
)
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like
from ate_replication_causalml_tpu.estimators import (
    EstimatorResult,
    ResultTable,
    ate_condmean_lasso,
    ate_condmean_ols,
    ate_lasso,
    belloni,
    causal_forest_report,
    double_ml,
    doubly_robust,
    doubly_robust_glm,
    logistic_propensity,
    naive_ate,
    prop_score_lasso,
    prop_score_ols,
    prop_score_weight,
    residual_balance_ate,
)
from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.models.forest import rf_oob_propensity
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import (
    ChaosSpecError,
    ChaosStageFault,
    NonFiniteResult,
)
from ate_replication_causalml_tpu.scheduler import (
    ArtifactSpec,
    StageSpec,
    SweepEngine,
    default_workers,
)
from ate_replication_causalml_tpu.utils.profiling import (
    StageTimer,
    xla_trace,
    xprof_annotation,
    xprof_run,
)


# The sweep's result-row manifest, in notebook order (Rmd:128-272) —
# ``run_sweep``'s ``report.results`` contains exactly these methods (the
# oracle rides separately in ``report.oracle``). External contracts
# (the driver's multichip dryrun, tests) assert against THIS tuple, not
# a hard-coded row count, so adding or removing a sweep stage updates
# every consumer in one place.
SWEEP_METHODS = (
    "naive",
    "Direct Method",
    "Propensity_Weighting",
    "Propensity_Regression",
    "Propensity_Weighting_LASSOPS",
    "Single-equation LASSO",
    "Usual LASSO",
    "Doubly Robust with Random Forest PS",
    "Doubly Robust with logistic regression PS",
    "Belloni et.al",
    "Double Machine Learning",
    "residual_balancing",
    "Causal Forest(GRF)",
)


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """Every constant the notebook hardcodes, in one place.

    Tree counts are the notebook's call-site values
    (``ate_replication.Rmd:217, 232, 255``); ``quick()`` scales them down
    for smoke runs.
    """

    prep: PrepConfig = PrepConfig()
    synthetic_pool: int = 120_000   # raw rows generated when no CSV is given
    synthetic_seed: int = 0
    true_ate: float = 0.095         # synthetic generator's target (oracle ≈ this)
    dr_trees: int = 2500            # doubly_robust(..., 2500), Rmd:217
    dml_trees: int = 2000           # double_ml(..., num_tree = 2000), Rmd:232
    cf_trees: int = 2000            # grf num.trees, Rmd:255
    cf_nuisance_trees: int = 500
    forest_depth: int = 9
    balance_iters: int = 12_000     # ADMM budget; 4k leaves ~3e-3 residual at 50k rows
    seed: int = 0                   # jax.random seed for the TPU fast path
    # Parallel-axis composition: with >1 device the sweep shards forest
    # trees / little-bag groups over a tree-axis mesh and CV folds over
    # a fold-axis mesh (SURVEY.md §2.4). False forces single-device.
    use_mesh: bool = True
    # Stage isolation policy (ISSUE 3): "degrade" records a failing
    # estimator as a status="failed" checkpoint/report row and keeps the
    # sweep going (resume retries it); "raise" aborts on first failure.
    fail_policy: str = "degrade"

    def quick(self) -> "SweepConfig":
        return dataclasses.replace(
            self,
            prep=dataclasses.replace(self.prep, n_obs=8_000),
            synthetic_pool=20_000,
            dr_trees=250, dml_trees=200, cf_trees=200, cf_nuisance_trees=100,
            forest_depth=7, balance_iters=4_000,
        )


@dataclasses.dataclass
class SweepReport:
    """Everything the notebook run produces."""

    oracle: EstimatorResult
    results: ResultTable
    n_dropped: int
    n_biased: int
    incorrect_cf_ate: float | None = None
    incorrect_cf_se: float | None = None
    timings_s: dict = dataclasses.field(default_factory=dict)
    figure_paths: list = dataclasses.field(default_factory=list)
    #: method -> {"error", "attempts", "seconds"} for stages the
    #: isolation policy degraded instead of aborting on.
    failures: dict = dataclasses.field(default_factory=dict)


def _jsonsafe(obj):
    """NaN/Inf → None, recursively — report.json and results.jsonl must
    stay valid for strict parsers (the no-SE LASSO rows carry se=NaN)."""
    import math as _m

    if isinstance(obj, float):
        return None if not _m.isfinite(obj) else obj
    if isinstance(obj, dict):
        return {k: _jsonsafe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonsafe(v) for v in obj]
    return obj


class _Checkpoint:
    """Append-only JSONL of finished result rows, keyed by method name.

    The first record is a config fingerprint; a checkpoint written under
    a different config is set aside (renamed ``*.stale`` / ``*.stale.N``
    — never clobbering a prior set-aside) instead of being silently
    reused as current results.

    Torn lines (a kill mid-append, or chaos ``fs:torn_write``) are
    skipped and counted into ``checkpoint_torn_lines_total``. The
    journal stays append-only, so a torn line persists in the file and
    is re-counted on every subsequent resume of the same outdir — the
    metric reports the file's state, not newly lost data (the row
    itself is recomputed on the first resume after the tear).
    """

    def __init__(self, path: str | None, fingerprint: str, log=print):
        self.path = path
        # Appends are serialized (ISSUE 4): the scheduler's ordered
        # committer is single-flight by construction, but the journal's
        # torn-line/resume semantics are load-bearing enough that the
        # writer enforces its own mutual exclusion too (graftlint
        # JGL008 checks it).
        self._lock = threading.Lock()
        self.done: dict[str, dict] = {}
        if path and os.path.exists(path):
            recs = []
            torn = 0
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        recs.append(json.loads(line))
                    except json.JSONDecodeError:
                        # A kill mid-append leaves a truncated last line;
                        # completed rows before it are still good. Torn
                        # lines are counted — silent data loss must show
                        # up in metrics.json, not only in a log scroll.
                        torn += 1
                        log(f"checkpoint {path}: skipping unparsable line")
            if torn:
                obs.counter(
                    "checkpoint_torn_lines_total",
                    "unparsable results.jsonl lines skipped on resume",
                ).inc(torn)
                obs.emit("checkpoint_torn_lines", status="warning",
                         path=path, lines=torn)
            header = next((r for r in recs if r.get("method") == "__config__"), None)
            if header is None or header.get("fingerprint") != fingerprint:
                stale = _unused_stale_path(path)
                os.replace(path, stale)
                log(f"checkpoint {path} was written under a different config; "
                    f"moved to {stale} and starting fresh")
            else:
                self.done = {r["method"]: r for r in recs if r["method"] != "__config__"}
        if path and not self.done and not os.path.exists(path):
            # Atomic header write: a kill here must leave either no
            # checkpoint or a valid one-line one, never a torn header
            # that would stale-cycle the next resume. Appends below
            # stay plain "a" — the reader already tolerates a
            # truncated LAST line, and atomicity per row would mean
            # rewriting the whole journal.
            obs.atomic_write_text(path, json.dumps({"method": "__config__",
                                                    "fingerprint": fingerprint}) + "\n")

    def get(self, method: str) -> dict | None:
        return self.done.get(method)

    def put(self, rec: dict) -> None:
        rec = _jsonsafe(rec)
        with self._lock:
            self.done[rec["method"]] = rec
            if self.path:
                line = json.dumps(rec) + "\n"
                inj = chaos.active()
                if inj is not None:
                    # tamper:journal chaos first (ISSUE 15): persist a
                    # VALID line carrying a silently wrong ate — the
                    # corruption only the campaign's bit-identity
                    # invariant can catch. A tampered row is NEVER also
                    # torn: tearing it would drop the row the reader
                    # skips anyway, erasing the planted corruption while
                    # its injection stays recorded — a tamper the
                    # registry can no longer detect. The torn_write
                    # budget keeps for the next (untampered) append.
                    # fs:torn_write otherwise persists this row torn,
                    # the way a kill mid-append would. The in-memory
                    # copy above keeps the CURRENT run correct; the
                    # reader's torn-line skip + recompute-on-resume is
                    # the path under test.
                    tampered = inj.tamper_line(line, site=self.path)
                    if tampered == line:
                        line = inj.torn_line(line, site=self.path)
                    else:
                        line = tampered
                with open(self.path, "a") as f:
                    f.write(line)


def _unused_stale_path(path: str) -> str:
    """First free ``path + ".stale"[.N]`` — a second config change must
    not clobber the results set aside by the first one."""
    stale = path + ".stale"
    n = 0
    while os.path.exists(stale):
        n += 1
        stale = f"{path}.stale.{n}"
    return stale


#: Keys a checkpoint row must carry to resume. ``seconds``/extras are
#: optional (legacy rows), but the statistical payload is not.
_REQUIRED_ROW_KEYS = ("method", "ate", "lower_ci", "upper_ci", "se")


def _row_resumable(rec: dict) -> tuple[bool, str]:
    """Whether a checkpoint row can be resumed as-is, else why not
    (hand-edited/legacy rows missing keys, rows whose ate is not a
    finite number, and ``status="failed"`` rows all fall through to a
    recompute instead of crashing the resume)."""
    for k in _REQUIRED_ROW_KEYS:
        if k not in rec:
            return False, f"missing key {k!r}"
    if rec.get("status", "ok") != "ok":
        return False, f"status={rec.get('status')!r}"
    ate = rec["ate"]
    if isinstance(ate, bool) or not isinstance(ate, (int, float)):
        return False, f"non-numeric ate {ate!r}"
    if not math.isfinite(ate):
        return False, f"non-finite ate {ate!r}"
    return True, ""


def build_frames(
    config: SweepConfig, csv_path: str | None = None
) -> tuple[CausalFrame, CausalFrame, int]:
    """Ingest → prep → bias injection: the notebook's df and df_mod."""
    if csv_path:
        raw = load_raw_csv(csv_path)
    else:
        raw = make_ggl_like(
            config.synthetic_pool, seed=config.synthetic_seed, true_ate=config.true_ate
        )
    df = prepare_dataset(raw, config.prep)
    df_mod, dropped = inject_bias(df, config.prep)
    return df, df_mod, len(dropped)


def _resolve_scheduler(
    scheduler: str | None, workers: int | None, log: Callable[[str], None]
) -> int:
    """Worker-pool width from the scheduler mode + env knobs. Mode is
    deliberately NOT part of the checkpoint fingerprint: concurrent and
    sequential sweeps are bit-identical, so either may resume the
    other's journal."""
    mode = scheduler
    if mode is None:
        mode = (
            "sequential"
            if os.environ.get("ATE_TPU_SWEEP_SEQUENTIAL", "").strip().lower()
            in ("1", "true", "yes", "on")
            else "concurrent"
        )
    if mode not in ("sequential", "concurrent"):
        raise ValueError(
            f"scheduler must be 'sequential' or 'concurrent', got {mode!r}"
        )
    if mode == "concurrent" and (
        os.environ.get("ATE_TPU_TRACE_DIR") or os.environ.get("ATE_TPU_XPROF")
    ):
        # DEVICE capture only (ISSUE 5 satellite): jax.profiler state is
        # process-global — per-stage trace sessions (ATE_TPU_TRACE_DIR)
        # collide outright, and a whole-run capture (ATE_TPU_XPROF)
        # would interleave concurrent stages' device programs into an
        # unreadable timeline. Host-span tracing (trace.json via
        # ATE_TPU_TRACE) needs no profiler and stays concurrent.
        log("device profiling armed (ATE_TPU_TRACE_DIR/ATE_TPU_XPROF) — "
            "forcing sequential sweep; host-span tracing alone does not "
            "require this")
        mode = "sequential"
    if mode == "sequential":
        return 1
    # Clamp like default_workers clamps the env var: --workers 0/-1 must
    # not reach the engine as a zero-thread pool.
    return default_workers() if workers is None else max(1, workers)


def run_sweep(
    config: SweepConfig = SweepConfig(),
    csv_path: str | None = None,
    outdir: str | None = None,
    plots: bool = True,
    log: Callable[[str], None] = print,
    scheduler: str | None = None,
    workers: int | None = None,
    prefetch: bool | None = None,
) -> SweepReport:
    """The full notebook run, checkpointed and timed.

    Telemetry (observability/): the whole run is a ``run_sweep`` span;
    every estimator stage is a child span whose status records whether
    it COMPUTED or RESUMED from the checkpoint — the distinction the
    round-3 stale-resume incident had to be reconstructed from prints.
    With an ``outdir``, ``metrics.json`` + ``events.jsonl`` + a
    Prometheus textfile land next to ``report.json`` (all written
    atomically). ``ATE_TPU_TELEMETRY=0`` disables all of it; estimator
    outputs are bit-identical either way.

    Tracing (ISSUE 5): with an ``outdir`` the run additionally exports
    ``trace.json`` (Chrome/Perfetto catapult timeline — worker, lane,
    prefetch and committer tracks, artifact→stage flow arrows, counter
    tracks) and ``overlap_report.json`` (critical path, per-lane
    busy/wait, overlap efficiency, serialization blame) — disable with
    ``ATE_TPU_TRACE=0``. ``ATE_TPU_XPROF=<dir>`` adds one whole-run
    device capture with per-stage ``TraceAnnotation`` names matching
    the host spans (device capture forces the sequential scheduler;
    host tracing does not).

    Scheduling (ISSUE 4): ``scheduler`` is ``"concurrent"`` (default;
    DAG worker pool over the shared nuisance cache) or ``"sequential"``
    (single-threaded escape hatch — same numbers, same journal).
    ``workers`` bounds the pool (default ``ATE_TPU_SWEEP_WORKERS`` or
    ``min(4, cpus)``); ``prefetch`` overrides the compile-prefetch
    lane's default (on iff the persistent compile cache is enabled).
    """
    # Cache counters must exist in metrics.json even when the embedding
    # process never enabled the persistent cache (idempotent).
    obs.install_jax_monitoring()
    n_workers = _resolve_scheduler(scheduler, workers, log)
    # Everything this run logs starts after t_start — the trace export
    # below filters the (process-global, ring-buffered) event log down
    # to THIS run's records by that boundary.
    t_start = time.monotonic()
    sampler = None
    if outdir and n_workers > 1 and obs.trace_enabled():
        # Counter tracks for the trace (nuisance-cache traffic, backoff,
        # device memory). Multi-worker runs only: the --sequential
        # escape hatch promises a zero-thread process, so sequential
        # runs take a single inline sample at export time instead.
        sampler = obs.MetricSampler()
        sampler.start()
    try:
        with obs.span("run_sweep", out=outdir or "",
                      csv=csv_path or "synthetic") as root_sp:
            report = _run_sweep_impl(
                config, csv_path, outdir, plots, log,
                n_workers=n_workers,
                prefetch=prefetch,
                # Stage spans are opened on worker threads, where the
                # run_sweep span is not on the thread-local stack —
                # parentage rides explicitly.
                root_span_id=getattr(root_sp, "span_id", None),
            )
        return report
    finally:
        # Export in a finally: a failing run is exactly the run whose
        # telemetry (retry events, partial stage counters) matters
        # most. Device-memory gauges first (TPU reports them; CPU has
        # none), then the exporter trio — metrics.json / events.jsonl /
        # metrics.prom — plus trace.json / overlap_report.json (ISSUE
        # 5), beside report.json, after the root span has closed so the
        # event log contains the complete run. Each step is guarded
        # separately: in particular the sampler MUST stop even when an
        # earlier export step raises — a leaked daemon sampler would
        # keep feeding metric_sample events into the process-global
        # ring and double-rate the next run's counter tracks.
        if outdir:
            try:
                obs.record_device_memory(context="run_sweep")
            except Exception as e:  # noqa: BLE001 — observer must not
                # replace the run's real exception with a probe error.
                log(f"telemetry export failed: {e!r}")
        if sampler is not None:
            sampler.stop()
        elif outdir and obs.trace_enabled():
            obs.MetricSampler().sample_once()
        if outdir:
            try:
                written = obs.write_run_artifacts(outdir)
                written += _write_trace_artifacts(
                    outdir, t_start, n_workers, csv_path
                )
                if written:
                    log(f"telemetry: {', '.join(written)}")
            except Exception as e:  # noqa: BLE001 — observer must not
                # replace the run's real exception (full disk, outdir
                # deleted mid-run) with an export error.
                log(f"telemetry export failed: {e!r}")


def _write_trace_artifacts(
    outdir: str, t_start: float, workers: int, csv_path: str | None
) -> list[str]:
    """trace.json (catapult/Perfetto) + overlap_report.json beside
    metrics.json — the ISSUE 5 pair. The event log is process-global
    and ring-buffered, so records are filtered to this run's monotonic
    window first; the run's wall seconds and worker count ride the
    trace header for the analyzer's denominator. No-op (no husk files)
    when tracing is off (``ATE_TPU_TRACE=0`` or telemetry disabled)."""
    from ate_replication_causalml_tpu.observability import trace as _trace

    if not _trace.trace_enabled():
        return []
    records = [
        r for r in obs.EVENTS.records()
        if r.get("start_mono_s", 0.0) >= t_start - 1e-6
    ]
    run_rec = next((r for r in records if r["name"] == "run_sweep"), None)
    tr = _trace.build_trace(records, meta=_trace.run_meta(
        workers=workers,
        wall_s=run_rec["dur_s"] if run_rec else None,
        out=outdir, csv=csv_path or "synthetic",
        # A nonzero ring-eviction count warns the analyzer that the
        # window may be missing its earliest records.
        events_dropped=obs.EVENTS.dropped,
    ))
    return _trace.write_trace_artifacts(outdir, tr)


@dataclasses.dataclass
class _StageOutcome:
    """What a stage body hands the ordered committer: the result row
    plus everything the commit needs to journal/log it in declared
    order (ISSUE 4 — side effects are the committer's job; bodies may
    finish in any order)."""

    kind: str                   # "resumed" | "computed" | "failed"
    res: EstimatorResult
    record: dict | None = None  # checkpoint row (computed/failed)
    extras: dict = dataclasses.field(default_factory=dict)
    seconds: float = 0.0
    retry_why: str = ""         # non-resumable cached row's reason
    error: str = ""
    attempts: int = 0


def _run_sweep_impl(
    config: SweepConfig,
    csv_path: str | None,
    outdir: str | None,
    plots: bool,
    log: Callable[[str], None],
    n_workers: int = 1,
    prefetch: bool | None = None,
    root_span_id: str | None = None,
) -> SweepReport:
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    # Arm chaos NOW, with fresh per-run fault budgets: a malformed
    # ATE_TPU_CHAOS spec must fail the run at config time
    # (ChaosSpecError), not surface as thirteen degraded stages — and a
    # second sweep in the same process must get full budgets, not the
    # remnants the previous run left.
    chaos.reset()
    chaos.active()
    # Resume is only valid for the same config + data source + device
    # topology (mesh and single-device runs are statistically equivalent
    # but not bit-identical) + framework version: estimator code changes
    # between versions silently resurface stale rows otherwise (observed
    # in round 3 — a QP-solver upgrade resumed the pre-upgrade numbers).
    mesh_devices = jax.device_count() if config.use_mesh else 1
    fingerprint = (
        f"{config!r}|csv={csv_path or 'synthetic'}|devices={mesh_devices}"
        f"|version={__version__}"
    )
    ckpt = _Checkpoint(
        os.path.join(outdir, "results.jsonl") if outdir else None,
        fingerprint, log=log,
    )

    df, df_mod, n_dropped = build_frames(config, csv_path)
    log(f"prepared df n={df.n}, dropped {n_dropped} -> df_mod n={df_mod.n} "
        f"(reference on real data: 41,062 dropped, BASELINE.md)")

    timer = StageTimer()
    report = SweepReport(
        oracle=None, results=ResultTable(), n_dropped=n_dropped, n_biased=df_mod.n,
        timings_s=timer.seconds,
    )
    # Deterministic per-stage keys (stable across resume: skipping a
    # completed stage must not shift the keys of later stages).
    import zlib

    root_key = jax.random.key(config.seed)

    def key_for(name: str) -> jax.Array:
        return jax.random.fold_in(root_key, zlib.crc32(name.encode()))

    # Parallel-axis composition (SURVEY.md §2.4): on a multi-device
    # mesh, forests shard trees over TREE_AXIS and every cv.glmnet
    # shards folds over FOLD_AXIS. Resume note: mesh vs single-device
    # runs produce statistically equivalent but not bit-identical
    # numbers, so the device count is part of the config fingerprint
    # (see above).
    tree_mesh = None
    fold_axis = None
    fold_ctx = None
    if mesh_devices > 1:
        from ate_replication_causalml_tpu.parallel.mesh import (
            FOLD_AXIS,
            TREE_AXIS,
            make_mesh,
            use_mesh,
        )

        tree_mesh = make_mesh((TREE_AXIS,))
        fold_axis = FOLD_AXIS
        fold_mesh = make_mesh((FOLD_AXIS,))
        fold_ctx = lambda: use_mesh(fold_mesh)
        log(f"mesh: {jax.device_count()} devices — tree + fold axes active")

    def with_folds(fn):
        """Run ``fn`` under the fold-axis mesh when one is active."""
        if fold_ctx is None:
            return fn()
        with fold_ctx():
            return fn()

    stage_c = obs.counter(
        "sweep_stage_total", "sweep stages by resume-vs-computed status"
    )

    # Chaos stage faults are PLANNED, in declared order, before any
    # worker starts (chaos.plan_stage_faults): the `times` budget is
    # order-sensitive, and worker completion order must never decide
    # which stages it selects. Bodies read the plan; the injection
    # event/counter fires at raise time (chaos.record_stage_fault), so
    # an aborted sweep never reports a fault on a stage that was
    # skipped.
    fault_plan: set[str] = set()

    def _make_stage(
        method: str,
        fn: Callable[[object], object],
        needs: tuple[str, ...] = (),
        warm: Callable[[], object] | None = None,
        exclusive: str | None = None,
    ) -> tuple[StageSpec, bool]:
        """One estimator as a scheduler stage, under the config's
        isolation policy. ``fn(cache)`` returns an EstimatorResult, or
        (EstimatorResult, extras-dict) — extras ride the checkpoint
        record (read back via ``ckpt.get``). The stage span's status
        records whether the row was computed, resumed from the
        checkpoint, or failed-and-degraded.

        The resume decision is made HERE, at build time (it is a pure
        function of the loaded checkpoint): a resumed stage declares no
        artifact needs, so a fully checkpointed rerun schedules no
        nuisance fits at all — the old lazy ``_p_log`` guarantee, now by
        construction. Returns (spec, resumed).

        Degradation (``fail_policy="degrade"``): an exception (or a
        non-finite ATE — the finite-value guard) becomes a
        ``status="failed"`` row carrying the error, attempt count and
        seconds, in both the checkpoint and the report; the sweep
        continues. Resume retries failed rows — and rows a hand edit or
        format drift made unresumable (``_row_resumable``) — instead of
        crashing on them. ``KeyboardInterrupt``/``SystemExit`` always
        propagate: an operator's ^C is not an estimator failure."""
        cached = ckpt.get(method)
        resumable, why = _row_resumable(cached) if cached is not None else (False, "")
        if cached is not None and resumable:
            def run_resumed(cache, method=method, cached=cached):
                with obs.span("sweep_stage", parent_id=root_span_id,
                              method=method) as sp:
                    sp.set_status("resumed")
                    nanf = lambda v: float("nan") if v is None else v
                    res = EstimatorResult(
                        method=cached["method"], ate=cached["ate"],
                        lower_ci=nanf(cached["lower_ci"]),
                        upper_ci=nanf(cached["upper_ci"]),
                        se=nanf(cached["se"]),
                    )
                    return _StageOutcome(
                        "resumed", res, seconds=cached.get("seconds", 0.0)
                    )

            return StageSpec(method, run_resumed, needs=()), True

        retry_why = why if cached is not None else ""

        def run(cache, method=method, fn=fn, cached=cached,
                retry_why=retry_why):
            with obs.span("sweep_stage", parent_id=root_span_id,
                          method=method) as sp:
                if retry_why:
                    obs.emit("checkpoint_row_rejected", status="retrying",
                             method=method, reason=retry_why)
                sp.set_status("computed")
                # The prior attempt count rides the same hand-editable
                # row _row_resumable guards, so tolerate garbage too.
                prior = cached.get("attempts") if cached else 0
                attempts = (
                    int(prior) + 1
                    if isinstance(prior, (int, float))
                    and not isinstance(prior, bool)
                    else 1
                )
                try:
                    # xla_trace/xprof_annotation sanitize the label
                    # themselves (method names carry spaces/parens/dots
                    # — ``Causal Forest(GRF)``); the annotation name
                    # matches the host span so the XLA timeline lines
                    # up with the host tracks (ISSUE 5c).
                    with timer.stage(method), xla_trace(method), \
                            xprof_annotation(method):
                        if method in fault_plan:
                            inj_now = chaos.active()
                            if inj_now is not None:
                                inj_now.record_stage_fault(method)
                            raise ChaosStageFault(
                                f"chaos: injected stage fault on {method!r}"
                            )
                        out = fn(cache)
                    res, extras = out if isinstance(out, tuple) else (out, {})
                    if not math.isfinite(res.ate):
                        raise NonFiniteResult(
                            f"estimator returned ATE {res.ate!r} from finite "
                            f"inputs — refusing to record a garbage row"
                        )
                except (KeyboardInterrupt, SystemExit, ChaosSpecError):
                    # ^C is not an estimator failure, and a malformed
                    # chaos spec (env edited mid-run) is an operator
                    # error — both must abort, never degrade.
                    raise
                except Exception as e:
                    if config.fail_policy != "degrade":
                        raise
                    dt = timer.seconds.get(method, 0.0)
                    err = f"{type(e).__name__}: {e}"
                    sp.set_status("failed")
                    sp.set_attr("error", err)
                    obs.emit("sweep_stage_failed", status="error",
                             method=method, error=err, attempts=attempts)
                    nan = float("nan")
                    res = EstimatorResult(method=method, ate=nan,
                                          lower_ci=nan, upper_ci=nan,
                                          se=nan, status="failed")
                    return _StageOutcome(
                        "failed", res,
                        record=dict(res.to_dict(), error=err,
                                    attempts=attempts, seconds=round(dt, 3)),
                        seconds=dt, retry_why=retry_why, error=err,
                        attempts=attempts,
                    )
                dt = timer.seconds[method]
                sp.set_attr("seconds", round(dt, 3))
                return _StageOutcome(
                    "computed", res,
                    record=dict(res.to_dict(), seconds=round(dt, 3),
                                attempts=attempts, **extras),
                    extras=extras, seconds=dt, retry_why=retry_why,
                    attempts=attempts,
                )

        return StageSpec(method, run, needs=needs, warm=warm,
                         exclusive=exclusive), False

    def commit(spec: StageSpec, outcome: _StageOutcome) -> None:
        """Declared-order side effects: journal append, report/timer
        bookkeeping, log lines. The engine runs commits strictly in
        stage order, single-flight — so results.jsonl keeps the same
        notebook ordering a sequential sweep writes, whatever order the
        bodies finished in."""
        method = spec.name
        res = outcome.res
        if outcome.retry_why:
            log(f"  [retry] {method}: checkpoint row not resumable "
                f"({outcome.retry_why}); recomputing")
        if outcome.kind == "resumed":
            stage_c.inc(1, method=method, status="resumed")
            timer.seconds[method] = outcome.seconds
            log(f"  [resume] {method}: ate={res.ate:.4f}")
            return
        if outcome.kind == "failed":
            stage_c.inc(1, method=method, status="failed")
            report.failures[method] = {
                "error": outcome.error, "attempts": outcome.attempts,
                "seconds": round(outcome.seconds, 3),
            }
            ckpt.put(outcome.record)
            log(f"  [FAILED] {method}: {outcome.error} (attempt "
                f"{outcome.attempts}, {outcome.seconds:.1f}s) — degrading, "
                f"sweep continues")
            return
        stage_c.inc(1, method=method, status="computed")
        ckpt.put(outcome.record)
        if "incorrect_ate" in outcome.extras:
            log(f"  Incorrect ATE: {outcome.extras['incorrect_ate']:.3f} "
                f"(SE: {outcome.extras['incorrect_se']:.3f})"
                f"  [deliberate negative example, Rmd:262]")
        log(f"  {method}: ate={res.ate:.4f} ci=[{res.lower_ci:.4f},"
            f"{res.upper_ci:.4f}] ({outcome.seconds:.1f}s)")

    # ── Nuisance artifacts (ISSUE 4): fit-once, keyed by the run
    # fingerprint plus the config knobs each fit reads. Every fit uses
    # the same fold-in key / same jitted function the sequential stages
    # used, so sharing is bit-identical by construction. ──────────────
    from ate_replication_causalml_tpu.estimators.aipw import (
        _outcome_model_mu,
        outcome_model_mu,
    )
    from ate_replication_causalml_tpu.estimators.ipw import (
        _psols_core,
        _psw_core,
    )
    from ate_replication_causalml_tpu.ops.lasso import default_foldid

    _sds = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
    x_s, w_s, y_s = _sds(df_mod.x), _sds(df_mod.w), _sds(df_mod.y)

    # Multi-device collective programs (fold/tree shard_map) must keep a
    # single global launch order — two collective launches racing from
    # different host threads interleave per-device executions and
    # deadlock the rendezvous. Nodes in the "mesh" lane serialize among
    # themselves; everything else overlaps freely. Single-device runs
    # have no collectives and no lane.
    mesh_lane = "mesh" if mesh_devices > 1 else None

    # Device-resident artifact plane (ISSUE 8): mesh-lane artifacts
    # declare a sharding instead of the old materialized() host bounce
    # (np.asarray → jnp.asarray, host bandwidth paid twice per
    # handoff). The cache commits the declared layout inside the lane,
    # blocked until drained (same release discipline), stores the
    # device-resident form, and hands unlaned consumers ONE metered
    # host gather (parallel/shardio.py); a laned consumer declaring
    # consumes_sharding="device" would take the handoff with zero host
    # bytes. Row-sharded over the data axis when the row count divides
    # the mesh, replicated otherwise (this jax rejects uneven shards).
    artifact_sharding = None
    if mesh_devices > 1:
        from ate_replication_causalml_tpu.parallel.mesh import (
            DATA_AXIS,
            make_mesh as _make_mesh,
        )
        from ate_replication_causalml_tpu.parallel.shardio import row_sharding

        artifact_sharding = row_sharding(
            _make_mesh((DATA_AXIS,)), df_mod.n
        )

    artifacts = [
        # In-sample logistic propensity (Rmd:164-168) — consumed by both
        # propensity stages AND the DR-GLM stage (the same GLM fit).
        ArtifactSpec(
            "p_logistic",
            fit=lambda c: logistic_propensity(df_mod.x, df_mod.w),
            key=(fingerprint,),
            warm=lambda: logistic_propensity.lower(x_s, w_s).compile(),
        ),
        # The AIPW outcome-model (mu0, mu1) both doubly-robust stages
        # share (ate_functions.R:156-166 — one fit, two consumers).
        ArtifactSpec(
            "outcome_mu",
            fit=lambda c: outcome_model_mu(df_mod),
            key=(fingerprint,),
            warm=lambda: _outcome_model_mu.lower(x_s, w_s, y_s).compile(),
        ),
        # CV fold masks: the exact assignment cv_glmnet derives from
        # each stage's fold-in key (ops.lasso.default_foldid is
        # jit-invariant, asserted in tests/test_lasso.py).
        ArtifactSpec(
            "folds:ps_lasso",
            fit=lambda c: default_foldid(key_for("ps_lasso"), df_mod.n),
            key=(fingerprint, "ps_lasso"),
        ),
        ArtifactSpec(
            "folds:seq_lasso",
            fit=lambda c: default_foldid(key_for("seq_lasso"), df_mod.n),
            key=(fingerprint, "seq_lasso"),
        ),
        ArtifactSpec(
            "folds:usual_lasso",
            fit=lambda c: default_foldid(key_for("usual_lasso"), df_mod.n),
            key=(fingerprint, "usual_lasso"),
        ),
        # LASSO-logit propensity path at lambda.1se (ate_functions.R:
        # 133-146) — consumes its fold masks, feeds the IPW stage.
        ArtifactSpec(
            "lasso_ps",
            fit=lambda c: with_folds(lambda: prop_score_lasso(
                df_mod, foldid=c.get("folds:ps_lasso"),
                fold_axis=fold_axis)),
            needs=("folds:ps_lasso",),
            key=(fingerprint,),
            exclusive=mesh_lane,
            sharding=artifact_sharding,
        ),
        # RF OOB vote-fraction propensity (ate_functions.R:169-174).
        ArtifactSpec(
            "rf_oob_propensity",
            fit=lambda c: rf_oob_propensity(
                df_mod, key=key_for("dr_rf_prop"), n_trees=config.dr_trees,
                depth=config.forest_depth, mesh=tree_mesh),
            key=(fingerprint, config.dr_trees, config.forest_depth),
            exclusive=mesh_lane,
            sharding=artifact_sharding,
        ),
    ]

    # ── The sweep, in notebook order (Rmd:128-272). The declaration
    # list IS the commit/journal/report order, whatever the worker pool
    # does. ───────────────────────────────────────────────────────────
    def cf_fn(cache):
        cf = causal_forest_report(
            df_mod, key=key_for("causal_forest"), n_trees=config.cf_trees,
            nuisance_trees=config.cf_nuisance_trees, mesh=tree_mesh)
        return cf.result, {"incorrect_ate": cf.incorrect_ate,
                           "incorrect_se": cf.incorrect_se}

    stage_decls: list[tuple] = [
        ("oracle", lambda c: naive_ate(df, method="oracle"), (), None, None),
        ("naive", lambda c: naive_ate(df_mod), (), None, None),
        ("Direct Method", lambda c: ate_condmean_ols(df_mod), (), None, None),
        ("Propensity_Weighting",
         lambda c: prop_score_weight(df_mod, c.get("p_logistic")),
         ("p_logistic",),
         lambda: _psw_core.lower(
             x_s, w_s, y_s,
             jax.ShapeDtypeStruct((df_mod.n,), df_mod.x.dtype)).compile(),
         None),
        ("Propensity_Regression",
         lambda c: prop_score_ols(df_mod, c.get("p_logistic")),
         ("p_logistic",),
         lambda: _psols_core.lower(
             w_s, y_s,
             jax.ShapeDtypeStruct((df_mod.n,), df_mod.w.dtype)).compile(),
         None),
        ("Propensity_Weighting_LASSOPS",
         lambda c: prop_score_weight(
             df_mod, c.get("lasso_ps"),
             method="Propensity_Weighting_LASSOPS"),
         ("lasso_ps",), None, None),
        ("Single-equation LASSO",
         lambda c: with_folds(lambda: ate_condmean_lasso(
             df_mod, foldid=c.get("folds:seq_lasso"),
             fold_axis=fold_axis)),
         ("folds:seq_lasso",), None, mesh_lane),
        ("Usual LASSO",
         lambda c: with_folds(lambda: ate_lasso(
             df_mod, foldid=c.get("folds:usual_lasso"),
             fold_axis=fold_axis)),
         ("folds:usual_lasso",), None, mesh_lane),
        ("Doubly Robust with Random Forest PS",
         lambda c: doubly_robust(
             df_mod, lambda f: c.get("rf_oob_propensity"),
             key=key_for("dr_rf"), mu=c.get("outcome_mu")),
         ("rf_oob_propensity", "outcome_mu"), None, None),
        ("Doubly Robust with logistic regression PS",
         lambda c: doubly_robust_glm(
             df_mod, key=key_for("dr_glm"), p=c.get("p_logistic"),
             mu=c.get("outcome_mu")),
         ("p_logistic", "outcome_mu"), None, None),
        ("Belloni et.al",
         lambda c: with_folds(lambda: belloni(
             df_mod, key=key_for("belloni"), fold_axis=fold_axis)),
         (), None, mesh_lane),
        ("Double Machine Learning",
         lambda c: double_ml(df_mod, n_trees=config.dml_trees,
                             depth=config.forest_depth, key=key_for("dml"),
                             mesh=tree_mesh),
         (), None, mesh_lane),
        ("residual_balancing",
         lambda c: residual_balance_ate(df_mod, key=key_for("balance"),
                                        max_iters=config.balance_iters),
         (), None, None),
        # Causal forest: the result row plus the notebook's 'incorrect'
        # demo (Rmd:258-262). The demo values ride the checkpoint
        # record as stage extras.
        ("Causal Forest(GRF)", cf_fn, (), None, mesh_lane),
    ]

    stages: list[StageSpec] = []
    to_compute: list[str] = []
    for method, fn, needs, warm, lane in stage_decls:
        spec, resumed = _make_stage(method, fn, needs=needs, warm=warm,
                                    exclusive=lane)
        stages.append(spec)
        if not resumed:
            to_compute.append(method)

    inj = chaos.active()
    if inj is not None:
        # Resumed stages never reached the injector sequentially either
        # (they return before the chaos point) — plan over the rest.
        fault_plan.update(inj.plan_stage_faults(to_compute))

    engine = SweepEngine(
        artifacts, stages, commit=commit, workers=n_workers,
        prefetch=prefetch, span_parent=root_span_id,
    )
    if n_workers > 1:
        log(f"scheduler: concurrent sweep, {n_workers} workers"
            + (", compile prefetch on" if engine.prefetch else ""))
    # One whole-run device capture under $ATE_TPU_XPROF (no-op without
    # it); stage bodies carry matching TraceAnnotations.
    with xprof_run("run_sweep"):
        outcomes = engine.run()

    report.oracle = outcomes["oracle"].res
    for m in SWEEP_METHODS:
        report.results.append(outcomes[m].res)
    cf_rec = ckpt.get("Causal Forest(GRF)") or {}
    report.incorrect_cf_ate = cf_rec.get("incorrect_ate")
    report.incorrect_cf_se = cf_rec.get("incorrect_se")

    # Producer-side manifest check: the stage literals above ARE the
    # sweep; this catches a stage added/reordered without updating
    # SWEEP_METHODS at the definition site, in every test path (review
    # r5: the tuple is otherwise a parallel transcription).
    assert [r.method for r in report.results] == list(SWEEP_METHODS), (
        [r.method for r in report.results]
    )

    if outdir:
        # Atomic (tmp + os.replace): a kill mid-write must not leave a
        # truncated report.json next to a valid results.jsonl.
        obs.atomic_write_json(
            os.path.join(outdir, "report.json"),
            _jsonsafe({
                "oracle": report.oracle.to_dict(),
                "results": [r.to_dict() for r in report.results],
                "n_dropped": report.n_dropped,
                "n_biased": report.n_biased,
                "incorrect_cf": [report.incorrect_cf_ate, report.incorrect_cf_se],
                "timings_s": {k: round(v, 3) for k, v in report.timings_s.items()},
                "failures": report.failures,
            }),
        )
    if plots and outdir:
        from ate_replication_causalml_tpu.viz import notebook_figures

        # A degraded oracle cannot anchor the reference band; the
        # figures render the partial sweep with failures annotated.
        oracle_fig = (
            report.oracle
            if report.oracle is not None and math.isfinite(report.oracle.ate)
            else None
        )
        report.figure_paths = notebook_figures(
            report.results, oracle_fig, outdir)
        log(f"figures: {report.figure_paths}")
    if outdir:
        log(f"report: {write_report_md(report, outdir, csv_path=csv_path)}")
    return report


def write_report_md(report: SweepReport, outdir: str,
                    csv_path: str | None = None) -> str:
    """Render the notebook-equivalent replication document
    (``results/REPORT.md``), mirroring ``ate_replication.md`` section by
    section — data prep counts, RCT oracle vs naive, the estimator
    comparison, the deliberate 'Incorrect ATE' demo line
    (``ate_replication.md:294``), and the three figures inline — so a
    reader can diff the two documents."""
    fmt = lambda v: "—" if v is None or (isinstance(v, float) and not np.isfinite(v)) else f"{v:.4f}"
    o = report.oracle
    lines = [
        "# ATE replication — TPU-native run",
        "",
        "Rendered by `ate_replication_causalml_tpu.pipeline` (the "
        "`ate_replication.md` equivalent; reference sections cited inline).",
        "",
        "## Data",
        "",
        f"* Source: `{csv_path}`" if csv_path else
        "* Source: synthetic GGL-like generator (real CSV unavailable — "
        "see RESULTS.md 'Real-dataset attempt'; fetch via "
        "`scripts/fetch_ggl.sh`)",
        f"* Rows after prep (sampled, scaled, na.omit): "
        f"{report.n_dropped + report.n_biased}",
        "* Bias injection (`ate_replication.Rmd:97-123`) dropped:",
        "",
        "```",
        f"## [1] {report.n_dropped}",
        "```",
        "",
        f"  (reference on the real data: `## [1] 41062`, "
        f"`ate_replication.md:118`)",
        f"* Biased sample `df_mod`: {report.n_biased} rows",
        "",
        "## RCT oracle vs naive on the biased sample",
        "",
        "| Method | ATE | 95% CI |",
        "|---|---|---|",
        f"| RCT (oracle) | {fmt(o.ate)} | [{fmt(o.lower_ci)}, {fmt(o.upper_ci)}] |",
    ]
    naive = next((r for r in report.results if r.method == "naive"), None)
    if naive is not None:
        lines.append(
            f"| naive (biased) | {fmt(naive.ate)} | "
            f"[{fmt(naive.lower_ci)}, {fmt(naive.upper_ci)}] |")
    lines += [
        "",
        "The naive estimate on the biased sample is far from the RCT "
        "answer — the injected selection bias every estimator below "
        "must remove (`ate_replication.md:157`).",
        "",
    ]
    figs = [os.path.basename(p) for p in report.figure_paths]
    if len(figs) >= 1:
        lines += [f"![oracle vs naive]({figs[0]})", ""]
    lines += [
        "## Estimator comparison (notebook order, `Rmd:128-272`)",
        "",
        "| Method | ATE | 95% CI | seconds |",
        "|---|---|---|---|",
    ]
    for r in report.results:
        if getattr(r, "status", "ok") != "ok":
            lines.append(f"| {r.method} | ✗ failed | — | — |")
            continue
        secs = report.timings_s.get(r.method)
        lines.append(
            f"| {r.method} | {fmt(r.ate)} | [{fmt(r.lower_ci)}, "
            f"{fmt(r.upper_ci)}] | {secs:.1f} |" if secs is not None else
            f"| {r.method} | {fmt(r.ate)} | [{fmt(r.lower_ci)}, "
            f"{fmt(r.upper_ci)}] | — |")
    if report.failures:
        lines += [
            "",
            "### Degraded stages",
            "",
            "The sweep's isolation policy recorded these estimators as "
            "failed and carried on (partial coverage, not an aborted "
            "run); re-running with the same output directory retries "
            "exactly these rows:",
            "",
            "| Method | error | attempts |",
            "|---|---|---|",
        ]
        # Raw exception text can carry '|' (shape errors) or backticks —
        # escape so one bad message cannot corrupt the table markup.
        esc = lambda s: str(s).replace("|", "\\|").replace("`", "'")
        for m, f in report.failures.items():
            lines.append(f"| {m} | `{esc(f.get('error', '?'))}` | {f.get('attempts', '?')} |")
    if len(figs) >= 2:
        lines += ["", f"![regression methods]({figs[1]})"]
    lines += [
        "",
        "## Causal forest: the deliberate negative example",
        "",
        "The mean of CATE predictions with SE = sqrt(mean per-point "
        "variance) is the WRONG way to aggregate "
        "(`ate_replication.Rmd:258-262`; printed as "
        "`Incorrect ATE: 0.083 (SE: 0.198)` on the real data, "
        "`ate_replication.md:294`):",
        "",
        "```",
    ]
    if report.incorrect_cf_ate is not None:
        lines.append(
            f"## Incorrect ATE: {report.incorrect_cf_ate:.3f} "
            f"(SE: {report.incorrect_cf_se:.3f})")
    lines += [
        "```",
        "",
        "The correct doubly-robust aggregation "
        "(`grf::estimate_average_effect` equivalent) is the "
        "`Causal Forest(GRF)` row above.",
        "",
    ]
    if len(figs) >= 3:
        lines += [f"![causal ML methods]({figs[2]})", ""]
    path = os.path.join(outdir, "REPORT.md")
    obs.atomic_write_text(path, "\n".join(lines))
    return path


def main(argv: Iterable[str] | None = None) -> SweepReport:
    import argparse

    from ate_replication_causalml_tpu.utils.compile_cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="results", help="output directory")
    ap.add_argument("--csv", default=None,
                    help="path to socialpresswgeooneperhh_NEIGH.csv (else synthetic)")
    ap.add_argument("--quick", action="store_true", help="small smoke-run sizes")
    ap.add_argument("--no-plots", action="store_true")
    ap.add_argument("--sequential", action="store_true",
                    help="single-threaded sweep (debugging escape hatch; "
                         "bit-identical to the concurrent default)")
    ap.add_argument("--workers", type=int, default=None,
                    help="worker-pool width for the concurrent sweep "
                         "(default: ATE_TPU_SWEEP_WORKERS or min(4, cpus))")
    args = ap.parse_args(argv if argv is None else list(argv))

    config = SweepConfig()
    if args.quick:
        config = config.quick()
    report = run_sweep(config, csv_path=args.csv, outdir=args.out,
                       plots=not args.no_plots,
                       scheduler="sequential" if args.sequential else None,
                       workers=args.workers)
    print(repr(report.results))
    return report


if __name__ == "__main__":
    main()

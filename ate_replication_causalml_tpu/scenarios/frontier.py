"""Adversarial failure-frontier search over the DGP knob space
(ISSUE 19, tentpole part b).

The scenario matrix answers "how do these estimators do on THESE
designs"; the frontier asks the adversarial question — WHERE does each
estimator's coverage collapse, and what is the minimal knob vector that
breaks it. The search is a seeded refinement loop grounded in what the
literature proves breaks things: the ``overlap × confounding`` corner
(η → 0 under strong γ — the overlap-violation regime residual balancing
arXiv:1604.07125 targets) and the ``dimension × sparsity`` edge (dense
coefficients violating the approximate-sparsity premise of
post-double-selection, arXiv:1201.0224).

Mechanics, all riding the ISSUE 19 streaming plane:

* **probes are streaming blocks** — every (estimator, knob-vector)
  probe dispatches width-W blocks through the column's fused
  :func:`~.aggregate.aggregate_executable` and merges
  :class:`~.aggregate.AggState` host-side: O(1) bytes per block, one
  executable per probed column, millions of probe cells affordable.
* **MC-SE-aware acquisition** — a probe starts at ``n_reps``
  replicates; when the coverage deficit ``nominal − coverage`` exceeds
  ``refine_z`` binomial MC standard errors the probe EXTENDS to
  ``refine_reps`` (same blocks plus new ones — the extend-reps resume
  contract), so replicate budget concentrates where coverage is
  collapsing. The final verdict is ``failing`` iff the deficit exceeds
  ``fail_z`` MC-SEs at the final replicate count: a pure function of
  the root seed.
* **ddmin shrinking** — every failing knob vector is delta-debugged
  (:func:`~..resilience.campaign.ddmin`, the chaos campaign's
  minimizer over a different atom vocabulary) down to a 1-minimal set
  of knob DELTAS from the baseline design that still fails, then
  confirmed with one fresh probe and recorded with a one-line repro.
  The γ/η interaction makes this genuinely informative:
  ``e(x) = η + (1−2η)σ(γx₁)`` degenerates to e ≡ ½ when EITHER γ=0 or
  η=½, so neither knob alone can reproduce an overlap failure — the
  minimal vector is the pair.
* **resumable like everything else** — probe blocks journal to
  ``frontier.jsonl`` through the pipeline ``_Checkpoint`` (fingerprint
  header, torn-line tolerance, ``.stale`` set-aside), keyed by
  (estimator, knob vector, rep range); a SIGKILL mid-search resumes
  block-exact. The committed **FAILURE_ATLAS.json** goes through the
  atomic-export helpers with sorted keys and carries NO wall-clock —
  same root seed ⇒ byte-identical atlas, resumed or straight through.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import os
from typing import Callable

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.scenarios.aggregate import (
    AggState,
    N_STATS,
    aggregate_executable,
)
from ate_replication_causalml_tpu.scenarios.batched import (
    SCENARIO_ESTIMATORS,
    batch_mask,
    pad_ids,
)
from ate_replication_causalml_tpu.scenarios.dgp import DGPSpec

#: bump when the probe-record layout, the acquisition rule or the atlas
#: schema change — old frontier journals must not resume new searches.
FRONTIER_SCHEMA_TAG = "scenarios-frontier-v1"

#: DGPSpec fields a frontier axis may vary, with the caster that keeps
#: journal/atlas values and DGPSpec construction in exact agreement.
KNOB_FIELDS: dict[str, Callable] = {
    "n": int, "p": int, "sparsity": int,
    "confounding": float, "overlap": float, "tau_scale": float,
}


def _probes_counter():
    return obs.counter(
        "scenario_frontier_probes_total",
        "frontier probe blocks by estimator and computed/resumed status",
    )


@dataclasses.dataclass(frozen=True)
class FrontierAxis:
    """One named failure surface: a small set of DGP knobs and the grid
    values each takes (declared order IS probe/atlas order). The grid
    is the cartesian product — corners are where the literature's
    failure modes live, and interior points give the surface its MC
    error-banded shape."""

    name: str
    knobs: tuple[tuple[str, tuple[float, ...]], ...]

    def __post_init__(self) -> None:
        for knob, values in self.knobs:
            if knob not in KNOB_FIELDS:
                raise ValueError(
                    f"axis {self.name!r}: unknown knob {knob!r}; "
                    f"known: {sorted(KNOB_FIELDS)}"
                )
            if not values:
                raise ValueError(
                    f"axis {self.name!r}: knob {knob!r} has no values"
                )

    def points(self) -> list[dict]:
        """Knob vectors in declared cartesian order."""
        names = [k for k, _ in self.knobs]
        grids = [v for _, v in self.knobs]
        return [
            dict(zip(names, combo))
            for combo in itertools.product(*grids)
        ]


@dataclasses.dataclass(frozen=True)
class FrontierSpec:
    """The whole search: baseline design, axes, estimators, replicate
    policy and the acquisition thresholds. ``n_reps`` is the initial
    probe budget; probes whose coverage deficit exceeds ``refine_z``
    MC-SEs extend to ``refine_reps``; ``fail_z`` MC-SEs at the final
    count is the failure verdict."""

    axes: tuple[FrontierAxis, ...]
    estimators: tuple[str, ...]
    baseline: DGPSpec
    n_reps: int = 64
    refine_reps: int = 192
    batch_width: int = 32
    seed: int = 0
    nominal: float = 0.95
    fail_z: float = 4.0
    refine_z: float = 2.0

    def __post_init__(self) -> None:
        for name in self.estimators:
            est = SCENARIO_ESTIMATORS.get(name)
            if est is None:
                raise ValueError(f"unknown scenario estimator {name!r}")
            if not est.vmapped:
                raise ValueError(
                    f"frontier probes stream through the vmapped "
                    f"aggregate executable; {name!r} is not vmappable"
                )
        if self.refine_reps < self.n_reps:
            raise ValueError("refine_reps must be >= n_reps")

    def width(self) -> int:
        """One probe-block width for the WHOLE search (initial and
        refined probes alike): refinement extends a probe by appending
        blocks, and f32 merges are segment-dependent — changing width
        mid-probe would break both block reuse and bit-determinism."""
        return min(self.batch_width, self.n_reps)

    def fingerprint(self) -> str:
        """Journal resume validity. Replicate counts stay OUT (the
        extend-reps contract: raising budgets resumes completed
        blocks); the block width is IN (blocks of different widths can
        never merge bit-exactly)."""
        axes = ";".join(f"{a.name}={a.knobs!r}" for a in self.axes)
        return (
            f"{FRONTIER_SCHEMA_TAG}|base={self.baseline.fields()!r}"
            f"|axes=[{axes}]|est={list(self.estimators)!r}"
            f"|seed={self.seed}|w={self.width()}"
            f"|nominal={self.nominal!r}|fz={self.fail_z!r}"
            f"|rz={self.refine_z!r}"
        )


def knobs_id(knobs: dict) -> str:
    """Canonical order-free identity of a knob vector — the journal /
    probe-cache / repro vocabulary. ``%g`` formatting round-trips every
    grid value exactly (ints stay ints, 0.02 stays 0.02)."""
    return ",".join(f"{k}={knobs[k]:g}" for k in sorted(knobs))


def parse_knobs(text: str) -> dict:
    """Inverse of :func:`knobs_id` (the ``--repro --knobs`` operand)."""
    out: dict = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        if k not in KNOB_FIELDS:
            raise ValueError(f"unknown frontier knob {k!r}")
        out[k] = KNOB_FIELDS[k](float(v))
    return out


def dgp_for(baseline: DGPSpec, knobs: dict) -> DGPSpec:
    """The probed design: baseline with the knob deltas applied. The
    name encodes the deltas — DGP names are the cell-id/executable-key
    namespace, so distinct knob vectors must never collide."""
    deltas = {k: KNOB_FIELDS[k](v) for k, v in knobs.items()}
    name = f"fr({knobs_id(knobs)})" if knobs else f"fr(base:{baseline.name})"
    return dataclasses.replace(baseline, name=name, **deltas)


def probe_row_id(est_name: str, knobs: dict, batch: tuple[int, ...]) -> str:
    return f"probe:{est_name}|{knobs_id(knobs)}|r{batch[0]}-{batch[-1]}"


def _probe_resumable(rec: dict) -> bool:
    if rec.get("schema") != FRONTIER_SCHEMA_TAG:
        return False
    if rec.get("status", "ok") != "ok":
        return False
    stats = rec.get("stats")
    if not isinstance(stats, list) or len(stats) != N_STATS:
        return False
    return all(
        isinstance(v, (int, float)) and math.isfinite(v) for v in stats
    )


class FrontierSearch:
    """The seeded search loop. One instance per run; every probe result
    is cached by ``(estimator, knob vector, reps)``, so the ddmin
    shrinker re-probes each candidate subset at most once and the
    full-vector seed probe is free."""

    def __init__(self, spec: FrontierSpec, ckpt=None,
                 log: Callable[[str], None] = print):
        import jax

        self.spec = spec
        self.ckpt = ckpt
        self.log = log
        self.root_key = jax.random.key(spec.seed)
        self.cache: dict[tuple, AggState] = {}
        self.blocks = 0          # probe blocks folded (computed + resumed)
        self.cells = 0           # probe cells those blocks carried
        self.shrink_probes = 0   # distinct probes the shrinker spent

    # ── probing ───────────────────────────────────────────────────────

    def probe(self, est_name: str, knobs: dict, n_reps: int,
              journal: bool = True) -> AggState:
        """Merged aggregate state of ``n_reps`` replicates of the probed
        column, block-journaled and block-resumable. ``journal=False``
        is the fresh-confirmation/repro path: recompute every block,
        trust nothing."""
        import jax.numpy as jnp

        key = (est_name, knobs_id(knobs), n_reps)
        if journal and key in self.cache:
            return self.cache[key]
        spec = self.spec
        est = SCENARIO_ESTIMATORS[est_name]
        dgp = dgp_for(spec.baseline, knobs)
        width = spec.width()
        exe = aggregate_executable(
            dgp, est, width, column=f"frontier:{est_name}:{dgp.name}",
        )
        probes_c = _probes_counter()
        state = AggState.zero()
        for lo in range(0, n_reps, width):
            batch = tuple(range(lo, min(lo + width, n_reps)))
            method = probe_row_id(est_name, knobs, batch)
            rec = self.ckpt.get(method) if (journal and self.ckpt) else None
            if rec is not None and _probe_resumable(rec):
                block = AggState.from_array(np.asarray(rec["stats"]))
                probes_c.inc(1, estimator=est_name, status="resumed")
            else:
                ids = pad_ids(dgp.name, batch, width)
                mask = batch_mask(batch, width, dgp.dtype)
                stats = np.asarray(exe(
                    self.root_key, jnp.asarray(ids), jnp.asarray(mask),
                ))
                block = AggState.from_array(stats)
                probes_c.inc(1, estimator=est_name, status="computed")
                if journal and self.ckpt is not None:
                    self.ckpt.put({
                        "method": method,
                        "schema": FRONTIER_SCHEMA_TAG,
                        "estimator": est_name,
                        "knobs": {k: knobs[k] for k in sorted(knobs)},
                        "reps": [batch[0], batch[-1]],
                        "width": width,
                        "status": "ok",
                        "stats": list(block.stats),
                    })
            state = state.merge(block)
            self.blocks += 1
            self.cells += len(batch)
        if journal:
            self.cache[key] = state
        return state

    def verdict(self, state: AggState, n_reps: int) -> dict:
        """Pure classification of one probed state. ``degenerate``
        means no SE-carrying replicate survived (coverage undefined) —
        reported, never silently dropped."""
        spec = self.spec
        summ = state.summary(spec.nominal)
        cov, mc = summ["coverage"], summ["coverage_mc_se"]
        out = {
            "reps": n_reps,
            "n_ok": summ["n_ok"],
            "n_se": int(state.n_se),
            "coverage": cov,
            "mc_se": mc,
            "bias": summ["bias"],
            "rmse": summ["rmse"],
            "power": summ["power"],
        }
        if cov is None:
            out["deficit"] = None
            out["verdict"] = "degenerate"
            return out
        deficit = spec.nominal - cov
        out["deficit"] = deficit
        out["verdict"] = (
            "failing" if deficit > spec.fail_z * mc else "ok"
        )
        return out

    def probe_point(self, est_name: str, knobs: dict) -> dict:
        """One grid cell: initial probe, MC-SE-aware refinement, final
        verdict."""
        spec = self.spec
        state = self.probe(est_name, knobs, spec.n_reps)
        cell = self.verdict(state, spec.n_reps)
        refined = False
        if (
            cell["deficit"] is not None
            and cell["deficit"] > spec.refine_z * cell["mc_se"]
            and spec.refine_reps > spec.n_reps
        ):
            refined = True
            state = self.probe(est_name, knobs, spec.refine_reps)
            cell = self.verdict(state, spec.refine_reps)
        cell["refined"] = refined
        return cell

    # ── shrinking ─────────────────────────────────────────────────────

    def shrink(self, est_name: str, knobs: dict, reps: int) -> dict:
        """ddmin the failing knob vector down to a 1-minimal delta set
        that still fails at the same replicate count, then CONFIRM with
        one fresh un-journaled probe. Atoms are (knob, value) deltas
        from the baseline; knobs already at baseline value contribute
        no atom (they cannot be part of any minimal explanation)."""
        from ate_replication_causalml_tpu.resilience.campaign import ddmin

        base = self.spec.baseline
        atoms = sorted(
            (k, v) for k, v in knobs.items()
            if KNOB_FIELDS[k](v) != getattr(base, k)
        )
        probed = [0]

        def fails(subset: list) -> bool:
            sub = dict(subset)
            # A candidate subset can leave the estimator inapplicable
            # (e.g. keeping p=96 while dropping the n knob that made
            # the design estimable) — that is "not this failure", never
            # a probe.
            if not SCENARIO_ESTIMATORS[est_name].applicable(
                dgp_for(base, sub)
            ):
                return False
            key = (est_name, knobs_id(sub), reps)
            if key not in self.cache:
                probed[0] += 1
            state = self.probe(est_name, sub, reps)
            v = self.verdict(state, reps)
            return v["verdict"] == "failing"

        minimal = dict(ddmin(atoms, fails)) if atoms else {}
        self.shrink_probes += probed[0]
        confirm = self.verdict(
            self.probe(est_name, minimal, reps, journal=False), reps,
        )
        repro = (
            "python -m ate_replication_causalml_tpu.scenarios.frontier "
            f"--repro --estimator {est_name} "
            f"--knobs '{knobs_id(minimal)}' --reps {reps} "
            f"--seed {self.spec.seed} --n {base.n} "
            f"--batch {self.spec.width()}"
        )
        return {
            "minimal_knobs": {k: minimal[k] for k in sorted(minimal)},
            "confirmed": confirm["verdict"] == "failing",
            "confirm_coverage": confirm["coverage"],
            "repro": repro,
        }


def run_frontier(
    spec: FrontierSpec, outdir: str | None = None,
    log: Callable[[str], None] = print,
) -> dict:
    """The full search: probe every axis grid cell for every estimator,
    refine where coverage is collapsing, shrink every failure, return
    (and atomically export) the atlas. The atlas carries no wall-clock
    and no resume-history-dependent fields — same root seed, byte-same
    FAILURE_ATLAS.json."""
    from ate_replication_causalml_tpu.pipeline import _Checkpoint

    obs.install_jax_monitoring()
    ckpt = None
    if outdir:
        os.makedirs(outdir, exist_ok=True)
        ckpt = _Checkpoint(
            os.path.join(outdir, "frontier.jsonl"),
            spec.fingerprint(), log=log,
        )
    search = FrontierSearch(spec, ckpt=ckpt, log=log)
    axes_out: list[dict] = []
    failures: list[dict] = []
    with obs.span("run_frontier", axes=len(spec.axes),
                  estimators=len(spec.estimators)):
        for axis in spec.axes:
            cells: list[dict] = []
            for knobs in axis.points():
                for est_name in spec.estimators:
                    dgp = dgp_for(spec.baseline, knobs)
                    est = SCENARIO_ESTIMATORS[est_name]
                    entry: dict = {
                        "estimator": est_name,
                        "knobs": {k: knobs[k] for k in sorted(knobs)},
                    }
                    if not est.applicable(dgp):
                        entry["verdict"] = "skipped"
                        cells.append(entry)
                        continue
                    cell = search.probe_point(est_name, knobs)
                    entry.update(cell)
                    cells.append(entry)
                    if cell["verdict"] != "failing":
                        continue
                    log(
                        f"frontier: {est_name} FAILS at "
                        f"{knobs_id(knobs)} (coverage "
                        f"{cell['coverage']:.3f}, deficit "
                        f"{cell['deficit']:.3f} > "
                        f"{spec.fail_z:g}·{cell['mc_se']:.4f}) — "
                        f"shrinking"
                    )
                    shrunk = search.shrink(est_name, knobs, cell["reps"])
                    failures.append({
                        "estimator": est_name,
                        "axis": axis.name,
                        "knobs": entry["knobs"],
                        "reps": cell["reps"],
                        "coverage": cell["coverage"],
                        "mc_se": cell["mc_se"],
                        **shrunk,
                    })
            axes_out.append({
                "name": axis.name,
                "knobs": {k: list(v) for k, v in axis.knobs},
                "cells": cells,
            })
    atlas = {
        "schema": FRONTIER_SCHEMA_TAG,
        "schema_version": 1,
        "fingerprint": spec.fingerprint(),
        "seed": spec.seed,
        "nominal": spec.nominal,
        "fail_z": spec.fail_z,
        "refine_z": spec.refine_z,
        "n_reps": spec.n_reps,
        "refine_reps": spec.refine_reps,
        "block_width": spec.width(),
        "baseline": {
            f.name: getattr(spec.baseline, f.name)
            for f in dataclasses.fields(spec.baseline)
        },
        "estimators": list(spec.estimators),
        "axes": axes_out,
        "failures": failures,
        "probes": {
            "blocks": search.blocks,
            "cells": search.cells,
            "shrink_probes": search.shrink_probes,
        },
    }
    if outdir:
        obs.atomic_write_json(
            os.path.join(outdir, "FAILURE_ATLAS.json"), atlas,
            sort_keys=True,
        )
        try:
            obs.write_run_artifacts(outdir)
        except Exception as e:  # noqa: BLE001 — telemetry export must
            # not fail the search whose atlas already committed.
            log(f"frontier telemetry export failed: {e!r}")
    log(
        f"frontier: {sum(len(a['cells']) for a in axes_out)} grid cells, "
        f"{len(failures)} failure(s), {search.blocks} probe blocks "
        f"({search.cells} cells)"
    )
    return atlas


# ── stock specs ──────────────────────────────────────────────────────


def default_frontier_spec(seed: int = 0) -> FrontierSpec:
    """The committed-atlas search: both literature axes at full scale.
    Axis A sweeps the overlap-violation corner (arXiv:1604.07125's
    regime) at the baseline n=96, where weak-overlap IPW genuinely
    destabilizes (at large n the logit propensity recovers and the
    corner merely undercovers inside the MC band). Axis B sweeps
    dimension against coefficient density (dense p≫small-sample
    designs — the anti-sparsity stress of arXiv:1201.0224); it pins
    n=256 through a single-valued axis knob so every p stays estimable
    (n > p + 2) — which also makes n part of the shrinker's atom
    vocabulary, so an axis-B failure's minimal vector names BOTH the
    dimension and the sample size it needs."""
    baseline = DGPSpec(
        name="frontier_base", n=96, p=4, tau="constant",
        tau_scale=0.8, confounding=0.0, overlap=0.5, sparsity=0,
    )
    return FrontierSpec(
        axes=(
            FrontierAxis(
                "overlap_confounding",
                (("confounding", (0.0, 2.0, 4.0, 6.0)),
                 ("overlap", (0.5, 0.1, 0.02))),
            ),
            FrontierAxis(
                "dimension_sparsity",
                (("n", (256,)),
                 ("p", (4, 48, 96)), ("sparsity", (0, 4))),
            ),
        ),
        estimators=("ipw_logit", "aipw_logit"),
        baseline=baseline,
        n_reps=64,
        refine_reps=192,
        batch_width=32,
        seed=seed,
    )


def micro_frontier_spec(seed: int = 0) -> FrontierSpec:
    """The tier-1 search: the 2×2 corners of the overlap/confounding
    axis for one estimator — four probed columns, compile budget
    O(4), seconds not minutes, but the same acquisition/shrink/atlas
    machinery end to end (the γ/η interaction still makes the minimal
    failing vector the PAIR of knobs)."""
    baseline = DGPSpec(
        name="frontier_micro_base", n=96, p=4, tau="constant",
        tau_scale=0.8, confounding=0.0, overlap=0.5, sparsity=0,
    )
    return FrontierSpec(
        axes=(
            FrontierAxis(
                "overlap_confounding",
                (("confounding", (0.0, 6.0)),
                 ("overlap", (0.5, 0.02))),
            ),
        ),
        estimators=("ipw_logit",),
        baseline=baseline,
        n_reps=16,
        refine_reps=48,
        batch_width=16,
        seed=seed,
    )


# ── CLI ──────────────────────────────────────────────────────────────


def main(argv: list[str] | None = None) -> dict:
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        description="Adversarial failure-frontier search (ISSUE 19)")
    ap.add_argument("--out", default=None,
                    help="output directory (frontier.jsonl + "
                    "FAILURE_ATLAS.json + telemetry)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--micro", action="store_true",
                    help="run the tier-1 micro search instead of the "
                    "full committed-atlas search")
    ap.add_argument("--repro", action="store_true",
                    help="replay ONE probe fresh (no journal, no "
                    "cache) and print its verdict as JSON — the "
                    "one-line repro the atlas records per failure")
    ap.add_argument("--estimator", default="ipw_logit")
    ap.add_argument("--knobs", default="",
                    help="comma list k=v of knob deltas from baseline")
    ap.add_argument("--reps", type=int, default=None)
    ap.add_argument("--n", type=int, default=None,
                    help="baseline sample size override (repro lines "
                    "pin the atlas baseline's n)")
    ap.add_argument("--batch", type=int, default=None,
                    help="probe block width override (repro lines pin "
                    "the search's width — f32 merges are "
                    "segment-dependent)")
    args = ap.parse_args(argv)

    spec = (micro_frontier_spec(seed=args.seed) if args.micro
            else default_frontier_spec(seed=args.seed))
    if args.n is not None:
        spec = dataclasses.replace(
            spec, baseline=dataclasses.replace(spec.baseline, n=args.n))
    if args.batch is not None:
        spec = dataclasses.replace(spec, batch_width=args.batch)

    if args.repro:
        reps = spec.refine_reps if args.reps is None else args.reps
        # Pin the block segmentation exactly: width() floors at n_reps,
        # so a tiny --reps repro must not accidentally shrink the width
        # the failing search used.
        if args.batch is not None:
            spec = dataclasses.replace(
                spec, n_reps=max(spec.n_reps, args.batch))
        search = FrontierSearch(spec, ckpt=None, log=print)
        knobs = parse_knobs(args.knobs)
        state = search.probe(args.estimator, knobs, reps, journal=False)
        verdict = search.verdict(state, reps)
        verdict["estimator"] = args.estimator
        verdict["knobs"] = {k: knobs[k] for k in sorted(knobs)}
        print(_json.dumps(verdict, sort_keys=True))
        return verdict

    return run_frontier(spec, outdir=args.out)


if __name__ == "__main__":
    main()

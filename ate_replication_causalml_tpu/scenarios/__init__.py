"""Monte-Carlo scenario matrix (ISSUE 13 + 19): seeded synthetic DGP
library, batched (vmapped-replicate) estimator entry points, the matrix
runner on the SweepEngine — streaming device-resident aggregates by
default, per-cell rows opt-in — and the adversarial failure-frontier
search. One executable per scenario COLUMN, millions of cells — see
``scenarios/matrix.py`` and ``scenarios/frontier.py`` for the
contracts."""

from ate_replication_causalml_tpu.scenarios.aggregate import (
    AGG_SCHEMA_TAG,
    AggState,
    N_STATS,
    STAT_FIELDS,
    aggregate_executable,
    batch_stats,
    fold_executable,
    fold_rows,
)
from ate_replication_causalml_tpu.scenarios.batched import (
    MAX_VMAP_COLLAPSE_ULP,
    SCENARIO_ESTIMATORS,
    ScenarioEstimator,
    batch_mask,
    cell_fn,
    clear_executables,
    column_cache_key,
    column_executable,
    pad_ids,
    scalar_executable,
)
from ate_replication_causalml_tpu.scenarios.dgp import (
    DGPSpec,
    STOCK_DGPS,
    data_cell_id,
    estimator_salt,
    generate,
)
from ate_replication_causalml_tpu.scenarios.matrix import (
    ColumnPlan,
    MatrixReport,
    MatrixSpec,
    block_row_id,
    cell_row_id,
    column_aggregates,
    column_name,
    compare_cells,
    micro_matrix_spec,
    plan_columns,
    run_matrix,
    run_scalar_replay,
)

__all__ = [
    "AGG_SCHEMA_TAG", "MAX_VMAP_COLLAPSE_ULP", "N_STATS",
    "SCENARIO_ESTIMATORS", "STAT_FIELDS", "STOCK_DGPS",
    "AggState", "ColumnPlan", "DGPSpec", "MatrixReport", "MatrixSpec",
    "ScenarioEstimator",
    "aggregate_executable", "batch_mask", "batch_stats", "block_row_id",
    "cell_fn", "cell_row_id", "clear_executables", "column_aggregates",
    "column_cache_key", "column_executable", "column_name",
    "compare_cells", "data_cell_id", "estimator_salt", "fold_executable",
    "fold_rows", "generate", "micro_matrix_spec", "pad_ids",
    "plan_columns", "run_matrix", "run_scalar_replay",
    "scalar_executable",
]

"""Monte-Carlo scenario matrix (ISSUE 13): seeded synthetic DGP
library, batched (vmapped-replicate) estimator entry points, and the
matrix runner on the SweepEngine. One executable per scenario COLUMN,
thousands of cells — see ``scenarios/matrix.py`` for the contracts."""

from ate_replication_causalml_tpu.scenarios.batched import (
    MAX_VMAP_COLLAPSE_ULP,
    SCENARIO_ESTIMATORS,
    ScenarioEstimator,
    cell_fn,
    clear_executables,
    column_cache_key,
    column_executable,
    scalar_executable,
)
from ate_replication_causalml_tpu.scenarios.dgp import (
    DGPSpec,
    STOCK_DGPS,
    data_cell_id,
    estimator_salt,
    generate,
)
from ate_replication_causalml_tpu.scenarios.matrix import (
    ColumnPlan,
    MatrixReport,
    MatrixSpec,
    cell_row_id,
    column_aggregates,
    column_name,
    compare_cells,
    micro_matrix_spec,
    plan_columns,
    run_matrix,
    run_scalar_replay,
)

__all__ = [
    "MAX_VMAP_COLLAPSE_ULP", "SCENARIO_ESTIMATORS", "STOCK_DGPS",
    "ColumnPlan", "DGPSpec", "MatrixReport", "MatrixSpec",
    "ScenarioEstimator",
    "cell_fn", "cell_row_id", "clear_executables", "column_aggregates",
    "column_cache_key", "column_executable", "column_name",
    "compare_cells", "data_cell_id", "estimator_salt", "generate",
    "micro_matrix_spec", "plan_columns", "run_matrix",
    "run_scalar_replay", "scalar_executable",
]

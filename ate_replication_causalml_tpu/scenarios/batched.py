"""Batched estimator entry points (ISSUE 13, tentpole part b).

The round-5 hardware lesson says executable COUNT is a first-class cost
(1–5 s of remote compile each), so a Monte-Carlo matrix of thousands of
(DGP × estimator × seed) cells must not compile per cell. This module
vmaps the REPLICATE axis of the closed-form/GLM/LASSO estimators into
one fit+estimate executable per (DGP-shape × estimator × config)
column:

* :func:`cell_fn` — one replicate, a pure function of
  ``(root_key, cell_id)``: fold in the data key, generate the DGP draw
  (``scenarios/dgp.py``), derive the estimator's private key, estimate.
  The SAME function is the scalar-replay path, so batched-vs-sequential
  bit-identity is an assertion about vmap collapse, not about two
  implementations agreeing.
* :func:`column_executable` — ``jit(vmap(cell_fn))`` AOT-lowered and
  compiled ONCE per column cache key; every batch of replicate seeds in
  the column dispatches through it. The cache key
  (:func:`column_cache_key`) is the DGP spec's full field tuple plus
  the estimator name and batch width — two configs can never share an
  executable.
* forest-class engines (``vmapped=False``) cannot vmap a whole fit;
  the planner (``scenarios/matrix.py``) packs them at width 1 and the
  stage body dispatches each cell eagerly through the models' existing
  dispatch machinery instead.

Batched == scalar bit-identity: every estimator here reduces over the
ROW axis, which vmap leaves untouched, and XLA:CPU folds dot-product K
axes in 256-wide panels position-independently (the PR 10 probe) — so
collapse is exact for the stock estimators at the stock shapes; the
micro-matrix test asserts ``array_equal`` and any future estimator that
legitimately reassociates must pin its ulp bound there with a
rationale, not widen the default.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.scenarios.dgp import (
    DGPSpec,
    estimator_salt,
    generate,
)

#: bump when the cell function's derivation chain changes shape — old
#: journals must not resume against new numerics.
SCHEMA_TAG = "scenarios-v1"


@dataclasses.dataclass(frozen=True)
class ScenarioEstimator:
    """One estimator the matrix can schedule.

    ``fn(spec, x, w, y, key) -> (ate, se)`` must be pure jax when
    ``vmapped`` (it is traced under ``vmap``+``jit``); non-vmapped
    engines receive concrete arrays and may dispatch however the
    underlying model does. ``has_se`` gates the coverage/power
    aggregates (the LASSO point estimates carry ``se=NaN`` like the
    reference's no-SE rows)."""

    name: str
    fn: Callable
    vmapped: bool = True
    has_se: bool = True
    #: GLM/OLS designs need n > p + 2 (intercept + treatment columns);
    #: the planner refuses inapplicable (DGP, estimator) columns.
    needs_tall: bool = True
    #: whether the batched column is BIT-identical to its scalar replay
    #: (the PR 10 discipline). True for pure row-reduction estimators
    #: (vmap leaves the reduction axis untouched). False where the
    #: estimator's matmuls reassociate under batching: XLA:CPU lowers a
    #: scalar (n,p)@(p,) to a sequential gemv but the vmapped
    #: (B,n,p)@(p,) to a panel-folded gemm (the PR 10 probe — K folds
    #: in 256-wide panels, shape-dependent), so GLM/OLS columns are
    #: pinned at MAX_VMAP_COLLAPSE_ULP instead, with this rationale.
    vmap_collapse_exact: bool = False

    def applicable(self, spec: DGPSpec) -> bool:
        return (not self.needs_tall) or spec.n > spec.p + 2


def _est_naive(spec, x, w, y, key):
    from ate_replication_causalml_tpu.estimators.naive import _naive_core

    return _naive_core(w, y)


def _est_ols(spec, x, w, y, key):
    from ate_replication_causalml_tpu.estimators.ols import _direct_core

    return _direct_core(x, w, y)


def _est_ipw_logit(spec, x, w, y, key):
    from ate_replication_causalml_tpu.estimators.ipw import (
        _psw_core,
        logistic_propensity,
    )

    return _psw_core(x, w, y, logistic_propensity(x, w))


def _est_aipw_logit(spec, x, w, y, key):
    """Textbook AIPW (``compat="fixed"`` sign — the doubly-robust form,
    not the reference's published quirk) with sandwich SE: the coverage
    claims validated against Chernozhukov et al. rates must use the
    estimator the theory is about."""
    from ate_replication_causalml_tpu.estimators.aipw import (
        _outcome_model_mu,
        aipw_sandwich_se,
    )
    from ate_replication_causalml_tpu.ops import bootstrap as bt
    from ate_replication_causalml_tpu.ops.glm import logistic_glm
    from ate_replication_causalml_tpu.ops.linalg import add_intercept

    p = logistic_glm(add_intercept(x), w).fitted
    mu0, mu1 = _outcome_model_mu(x, w, y)
    tau = bt._aipw_tau(w, y, p, mu0, mu1, -1.0)
    return tau, aipw_sandwich_se(w, y, p, mu0, mu1, tau)


def _est_lasso(spec, x, w, y, key):
    """Single-equation LASSO (W never shrunk) — the p≫n column's
    estimator (Belloni-style sparse designs). Point estimate only, like
    the reference's no-SE LASSO rows."""
    from ate_replication_causalml_tpu.ops.lasso import cv_glmnet, default_foldid

    xw = jnp.concatenate([x, w[:, None]], axis=1)
    pfac = jnp.concatenate(
        [jnp.ones(x.shape[1], xw.dtype), jnp.zeros(1, xw.dtype)]
    )
    foldid = default_foldid(key, x.shape[0])
    cv = cv_glmnet(xw, y, family="gaussian", penalty_factor=pfac,
                   foldid=foldid)
    _, coefs = cv.coef_at("1se")
    return coefs[-1], jnp.full((), jnp.nan, xw.dtype)


def _est_aipw_rf(spec, x, w, y, key):
    """AIPW over a micro random-forest OOB propensity — the
    representative NON-vmappable engine: a whole forest fit cannot ride
    a vmap axis, so the planner packs these cells at width 1 and each
    dispatch goes through the forest's existing chunked-dispatch path
    (the scheduler/nuisance-cache discipline, not a batched column)."""
    from ate_replication_causalml_tpu.data.frame import CausalFrame
    from ate_replication_causalml_tpu.estimators.aipw import doubly_robust
    from ate_replication_causalml_tpu.models.forest import rf_oob_propensity

    frame = CausalFrame(x=jnp.asarray(x), w=jnp.asarray(w),
                        y=jnp.asarray(y), schema=None)
    res = doubly_robust(
        frame,
        lambda f: rf_oob_propensity(f, key=key, n_trees=16, depth=4),
        compat="fixed",
    )
    return res.ate, res.se


#: ulp budget (in units of f32 spacing at the compared magnitude) for
#: estimators whose vmap collapse legitimately reassociates. Measured
#: on this image: ≤ 4 ulp at n=128 (every reduction under XLA:CPU's
#: 256-wide gemm K panel — gemv and batched gemm accumulate in the
#: same order), ≤ ~200 ulp at n=384 (K crosses the panel width, the
#: two lowerings genuinely reassociate n-length IRLS/OLS reductions,
#: and the weak-overlap IPW column amplifies the drift through its
#: near-singular weighting). 512 bounds the measured regime with
#: headroom; a real numerics bug (wrong data, wrong key threading)
#: diverges by orders of magnitude more, not ulps.
MAX_VMAP_COLLAPSE_ULP = 512.0

SCENARIO_ESTIMATORS: dict[str, ScenarioEstimator] = {
    e.name: e
    for e in (
        ScenarioEstimator("naive", _est_naive, needs_tall=False,
                          vmap_collapse_exact=True),
        ScenarioEstimator("ols", _est_ols),
        ScenarioEstimator("ipw_logit", _est_ipw_logit),
        ScenarioEstimator("aipw_logit", _est_aipw_logit),
        ScenarioEstimator("lasso", _est_lasso, has_se=False,
                          needs_tall=False),
        ScenarioEstimator("aipw_rf", _est_aipw_rf, vmapped=False),
    )
}


def cell_fn(spec: DGPSpec, est: ScenarioEstimator) -> Callable:
    """The per-replicate function ``(root_key, cell_id) ->
    (ate, se, tau_true)`` — shared verbatim by the batched executable
    and the scalar replay. The data key is ``fold_in(root, cell_id)``
    (estimator-independent: every estimator in a (DGP, rep) row sees
    the same draw); the estimator's private key folds a per-estimator
    salt off the data key."""
    salt = np.uint32(estimator_salt(est.name))

    def run(root_key, cid):
        data_key = jax.random.fold_in(root_key, cid)
        x, w, y, tau_true = generate(spec, data_key)
        est_key = jax.random.fold_in(data_key, salt)
        ate, se = est.fn(spec, x, w, y, est_key)
        return (jnp.asarray(ate), jnp.asarray(se), tau_true)

    return run


def pad_ids(dgp_name: str, batch: tuple[int, ...], width: int) -> np.ndarray:
    """Cell-id operand for one dispatched batch: the final partial
    batch pads to the column's one executable width with duplicate ids
    (one executable SHAPE per column, the compile-count contract).
    Rows mode discards the padded outputs host-side; aggregate mode
    masks them inside the epilogue — both consume this same layout."""
    from ate_replication_causalml_tpu.scenarios.dgp import data_cell_id

    return np.asarray(
        [data_cell_id(dgp_name, r) for r in batch]
        + [data_cell_id(dgp_name, batch[0])] * (width - len(batch)),
        dtype=np.uint32,
    )


def batch_mask(batch: tuple[int, ...], width: int,
               dtype: str = "float32") -> np.ndarray:
    """The matching lane mask: 1.0 on real lanes, 0.0 on padding."""
    return np.asarray(
        [1.0] * len(batch) + [0.0] * (width - len(batch)), dtype=dtype
    )


def column_cache_key(spec: DGPSpec, estimator: str, width: int | None) -> tuple:
    """The executable-cache identity of one scenario column: the DGP
    spec's FULL field tuple (two specs differing in any knob can never
    share an executable), the estimator name, the packed batch width
    (``None`` = the scalar-replay executable), and the schema tag."""
    return (SCHEMA_TAG, spec.fields(), estimator, width)


#: compiled column executables by column_cache_key — the process-global
#: fit-once store that makes `jax_compiles_total` grow with COLUMNS,
#: not cells. Guarded by _EXE_LOCK (graftlint JGL008 discipline).
_EXECUTABLES: dict[tuple, object] = {}
_EXE_LOCK = threading.Lock()


def clear_executables() -> None:
    """Test hook: drop the compiled-column cache (compile-count
    assertions need a cold start)."""
    with _EXE_LOCK:
        _EXECUTABLES.clear()


def _compile_counter():
    return obs.counter(
        "scenario_column_compile_total",
        "scenario column executables AOT-compiled, by column and kind",
    )


def cached_executable(key: tuple, build: Callable, column: str, kind: str):
    """The fit-once executable-cache discipline every scenario
    executable family shares: lock-guarded lookup, ``build()`` (the
    ``lower().compile()``) outside the lock, ``setdefault`` commit — a
    compile race loses benignly, both compiles are the same function
    and the first writer wins the cache slot — and one per-column
    compile-counter tick for the thread that actually compiled."""
    with _EXE_LOCK:
        exe = _EXECUTABLES.get(key)
    if exe is not None:
        return exe
    compiled = build()
    with _EXE_LOCK:
        exe = _EXECUTABLES.setdefault(key, compiled)
    _compile_counter().inc(1, column=column, kind=kind)
    return exe


def column_executable(
    spec: DGPSpec, est: ScenarioEstimator, width: int, column: str = "",
    ids_sharding=None,
):
    """The column's ONE batched executable:
    ``compiled(root_key, cell_ids[width]) -> (ate[width], se[width],
    tau_true[width])``, AOT-lowered and compiled on first request and
    shared by every batch in the column (and by identical columns in
    later matrices in the same process).

    ``ids_sharding`` (a ``NamedSharding`` over the replicate axis, the
    matrix runner's ``ATE_TPU_SCENARIO_SHARD`` path) lowers the program
    with the cell-id input row-sharded over the mesh and the outputs
    replicated: the replicate axis is embarrassingly parallel, so each
    device computes its replicate slice and the result gathers once.
    The sharding joins the cache key — a sharded and an unsharded run
    never share an executable (their input layouts differ), but each
    still compiles exactly one per column. Callers dispatch sharded
    executables inside the mesh lane (a multi-device program launched
    off-lane can interleave another collective's rendezvous — the PR 4
    rule)."""
    if not est.vmapped:
        raise ValueError(
            f"estimator {est.name!r} is not vmappable — the planner must "
            "pack it at width 1 through the sequential path"
        )
    key = column_cache_key(spec, est.name, width) + (ids_sharding,)

    def build():
        fn = jax.vmap(cell_fn(spec, est), in_axes=(None, 0))
        root = jax.random.key(0)
        ids = jnp.zeros((width,), jnp.uint32)
        if ids_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(ids_sharding.mesh, P())
            jitted = jax.jit(fn, in_shardings=(rep, ids_sharding),
                             out_shardings=rep)
            ids = jax.device_put(np.zeros((width,), np.uint32), ids_sharding)
            root = jax.device_put(root, rep)
        else:
            jitted = jax.jit(fn)
        return jitted.lower(root, ids).compile()

    return cached_executable(
        key, build, column or f"{spec.name}:{est.name}", "batched")


def scalar_executable(spec: DGPSpec, est: ScenarioEstimator, column: str = ""):
    """The scalar-replay executable for the same cell function —
    ``compiled(root_key, cell_id) -> (ate, se, tau_true)``. One compile
    per column here too; the sequential leg pays per-CELL dispatches,
    not per-cell compiles (that is the honest baseline the batching is
    measured against)."""
    key = column_cache_key(spec, est.name, None)

    def build():
        fn = cell_fn(spec, est)
        root = jax.random.key(0)
        cid = jnp.zeros((), jnp.uint32)
        return jax.jit(fn).lower(root, cid).compile()

    return cached_executable(
        key, build, column or f"{spec.name}:{est.name}", "scalar")

"""Seeded synthetic DGP library for the Monte-Carlo scenario matrix
(ISSUE 13, tentpole part a).

Every cell's data is a PURE function of ``fold_in(root_key, cell_id)``:
the generator takes a key and a frozen :class:`DGPSpec` and returns the
replicate's ``(x, w, y, tau_true)`` with no ambient state — which is
what lets the batched estimator entry points (``scenarios/batched.py``)
vmap the replicate axis into ONE executable per scenario column, and
what makes checkpoint/resume at cell granularity bit-identical (the
same ``cell_id`` always regenerates the same bits).

The knobs stress exactly what the literature proves:

* ``tau="hetero"`` — smooth heterogeneous τ(x) surfaces in the style of
  Wager & Athey (arXiv:1510.04342, the honest-forest asymptotics
  benchmark surfaces);
* ``confounding`` — propensity loading on x₁ (γ in
  ``e(x) = η + (1-2η)·σ(γ·x₁)``), the cross-fitting stress of
  Chernozhukov et al. (arXiv:1608.00060);
* ``overlap`` — η above: the minimum propensity. Small η pushes e(x)
  toward {0,1}, the overlap-violation regime residual balancing
  (arXiv:1604.07125) targets;
* ``sparsity``/large ``p`` — p≫n designs with Belloni-style decaying
  coefficients (arXiv:1201.0224, post-double-selection).

The outcome is binary through a logit link, so the per-replicate truth
``tau_true = mean(p₁(x) - p₀(x))`` is EXACT (the sample-average
treatment effect on the probability scale, computed from the potential
probabilities, not from realized draws) — coverage/bias/RMSE per cell
need no Monte-Carlo approximation of the estimand.
"""

from __future__ import annotations

import dataclasses
import zlib

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DGPSpec:
    """One synthetic design, fully determined by its fields (the fields
    ARE the column cache key — see ``scenarios.batched.column_cache_key``).

    ``tau``: ``"constant"`` (τ(x) ≡ ``tau_scale`` on the logit scale —
    the calibration design every correctly-specified estimator must
    cover at nominal rate) or ``"hetero"`` (the Wager–Athey-style smooth
    surface above).
    """

    name: str
    n: int = 512
    p: int = 4
    tau: str = "constant"
    tau_scale: float = 0.8
    confounding: float = 0.0
    overlap: float = 0.5
    sparsity: int = 0
    dtype: str = "float32"

    def __post_init__(self) -> None:
        if self.tau not in ("constant", "hetero"):
            raise ValueError(f"tau must be 'constant' or 'hetero', got {self.tau!r}")
        if not (0.0 < self.overlap <= 0.5):
            raise ValueError(f"overlap must be in (0, 0.5], got {self.overlap!r}")
        if self.sparsity < 0 or self.sparsity > self.p:
            raise ValueError(f"sparsity must be in [0, p], got {self.sparsity!r}")

    def fields(self) -> tuple:
        """The spec as a flat tuple — the hashable identity the column
        cache key and the checkpoint fingerprint are built from."""
        return tuple(
            getattr(self, f.name) for f in dataclasses.fields(self)
        )


def data_cell_id(dgp_name: str, rep: int) -> int:
    """Stable uint32 id of one replicate's DATA (shared by every
    estimator in the same (DGP, rep) row — the standard MC design: all
    estimators see the same draw). ``fold_in(root_key, data_cell_id)``
    is the replicate's data key."""
    return zlib.crc32(f"dgp={dgp_name}|rep={rep}".encode())


def estimator_salt(estimator_name: str) -> int:
    """Stable uint32 fold-in constant deriving an estimator's private
    key (fold masks, any internal randomness) from the replicate's data
    key — distinct estimators on the same data draw independent keys."""
    return zlib.crc32(f"est={estimator_name}".encode())


def _beta(spec: DGPSpec, dtype) -> jax.Array:
    """Deterministic baseline coefficients. Dense designs load every
    column at 1/√p; sparse designs (``sparsity`` = s > 0) use the
    Belloni-style 1/(j+1) decay on the first s columns and exact zeros
    elsewhere — the approximately-sparse regime of arXiv:1201.0224."""
    idx = jnp.arange(spec.p, dtype=dtype)
    if spec.sparsity > 0:
        return jnp.where(idx < spec.sparsity, 1.0 / (idx + 1.0), 0.0)
    return jnp.full((spec.p,), 1.0 / jnp.sqrt(jnp.asarray(spec.p, dtype)))


def propensity(spec: DGPSpec, x: jax.Array) -> jax.Array:
    """``e(x) = η + (1-2η)·σ(γ·x₁)``: γ=0 is a randomized design with
    e ≡ 1/2 (the calibration DGP); η bounds e away from {0,1}, so small
    η under strong γ is a graded overlap violation, never a hard one —
    IPW variance blows up smoothly instead of dividing by zero."""
    dtype = x.dtype
    eta = jnp.asarray(spec.overlap, dtype)
    gamma = jnp.asarray(spec.confounding, dtype)
    return eta + (1.0 - 2.0 * eta) * jax.nn.sigmoid(gamma * x[:, 0])


def tau_surface(spec: DGPSpec, x: jax.Array) -> jax.Array:
    """τ(x) on the logit scale. ``"hetero"`` composes the Wager–Athey
    bump ``ς(v) = 1 + 1/(1+exp(-20(v-1/3)))`` (arXiv:1510.04342, their
    heterogeneous-effect surfaces on U(0,1) covariates) over
    ``σ(x₁)``/``σ(x₂)`` — smooth, bounded, genuinely x-dependent."""
    dtype = x.dtype
    scale = jnp.asarray(spec.tau_scale, dtype)
    if spec.tau == "constant":
        return jnp.full((x.shape[0],), scale)
    varsigma = lambda v: 1.0 + 1.0 / (1.0 + jnp.exp(-20.0 * (v - 1.0 / 3.0)))
    u1 = jax.nn.sigmoid(x[:, 0])
    u2 = jax.nn.sigmoid(x[:, 1 % spec.p])
    return scale * varsigma(u1) * varsigma(u2) / 4.0


def generate(
    spec: DGPSpec, key: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One replicate: ``(x, w, y, tau_true)``, a pure function of
    ``(spec, key)``.

    Binary outcome through a logit link: ``p₀ = σ(x·β)``,
    ``p₁ = σ(x·β + τ(x))``; realized ``y`` uses a SHARED uniform for
    both potential outcomes (monotone potential outcomes — the same
    device the repo's GGL generator uses). ``tau_true`` is the exact
    sample-average effect ``mean(p₁ - p₀)`` — the estimand coverage is
    measured against."""
    dtype = jnp.dtype(spec.dtype)
    kx, kw, ky = jax.random.split(key, 3)
    x = jax.random.normal(kx, (spec.n, spec.p), dtype)
    e = propensity(spec, x)
    w = (jax.random.uniform(kw, (spec.n,), dtype) < e).astype(dtype)
    eta0 = jnp.matmul(x, _beta(spec, dtype))
    p0 = jax.nn.sigmoid(eta0)
    p1 = jax.nn.sigmoid(eta0 + tau_surface(spec, x))
    u = jax.random.uniform(ky, (spec.n,), dtype)
    y = jnp.where(w == 1.0, (u < p1), (u < p0)).astype(dtype)
    tau_true = jnp.mean(p1 - p0)
    return x, w, y, tau_true


#: The stock designs the micro matrix, the bench record and the tests
#: draw from. ``calibration`` is the randomized correctly-specified
#: design whose coverage must sit at nominal (the SCENARIO_MATRIX.json
#: contract); the others turn one literature knob each.
STOCK_DGPS: dict[str, DGPSpec] = {
    d.name: d
    for d in (
        DGPSpec(name="calibration", n=512, p=4, tau="constant",
                tau_scale=0.8, confounding=0.0, overlap=0.5),
        DGPSpec(name="hetero_confounded", n=512, p=4, tau="hetero",
                tau_scale=0.8, confounding=1.0, overlap=0.1),
        DGPSpec(name="overlap_violation", n=512, p=4, tau="constant",
                tau_scale=0.8, confounding=2.0, overlap=0.02),
        DGPSpec(name="sparse_highdim", n=128, p=384, tau="constant",
                tau_scale=0.8, confounding=0.5, overlap=0.2, sparsity=4),
    )
}

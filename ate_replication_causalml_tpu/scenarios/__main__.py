"""``python -m ate_replication_causalml_tpu.scenarios`` — the matrix
CLI (avoids runpy's found-in-sys.modules warning that the
``.scenarios.matrix`` form triggers, since the package __init__
imports the module)."""

from ate_replication_causalml_tpu.scenarios.matrix import main

main()

"""Monte-Carlo scenario-matrix runner (ISSUE 13, tentpole part c).

Runs a (DGP × estimator × seed) cell grid through the PR 4
:class:`~..scheduler.SweepEngine`: each scenario COLUMN contributes one
executable artifact (``exe:{column}`` — the AOT-compiled vmapped
fit+estimate program from ``scenarios/batched.py``) plus one stage per
packed replicate batch; commit order is declaration order, so
``cells.jsonl`` is deterministic whatever the worker pool does.

Contracts carried here:

* **O(columns) executables** — all replicate seeds in a column
  dispatch through its single compiled program; the per-column cache
  key (:func:`~.batched.column_cache_key`) means identical columns in
  later runs of the same process compile ZERO times. The bench/tests
  assert ``jax_compiles_total`` deltas against the column count, never
  the cell count.
* **degrade-don't-abort per cell** — a failed batch (or a non-finite
  point estimate) becomes ``status="failed"`` rows for exactly the
  affected cells; the matrix keeps going (``fail_policy="raise"``
  aborts, for debugging).
* **checkpoint/resume at cell granularity** — rows append to
  ``cells.jsonl`` (the pipeline's ``_Checkpoint`` journal, config-
  fingerprinted, torn-line tolerant); a resumed run packs only the
  missing replicates into batches and a fully-completed column
  declares no artifact needs, so it schedules zero fits and zero
  compiles — by construction, the ISSUE 4 resume guarantee.
* **sharded dispatch** (``ATE_TPU_SCENARIO_SHARD=1``, multi-device) —
  the replicate axis itself is row-sharded over the data-axis mesh:
  batch widths pad to the device count (``shardio.pad_to_multiple``,
  the satellite helper lifting the replicated fallback), cell-id
  uploads and result gathers move through the metered PR 8 artifact
  plane, and the collective dispatches serialize through the "mesh"
  lane (the PR 4 rendezvous discipline).

Batch width is deliberately NOT part of the checkpoint fingerprint:
batched columns are bit-identical to their scalar replays (asserted in
tests/test_scenarios.py), so journals resume across widths — exactly
like the sweep's concurrent/sequential modes sharing one journal.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Callable, Iterable

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
)
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import ChaosStageFault
from ate_replication_causalml_tpu.scenarios.batched import (
    SCENARIO_ESTIMATORS,
    SCHEMA_TAG,
    column_cache_key,
    column_executable,
    scalar_executable,
)
from ate_replication_causalml_tpu.scenarios.dgp import (
    DGPSpec,
    STOCK_DGPS,
    data_cell_id,
    estimator_salt,
)

_BATCH_ENV = "ATE_TPU_SCENARIO_BATCH"
_REPS_ENV = "ATE_TPU_SCENARIO_REPS"
_SHARD_ENV = "ATE_TPU_SCENARIO_SHARD"

#: 95% normal critical value, matching estimators.base.Z_95.
_Z95 = 1.96


def _env_int(name: str, default: int) -> int:
    """Bad values raise at config time (the ATE_TPU_HIST_MODE /
    ATE_TPU_PREDICT_PACK discipline): a typo'd knob must not silently
    run a multi-hour grid at the default scale."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected a positive integer"
        ) from None
    if value < 1:
        raise ValueError(f"{name}={value}: expected a positive integer")
    return value


def default_batch_width() -> int:
    return _env_int(_BATCH_ENV, 32)


def default_reps() -> int:
    return _env_int(_REPS_ENV, 64)


def _env_shard() -> bool:
    return os.environ.get(_SHARD_ENV, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


@dataclasses.dataclass(frozen=True)
class MatrixSpec:
    """One scenario matrix: the DGP grid, the estimator set, and the
    replicate/batching policy. ``shard=None`` defers to
    ``ATE_TPU_SCENARIO_SHARD``."""

    dgps: tuple[DGPSpec, ...]
    estimators: tuple[str, ...]
    n_reps: int = 64
    batch_width: int = 32
    seed: int = 0
    fail_policy: str = "degrade"
    shard: bool | None = None

    def __post_init__(self) -> None:
        if self.fail_policy not in ("degrade", "raise"):
            raise ValueError(
                f"fail_policy must be 'degrade' or 'raise', got "
                f"{self.fail_policy!r}"
            )
        for name in self.estimators:
            if name not in SCENARIO_ESTIMATORS:
                raise ValueError(
                    f"unknown scenario estimator {name!r}; known: "
                    f"{sorted(SCENARIO_ESTIMATORS)}"
                )
        # Names are the column/journal/cell-id namespace: two DGPs (or
        # estimator entries) sharing one silently collide on journal
        # keys and merge their aggregates.
        dgp_names = [d.name for d in self.dgps]
        for seq, what in ((dgp_names, "DGP"), (self.estimators, "estimator")):
            dupes = {x for x in seq if list(seq).count(x) > 1}
            if dupes:
                raise ValueError(
                    f"duplicate {what} name(s) in MatrixSpec: {sorted(dupes)}"
                )

    def fingerprint(self) -> str:
        """Resume validity: DGP field tuples + estimator set + seed +
        schema tag. Replicate count and batch width are deliberately
        absent — extending reps resumes completed cells, and batched ==
        scalar bit-identity (asserted in-suite) makes widths
        interchangeable over one journal."""
        dgps = ";".join(repr(d.fields()) for d in self.dgps)
        return (
            f"{SCHEMA_TAG}|dgps=[{dgps}]|est={list(self.estimators)!r}"
            f"|seed={self.seed}"
        )


def micro_matrix_spec(
    n_reps: int | None = None, batch_width: int | None = None,
    n: int = 384, seed: int = 0,
) -> MatrixSpec:
    """The canonical micro matrix (2 DGPs × 3 estimators): the
    calibration design (coverage must sit at nominal) and the
    heterogeneous confounded design, through the three vmapped GLM-class
    estimators. Shared by ``bench.py --scenario-matrix`` and the
    acceptance test so the committed SCENARIO_MATRIX.json and the
    tier-1 assertion exercise the same grid."""
    calib = dataclasses.replace(STOCK_DGPS["calibration"], n=n)
    hetero = dataclasses.replace(STOCK_DGPS["hetero_confounded"], n=n)
    return MatrixSpec(
        dgps=(calib, hetero),
        estimators=("naive", "ipw_logit", "aipw_logit"),
        n_reps=default_reps() if n_reps is None else n_reps,
        batch_width=default_batch_width() if batch_width is None else batch_width,
        seed=seed,
    )


def column_name(dgp: DGPSpec, estimator: str) -> str:
    return f"{dgp.name}:{estimator}"


def cell_row_id(dgp_name: str, estimator: str, rep: int) -> str:
    """The journal key of one cell — ``_Checkpoint`` keys rows by
    ``method``, so the cell id IS the method field."""
    return f"{dgp_name}:{estimator}:{rep}"


@dataclasses.dataclass(frozen=True)
class ColumnPlan:
    """One scheduled column: which replicates still need computing and
    how they pack into fixed-width batches (the last batch pads to the
    declared width with duplicate ids whose outputs are discarded — one
    executable shape per column, the compile-count contract)."""

    name: str
    dgp: DGPSpec
    estimator: str
    width: int
    mode: str                      # "vmapped" | "sequential"
    remaining: tuple[int, ...]
    batches: tuple[tuple[int, ...], ...]


def plan_columns(
    spec: MatrixSpec, done: Callable[[str], bool] = lambda _cell: False,
    devices: int = 1,
) -> tuple[list[ColumnPlan], list[str]]:
    """Pure cell-batching planner: pack each column's not-yet-done
    replicate seeds into fixed-width batches. Non-vmappable engines
    (forest-class) pack at width 1 — each cell dispatches through the
    model's own machinery. Sharded runs pad the width up to the device
    count. Returns ``(plans, skipped)`` where ``skipped`` names
    (DGP, estimator) pairs the estimator declared inapplicable
    (e.g. OLS on a p≫n design)."""
    plans: list[ColumnPlan] = []
    skipped: list[str] = []
    shard = _env_shard() if spec.shard is None else spec.shard
    for dgp in spec.dgps:
        for est_name in spec.estimators:
            est = SCENARIO_ESTIMATORS[est_name]
            col = column_name(dgp, est_name)
            if not est.applicable(dgp):
                skipped.append(col)
                continue
            width = min(spec.batch_width, spec.n_reps) if est.vmapped else 1
            if shard and est.vmapped and devices > 1:
                from ate_replication_causalml_tpu.parallel.shardio import (
                    pad_to_multiple,
                )

                width = pad_to_multiple(width, devices)
            remaining = tuple(
                r for r in range(spec.n_reps)
                if not done(cell_row_id(dgp.name, est_name, r))
            )
            batches = tuple(
                remaining[i:i + width]
                for i in range(0, len(remaining), width)
            )
            plans.append(ColumnPlan(
                name=col, dgp=dgp, estimator=est_name, width=width,
                mode="vmapped" if est.vmapped else "sequential",
                remaining=remaining, batches=batches,
            ))
    return plans, skipped


# ── aggregates ────────────────────────────────────────────────────────

#: Shape of the per-column error sketch (ISSUE 16). Estimation errors
#: ``ate - tau_true`` live well inside ±8 for every DGP in the matrix;
#: anything outside lands in the sketch's explicit tails, so mass is
#: conserved either way. 8 bins matches the serving stat-health plane's
#: default, so offline and served sketches stay merge-compatible.
_ERROR_SKETCH_RANGE = (-8.0, 8.0)
_ERROR_SKETCH_BINS = 8


def column_aggregates(rows: Iterable[dict], nominal: float = 0.95) -> dict:
    """Per-column Monte-Carlo summaries from cell rows (pure, jax-free,
    unit-tested): coverage of the per-replicate truth by the 95% CI,
    bias / RMSE of the point estimate, power of the |ate|/se > z test
    against τ=0, and the binomial MC standard errors the validator's
    within-MC-error bands are built from. Failed cells count into
    ``n_failed`` and nothing else; no-SE estimators (LASSO point rows)
    report ``coverage=None``/``power=None``."""
    rows = list(rows)
    ok = [
        r for r in rows
        if r.get("status", "ok") == "ok"
        and isinstance(r.get("ate"), (int, float))
        and math.isfinite(r["ate"])
    ]
    with_se = [
        r for r in ok
        if isinstance(r.get("se"), (int, float)) and math.isfinite(r["se"])
    ]
    out: dict = {
        "n_cells": len(rows),
        "n_ok": len(ok),
        "n_failed": len(rows) - len(ok),
        "coverage": None,
        "power": None,
        "bias": None,
        "rmse": None,
        "coverage_mc_se": None,
        "nominal": nominal,
    }
    if ok:
        errs = [r["ate"] - r["tau_true"] for r in ok]
        out["bias"] = sum(errs) / len(errs)
        out["rmse"] = math.sqrt(sum(e * e for e in errs) / len(errs))
        out["mean_tau_true"] = sum(r["tau_true"] for r in ok) / len(ok)
    if with_se:
        covered = sum(
            1 for r in with_se
            if r["lower_ci"] <= r["tau_true"] <= r["upper_ci"]
        )
        rejected = sum(
            1 for r in with_se if abs(r["ate"]) > _Z95 * r["se"]
        )
        n = len(with_se)
        cov = covered / n
        out["coverage"] = cov
        out["power"] = rejected / n
        # Binomial MC standard error at the NOMINAL rate — the
        # validator's band is nominal ± z·this (using the nominal p
        # keeps the band honest when the observed rate is degenerate).
        out["coverage_mc_se"] = math.sqrt(nominal * (1.0 - nominal) / n)
    # Shared-sketch aggregates (ISSUE 16): the per-column error
    # distribution and CI-coverage reliability expressed through the
    # SAME mergeable sketch types the serving statistical-health plane
    # streams, so offline matrix columns and served traffic report one
    # schema — and sketches from sharded matrix runs merge
    # associatively, exactly like fleet-wide serving sketches.
    err_sketch = FixedBinSketch(*_ERROR_SKETCH_RANGE, _ERROR_SKETCH_BINS)
    if ok:
        err_sketch.update(errs)
    cov_sketch = CalibrationSketch()
    if with_se:
        cov_sketch.update(
            [nominal] * len(with_se),
            [r["lower_ci"] <= r["tau_true"] <= r["upper_ci"]
             for r in with_se],
        )
    out["sketches"] = {
        "error": err_sketch.to_dict(),
        "coverage": cov_sketch.to_dict(),
    }
    return out


def compare_cells(cells_a: Iterable[dict], cells_b: Iterable[dict]) -> dict:
    """Per-column batched-vs-scalar comparison (bench + tests): for each
    column the max deviation of ate/se/tau_true in f32 ULPS at the
    compared magnitude (NaN == NaN). Returns ``{"columns": {col:
    max_ulp}, "max_ulp": float, "exact_columns": [cols at 0 ulp],
    "missing": [cell ids present on one side only]}``."""
    am = {r["method"]: r for r in cells_a}
    bm = {r["method"]: r for r in cells_b}
    missing = sorted(set(am) ^ set(bm))
    per_col: dict[str, float] = {}
    for cell in set(am) & set(bm):
        ra, rb = am[cell], bm[cell]
        worst = per_col.get(ra["column"], 0.0)
        for field in ("ate", "se", "tau_true"):
            a, b = ra.get(field), rb.get(field)
            a_nan = not _finite(a)
            b_nan = not _finite(b)
            if a_nan and b_nan:
                continue
            if a_nan != b_nan:
                worst = float("inf")
                continue
            if a == b:
                continue
            scale = float(np.spacing(np.float32(max(abs(a), abs(b)))))
            worst = max(worst, abs(a - b) / scale)
        per_col[ra["column"]] = worst
    finite_ulps = [u for u in per_col.values() if math.isfinite(u)]
    return {
        "columns": per_col,
        "max_ulp": (float("inf") if len(finite_ulps) < len(per_col)
                    else max(finite_ulps, default=0.0)),
        "exact_columns": sorted(c for c, u in per_col.items() if u == 0.0),
        "missing": missing,
    }


# ── the runner ────────────────────────────────────────────────────────


@dataclasses.dataclass
class MatrixReport:
    """Everything one matrix run produces: per-cell rows (notebook
    order), per-column aggregates, and the perf evidence (wall seconds,
    compile-event delta, executables compiled) the bench record and the
    in-suite O(columns) assertion read."""

    cells: list = dataclasses.field(default_factory=list)
    columns: dict = dataclasses.field(default_factory=dict)
    skipped_columns: list = dataclasses.field(default_factory=list)
    n_resumed: int = 0
    n_computed: int = 0
    n_failed: int = 0
    wall_s: float = 0.0
    compile_events_delta: float = 0.0
    n_columns: int = 0
    n_batches: int = 0
    #: a SIGTERM drain (ISSUE 14) cut this run short: the journal holds
    #: the committed prefix and a rerun resumes cell-exact.
    drained: bool = False


def _cells_counter():
    return obs.counter(
        "scenario_cells_total",
        "scenario-matrix cells by column and computed/resumed/failed status",
    )


def _dispatch_counter():
    return obs.counter(
        "scenario_batch_dispatch_total",
        "scenario-matrix batch dispatches by column and vmapped/sequential mode",
    )


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


def _cell_record(
    plan: ColumnPlan, rep: int, ate: float, se: float, tau_true: float,
    seconds: float,
) -> dict:
    ate, se, tau_true = float(ate), float(se), float(tau_true)
    status = "ok" if math.isfinite(ate) else "failed"
    rec = {
        "method": cell_row_id(plan.dgp.name, plan.estimator, rep),
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "rep": rep,
        "ate": ate,
        "se": se,
        "lower_ci": ate - _Z95 * se if math.isfinite(se) else ate,
        "upper_ci": ate + _Z95 * se if math.isfinite(se) else ate,
        "tau_true": tau_true,
        "status": status,
        "seconds": round(seconds, 6),
    }
    if status == "failed":
        rec["error"] = f"NonFiniteResult: ate={ate!r}"
    return rec


def _failed_record(plan: ColumnPlan, rep: int, error: str) -> dict:
    nan = float("nan")
    return {
        "method": cell_row_id(plan.dgp.name, plan.estimator, rep),
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "rep": rep,
        "ate": nan, "se": nan, "lower_ci": nan, "upper_ci": nan,
        "tau_true": nan,
        "status": "failed",
        "error": error,
        "seconds": 0.0,
    }


def run_matrix(
    spec: MatrixSpec,
    outdir: str | None = None,
    workers: int | None = None,
    scheduler: str | None = None,
    prefetch: bool | None = None,
    log: Callable[[str], None] = print,
    drain_on_sigterm: bool = False,
) -> MatrixReport:
    """Run the matrix through the real SweepEngine. See module
    docstring for the contracts; telemetry exports to ``outdir`` beside
    ``cells.jsonl`` and ``matrix_report.json``. With
    ``drain_on_sigterm`` (the CLI default), SIGTERM gracefully drains
    the engine (ISSUE 14): in-flight batch stages complete, their rows
    commit in declared order through the checkpoint journal, the
    process exits 0 — and a resumed run picks up cell-exact where the
    drain stopped, exactly like the SIGKILL crash-resume contract but
    without losing the in-flight batches."""
    import jax

    from ate_replication_causalml_tpu.pipeline import (
        _Checkpoint,
        _resolve_scheduler,
        _row_resumable,
    )
    from ate_replication_causalml_tpu.scheduler import (
        ArtifactSpec,
        StageSpec,
        SweepEngine,
    )

    obs.install_jax_monitoring()
    n_workers = _resolve_scheduler(scheduler, workers, log)
    t_start = time.monotonic()
    compiles_before = obs.compile_event_count()
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    ckpt = _Checkpoint(
        os.path.join(outdir, "cells.jsonl") if outdir else None,
        spec.fingerprint(), log=log,
    )

    def resumable(cell: str) -> bool:
        rec = ckpt.get(cell)
        return rec is not None and _row_resumable(rec)[0]

    shard = _env_shard() if spec.shard is None else spec.shard
    devices = jax.device_count()
    shard = bool(shard and devices > 1)
    plans, skipped = plan_columns(spec, done=resumable,
                                  devices=devices if shard else 1)

    report = MatrixReport(skipped_columns=skipped, n_columns=len(plans))
    cells_c, disp_c = _cells_counter(), _dispatch_counter()
    root_key = jax.random.key(spec.seed)

    mesh = None
    ids_sharding = None
    root_dispatch = root_key
    if shard:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ate_replication_causalml_tpu.parallel.mesh import (
            DATA_AXIS,
            make_mesh,
        )

        mesh = make_mesh((DATA_AXIS,))
        ids_sharding = NamedSharding(mesh, P(DATA_AXIS))
        # The AOT executable's key operand is lowered replicated — bind
        # the dispatch copy once, not per batch.
        root_dispatch = jax.device_put(root_key, NamedSharding(mesh, P()))
        log(f"scenario matrix: sharded dispatch over {devices} devices")

    # Resumed cells never reach the engine: collect their rows now, in
    # plan order, so the report carries the full grid either way.
    for plan in plans:
        remaining = set(plan.remaining)
        for rep in range(spec.n_reps):
            cell = cell_row_id(plan.dgp.name, plan.estimator, rep)
            if rep in remaining:
                continue
            rec = ckpt.get(cell)
            if rec is not None:
                report.cells.append(rec)
                report.n_resumed += 1
                cells_c.inc(1, column=plan.name, status="resumed")

    artifacts: list = []
    stages: list = []
    lane = "mesh" if shard else None

    def make_exe_artifact(plan: ColumnPlan) -> str:
        name = f"exe:{plan.name}"
        # Fit and warm are the same compile-once call (the executable
        # cache makes the second invocation a lookup) — bind it once.
        fit = lambda c=None, p=plan: column_executable(
            p.dgp, SCENARIO_ESTIMATORS[p.estimator], p.width,
            column=p.name, ids_sharding=ids_sharding,
        )
        artifacts.append(ArtifactSpec(
            name, fit=fit,
            key=(spec.fingerprint(),
                 column_cache_key(plan.dgp, plan.estimator, plan.width)),
            warm=fit,
            exclusive=lane,
        ))
        return name

    def vmapped_stage(plan: ColumnPlan, bi: int, batch: tuple[int, ...],
                      exe_name: str) -> StageSpec:
        def run(cache, plan=plan, batch=batch, exe_name=exe_name):
            t0 = time.perf_counter()
            exe = cache.get(exe_name)
            # Pad the final partial batch to the column's one executable
            # width with duplicate ids; padded outputs are discarded
            # host-side (never journaled).
            ids = np.asarray(
                [data_cell_id(plan.dgp.name, r) for r in batch]
                + [data_cell_id(plan.dgp.name, batch[0])]
                * (plan.width - len(batch)),
                dtype=np.uint32,
            )
            if ids_sharding is not None:
                from ate_replication_causalml_tpu.parallel import shardio

                ids_dev = shardio.commit(ids, ids_sharding,
                                         artifact=plan.name)
                ate, se, tt = exe(root_dispatch, ids_dev)
                ate, se, tt = shardio.gather_host(
                    (ate, se, tt), artifact=plan.name
                )
            else:
                ate, se, tt = exe(root_key, jax.numpy.asarray(ids))
                ate, se, tt = (np.asarray(ate), np.asarray(se),
                               np.asarray(tt))
            dt = time.perf_counter() - t0
            disp_c.inc(1, column=plan.name, mode="vmapped")
            per_cell = dt / max(1, len(batch))
            return [
                _cell_record(plan, rep, ate[i], se[i], tt[i], per_cell)
                for i, rep in enumerate(batch)
            ]

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(exe_name,),
                         exclusive=lane)

    def sequential_stage(plan: ColumnPlan, bi: int,
                         batch: tuple[int, ...]) -> StageSpec:
        def run(cache, plan=plan, batch=batch):
            import jax.numpy as jnp

            est = SCENARIO_ESTIMATORS[plan.estimator]
            gen = scalar_generate_executable(plan.dgp, column=plan.name)
            salt = np.uint32(estimator_salt(est.name))
            rows = []
            for rep in batch:
                t0 = time.perf_counter()
                cid = jnp.asarray(data_cell_id(plan.dgp.name, rep),
                                  jnp.uint32)
                x, w, y, tau_true, est_key = gen(root_key, cid, salt)
                ate, se = est.fn(plan.dgp, x, w, y, est_key)
                disp_c.inc(1, column=plan.name, mode="sequential")
                rows.append(_cell_record(
                    plan, rep, float(ate), float(se), float(tau_true),
                    time.perf_counter() - t0,
                ))
            return rows

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(),
                         exclusive=lane)

    def wrap_degrade(spec_stage: StageSpec, plan: ColumnPlan,
                     batch: tuple[int, ...]) -> StageSpec:
        inner = spec_stage.run

        def run(cache):
            try:
                return inner(cache)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if spec.fail_policy != "degrade":
                    raise
                err = f"{type(e).__name__}: {e}"
                obs.emit("scenario_batch_failed", status="error",
                         column=plan.name, batch=len(batch), error=err)
                return [_failed_record(plan, rep, err) for rep in batch]

        return dataclasses.replace(spec_stage, run=run)

    # Chaos stage faults (ISSUE 15): plan against the declared batch
    # order up front — the pipeline's PR 4 discipline, so worker
    # completion order can never race the ``times`` budget — and inject
    # INSIDE the degrade wrapper, so a faulted batch becomes failed
    # rows for exactly its cells instead of aborting the matrix.
    inj = chaos.active()
    stage_faults: frozenset[str] = frozenset()
    if inj is not None:
        stage_faults = inj.plan_stage_faults([
            f"{p.name}#b{bi}"
            for p in plans for bi in range(len(p.batches))
        ])

    def wrap_stage_fault(spec_stage: StageSpec) -> StageSpec:
        def run(cache, _name=spec_stage.name):
            # Recorded when RAISED (record_stage_fault), never at plan
            # time — a drained/aborted matrix must not report a fault
            # injected on a batch that was skipped.
            inj.record_stage_fault(_name)
            raise ChaosStageFault(
                f"chaos: injected stage fault on {_name!r}"
            )

        return dataclasses.replace(spec_stage, run=run)

    for plan in plans:
        if not plan.batches:
            continue
        exe_name = None
        if plan.mode == "vmapped":
            exe_name = make_exe_artifact(plan)
        for bi, batch in enumerate(plan.batches):
            st = (
                vmapped_stage(plan, bi, batch, exe_name)
                if plan.mode == "vmapped"
                else sequential_stage(plan, bi, batch)
            )
            if st.name in stage_faults:
                st = wrap_stage_fault(st)
            stages.append(wrap_degrade(st, plan, batch))
            report.n_batches += 1

    def commit(spec_stage: StageSpec, rows: list) -> None:
        for rec in rows:
            ckpt.put(rec)
            report.cells.append(rec)
            if rec.get("status", "ok") == "ok":
                report.n_computed += 1
                cells_c.inc(1, column=rec["column"], status="computed")
            else:
                report.n_failed += 1
                cells_c.inc(1, column=rec["column"], status="failed")
        ok = sum(1 for r in rows if r.get("status", "ok") == "ok")
        log(f"  [{spec_stage.name}] {ok}/{len(rows)} cells ok")

    try:
        with obs.span("run_matrix", columns=len(plans),
                      reps=spec.n_reps, out=outdir or "") as root_sp:
            if stages:
                engine = SweepEngine(
                    artifacts, stages, commit=commit, workers=n_workers,
                    prefetch=prefetch,
                    span_parent=getattr(root_sp, "span_id", None),
                )
                prev_sigterm = None
                if drain_on_sigterm:
                    import signal

                    def _drain(signum, frame, _engine=engine):
                        # The ISSUE 14 drain contract: stop scheduling,
                        # finish in-flight batch stages, commit the
                        # declared-order prefix — run() then returns
                        # and the journal resumes cell-exact.
                        log("SIGTERM: draining scenario matrix "
                            "(in-flight batches will commit)")
                        _engine.request_drain()

                    try:
                        prev_sigterm = signal.signal(signal.SIGTERM, _drain)
                    except ValueError:
                        pass  # not the main thread — no signal wiring
                try:
                    engine.run()
                finally:
                    # Restore the caller's handler: a SIGTERM after this
                    # run must kill the process again, not drain a
                    # finished engine (and pin it in memory) forever.
                    if prev_sigterm is not None:
                        import signal

                        try:
                            signal.signal(signal.SIGTERM, prev_sigterm)
                        except ValueError:
                            pass
                if engine.draining:
                    report.drained = True
    finally:
        report.wall_s = time.monotonic() - t_start
        report.compile_events_delta = (
            obs.compile_event_count() - compiles_before
        )
        # Per-column aggregates over whatever completed — a failed run's
        # partial report is the one that matters for diagnosis.
        by_col: dict[str, list] = {}
        for rec in report.cells:
            by_col.setdefault(rec["column"], []).append(rec)
        report.columns = {
            col: column_aggregates(rows) for col, rows in by_col.items()
        }
        if outdir:
            try:
                obs.atomic_write_json(
                    os.path.join(outdir, "matrix_report.json"),
                    _report_json(spec, report),
                )
                obs.write_run_artifacts(outdir)
            except Exception as e:  # noqa: BLE001 — the export must not
                # replace the run's real exception.
                log(f"matrix export failed: {e!r}")
    log(
        f"scenario matrix: {report.n_computed} computed, "
        f"{report.n_resumed} resumed, {report.n_failed} failed across "
        f"{report.n_columns} columns in {report.wall_s:.1f}s "
        f"(compile events +{report.compile_events_delta:.0f})"
    )
    return report


def _report_json(spec: MatrixSpec, report: MatrixReport) -> dict:
    def _san(v):
        if isinstance(v, float) and not math.isfinite(v):
            return None
        if isinstance(v, dict):
            return {k: _san(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [_san(x) for x in v]
        return v

    return _san({
        "fingerprint": spec.fingerprint(),
        "n_reps": spec.n_reps,
        "batch_width": spec.batch_width,
        "columns": report.columns,
        "skipped_columns": report.skipped_columns,
        "n_computed": report.n_computed,
        "n_resumed": report.n_resumed,
        "n_failed": report.n_failed,
        "wall_s": round(report.wall_s, 3),
        "compile_events_delta": report.compile_events_delta,
        "drained": report.drained,
        "cells": report.cells,
    })


#: per-column compiled DGP-draw program for the sequential (forest)
#: path — the data generation still compiles once per column even when
#: the fit cannot ride a vmap axis.
def scalar_generate_executable(dgp: DGPSpec, column: str = ""):
    import jax
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.scenarios.batched import cached_executable
    from ate_replication_causalml_tpu.scenarios.dgp import generate

    key = ("scenario-gen", dgp.fields())

    def build():
        def gen(root_key, cid, salt):
            data_key = jax.random.fold_in(root_key, cid)
            x, w, y, tau_true = generate(dgp, data_key)
            return x, w, y, tau_true, jax.random.fold_in(data_key, salt)

        return jax.jit(gen).lower(
            jax.random.key(0), jnp.zeros((), jnp.uint32),
            jnp.zeros((), jnp.uint32),
        ).compile()

    return cached_executable(key, build, column or dgp.name, "generate")


def run_scalar_replay(
    spec: MatrixSpec, log: Callable[[str], None] = print
) -> MatrixReport:
    """The sequential scalar baseline for the VMAPPED columns: every
    cell through the per-column SCALAR executable (same cell function,
    unvmapped) — one compile per column, one dispatch per CELL. This is
    the leg the bench's batched-vs-sequential wall/compile comparison
    and the bit-identity assertion run against. Non-vmapped (forest)
    columns have no batched-vs-scalar distinction — their cells already
    dispatch one at a time in ``run_matrix`` — so they are excluded
    here and reported as ``skipped_columns``, keeping ``n_columns``
    consistent with the cells this report actually carries."""
    import jax
    import jax.numpy as jnp

    obs.install_jax_monitoring()
    t0 = time.monotonic()
    compiles_before = obs.compile_event_count()
    plans, skipped = plan_columns(spec)
    skipped = list(skipped) + [
        f"{p.name}: non-vmapped — no scalar-replay leg"
        for p in plans
        if not SCENARIO_ESTIMATORS[p.estimator].vmapped
    ]
    plans = [p for p in plans if SCENARIO_ESTIMATORS[p.estimator].vmapped]
    report = MatrixReport(skipped_columns=skipped, n_columns=len(plans))
    root_key = jax.random.key(spec.seed)
    for plan in plans:
        est = SCENARIO_ESTIMATORS[plan.estimator]
        exe = scalar_executable(plan.dgp, est, column=plan.name)
        for rep in range(spec.n_reps):
            tc = time.perf_counter()
            cid = jnp.asarray(data_cell_id(plan.dgp.name, rep), jnp.uint32)
            try:
                ate, se, tt = exe(root_key, cid)
                rec = _cell_record(
                    plan, rep, float(ate), float(se), float(tt),
                    time.perf_counter() - tc,
                )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # Mirror the batched leg's degrade-don't-abort: the two
                # legs of the bench comparison must account cells the
                # same way or their ok/failed columns contradict.
                if spec.fail_policy != "degrade":
                    raise
                rec = _failed_record(plan, rep, f"{type(e).__name__}: {e}")
            report.cells.append(rec)
            if rec["status"] == "ok":
                report.n_computed += 1
            else:
                report.n_failed += 1
    report.wall_s = time.monotonic() - t0
    report.compile_events_delta = obs.compile_event_count() - compiles_before
    by_col: dict[str, list] = {}
    for rec in report.cells:
        by_col.setdefault(rec["column"], []).append(rec)
    report.columns = {c: column_aggregates(r) for c, r in by_col.items()}
    log(f"scalar replay: {report.n_computed} cells "
        f"({report.n_failed} failed) in {report.wall_s:.1f}s")
    return report


def main(argv: list[str] | None = None) -> MatrixReport:
    import argparse

    ap = argparse.ArgumentParser(
        description="Run a Monte-Carlo scenario matrix (ISSUE 13)")
    ap.add_argument("--out", default=None, help="output directory "
                    "(cells.jsonl + matrix_report.json + telemetry)")
    ap.add_argument("--dgps", default="calibration,hetero_confounded",
                    help=f"comma list from {sorted(STOCK_DGPS)}")
    ap.add_argument("--estimators", default="naive,ipw_logit,aipw_logit")
    ap.add_argument("--reps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sequential", action="store_true")
    ap.add_argument("--workers", type=int, default=None)
    args = ap.parse_args(argv)
    spec = MatrixSpec(
        dgps=tuple(STOCK_DGPS[d] for d in args.dgps.split(",") if d),
        estimators=tuple(e for e in args.estimators.split(",") if e),
        n_reps=default_reps() if args.reps is None else args.reps,
        batch_width=(default_batch_width() if args.batch is None
                     else args.batch),
        seed=args.seed,
    )
    return run_matrix(
        spec, outdir=args.out,
        scheduler="sequential" if args.sequential else None,
        workers=args.workers,
        drain_on_sigterm=True,
    )


if __name__ == "__main__":
    main()

"""Monte-Carlo scenario-matrix runner (ISSUE 13, tentpole part c).

Runs a (DGP × estimator × seed) cell grid through the PR 4
:class:`~..scheduler.SweepEngine`: each scenario COLUMN contributes one
executable artifact (``exe:{column}`` — the AOT-compiled vmapped
fit+estimate program from ``scenarios/batched.py``) plus one stage per
packed replicate batch; commit order is declaration order, so
``cells.jsonl`` is deterministic whatever the worker pool does.

Contracts carried here:

* **O(columns) executables** — all replicate seeds in a column
  dispatch through its single compiled program; the per-column cache
  key (:func:`~.batched.column_cache_key`) means identical columns in
  later runs of the same process compile ZERO times. The bench/tests
  assert ``jax_compiles_total`` deltas against the column count, never
  the cell count.
* **degrade-don't-abort per cell** — a failed batch (or a non-finite
  point estimate) becomes ``status="failed"`` rows for exactly the
  affected cells; the matrix keeps going (``fail_policy="raise"``
  aborts, for debugging).
* **checkpoint/resume at cell granularity** — rows append to
  ``cells.jsonl`` (the pipeline's ``_Checkpoint`` journal, config-
  fingerprinted, torn-line tolerant); a resumed run packs only the
  missing replicates into batches and a fully-completed column
  declares no artifact needs, so it schedules zero fits and zero
  compiles — by construction, the ISSUE 4 resume guarantee.
* **sharded dispatch** (``ATE_TPU_SCENARIO_SHARD=1``, multi-device) —
  the replicate axis itself is row-sharded over the data-axis mesh:
  batch widths pad to the device count (``shardio.pad_to_multiple``,
  the satellite helper lifting the replicated fallback), cell-id
  uploads and result gathers move through the metered PR 8 artifact
  plane, and the collective dispatches serialize through the "mesh"
  lane (the PR 4 rendezvous discipline).

Batch width is deliberately NOT part of the checkpoint fingerprint:
batched columns are bit-identical to their scalar replays (asserted in
tests/test_scenarios.py), so journals resume across widths — exactly
like the sweep's concurrent/sequential modes sharing one journal.

ISSUE 19 rebuilt the hot path so AGGREGATES are the product and rows
are the exception:

* **streaming aggregate mode (the default)** — each batch dispatches
  the column's fused ``aggregate_executable`` (the vmapped cell
  program with the ``batch_stats`` segment-reduce epilogue traced on),
  so a width-W block returns one O(1) stat vector instead of W host
  rows; ``cells.jsonl`` carries ONE record per dispatched block
  (merged stats + the rep list) and resume granularity moves from
  cells to blocks. The checkpoint fingerprint gains an
  ``|mode=scenarios-agg-v1`` suffix, so a rows-mode journal resumed in
  aggregate mode is set aside as ``.stale`` by the header check — and
  the block-resume scan ADDITIONALLY asserts every record's ``schema``
  tag before trusting it (a hand-edited journal whose header lies must
  also set aside, never silently merge).
* **rows mode (``ATE_TPU_SCENARIO_ROWS=1`` or ``MatrixSpec(rows=True)``)**
  — the PR 13 per-cell path, unchanged: one journal record and one
  host row per cell, cell-granular resume, per-cell degrade. The
  campaign workloads and every consumer that reads a cell table pin
  this mode explicitly.

Extend-reps resume works in both modes (replicate count stays out of
the fingerprint); streaming blocks pack rep-contiguous chunks of the
declared width, so a resumed extension reduces the same segments a
straight-through run would — merged aggregates are bit-equal, not just
statistically equal.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Callable, Iterable

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
)
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import ChaosStageFault
from ate_replication_causalml_tpu.scenarios.aggregate import (
    AGG_SCHEMA_TAG,
    AggState,
    N_STATS,
    Z95,
    aggregate_executable,
    fold_rows,
)
from ate_replication_causalml_tpu.scenarios.batched import (
    SCENARIO_ESTIMATORS,
    SCHEMA_TAG,
    batch_mask,
    column_cache_key,
    column_executable,
    pad_ids,
    scalar_executable,
)
from ate_replication_causalml_tpu.scenarios.dgp import (
    DGPSpec,
    STOCK_DGPS,
    data_cell_id,
    estimator_salt,
)

_BATCH_ENV = "ATE_TPU_SCENARIO_BATCH"
_REPS_ENV = "ATE_TPU_SCENARIO_REPS"
_SHARD_ENV = "ATE_TPU_SCENARIO_SHARD"
_ROWS_ENV = "ATE_TPU_SCENARIO_ROWS"

#: 95% normal critical value, matching estimators.base.Z_95 and the
#: device epilogue (scenarios/aggregate.py — one constant, two homes
#: would drift).
_Z95 = Z95


def _env_int(name: str, default: int) -> int:
    """Bad values raise at config time (the ATE_TPU_HIST_MODE /
    ATE_TPU_PREDICT_PACK discipline): a typo'd knob must not silently
    run a multi-hour grid at the default scale."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected a positive integer"
        ) from None
    if value < 1:
        raise ValueError(f"{name}={value}: expected a positive integer")
    return value


def default_batch_width() -> int:
    return _env_int(_BATCH_ENV, 32)


def default_reps() -> int:
    return _env_int(_REPS_ENV, 64)


def _env_shard() -> bool:
    return os.environ.get(_SHARD_ENV, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


def _env_rows() -> bool:
    return os.environ.get(_ROWS_ENV, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


@dataclasses.dataclass(frozen=True)
class MatrixSpec:
    """One scenario matrix: the DGP grid, the estimator set, and the
    replicate/batching policy. ``shard=None`` defers to
    ``ATE_TPU_SCENARIO_SHARD``; ``rows=None`` defers to
    ``ATE_TPU_SCENARIO_ROWS`` (streaming aggregates by default, the
    per-cell row table opt-in)."""

    dgps: tuple[DGPSpec, ...]
    estimators: tuple[str, ...]
    n_reps: int = 64
    batch_width: int = 32
    seed: int = 0
    fail_policy: str = "degrade"
    shard: bool | None = None
    rows: bool | None = None

    def __post_init__(self) -> None:
        if self.fail_policy not in ("degrade", "raise"):
            raise ValueError(
                f"fail_policy must be 'degrade' or 'raise', got "
                f"{self.fail_policy!r}"
            )
        for name in self.estimators:
            if name not in SCENARIO_ESTIMATORS:
                raise ValueError(
                    f"unknown scenario estimator {name!r}; known: "
                    f"{sorted(SCENARIO_ESTIMATORS)}"
                )
        # Names are the column/journal/cell-id namespace: two DGPs (or
        # estimator entries) sharing one silently collide on journal
        # keys and merge their aggregates.
        dgp_names = [d.name for d in self.dgps]
        for seq, what in ((dgp_names, "DGP"), (self.estimators, "estimator")):
            dupes = {x for x in seq if list(seq).count(x) > 1}
            if dupes:
                raise ValueError(
                    f"duplicate {what} name(s) in MatrixSpec: {sorted(dupes)}"
                )

    def resolved_rows(self) -> bool:
        """Whether this run journals per-cell rows (the PR 13 path) or
        streaming aggregate blocks (the ISSUE 19 default)."""
        return _env_rows() if self.rows is None else bool(self.rows)

    def fingerprint(self) -> str:
        """Resume validity: DGP field tuples + estimator set + seed +
        schema tag. Replicate count and batch width are deliberately
        absent — extending reps resumes completed cells/blocks, and
        batched == scalar bit-identity (asserted in-suite) makes widths
        interchangeable over one journal. Aggregate mode appends its
        own schema tag: a rows journal and a block journal can NEVER
        resume each other — the header check sets the other mode's file
        aside as ``.stale``."""
        dgps = ";".join(repr(d.fields()) for d in self.dgps)
        fp = (
            f"{SCHEMA_TAG}|dgps=[{dgps}]|est={list(self.estimators)!r}"
            f"|seed={self.seed}"
        )
        if not self.resolved_rows():
            fp += f"|mode={AGG_SCHEMA_TAG}"
        return fp


def micro_matrix_spec(
    n_reps: int | None = None, batch_width: int | None = None,
    n: int = 384, seed: int = 0, rows: bool | None = None,
) -> MatrixSpec:
    """The canonical micro matrix (2 DGPs × 3 estimators): the
    calibration design (coverage must sit at nominal) and the
    heterogeneous confounded design, through the three vmapped GLM-class
    estimators. Shared by ``bench.py --scenario-matrix`` and the
    acceptance test so the committed SCENARIO_MATRIX.json and the
    tier-1 assertion exercise the same grid."""
    calib = dataclasses.replace(STOCK_DGPS["calibration"], n=n)
    hetero = dataclasses.replace(STOCK_DGPS["hetero_confounded"], n=n)
    return MatrixSpec(
        dgps=(calib, hetero),
        estimators=("naive", "ipw_logit", "aipw_logit"),
        n_reps=default_reps() if n_reps is None else n_reps,
        batch_width=default_batch_width() if batch_width is None else batch_width,
        seed=seed,
        rows=rows,
    )


def column_name(dgp: DGPSpec, estimator: str) -> str:
    return f"{dgp.name}:{estimator}"


def cell_row_id(dgp_name: str, estimator: str, rep: int) -> str:
    """The journal key of one cell — ``_Checkpoint`` keys rows by
    ``method``, so the cell id IS the method field."""
    return f"{dgp_name}:{estimator}:{rep}"


def block_row_id(column: str, batch: tuple[int, ...]) -> str:
    """The journal key of one streaming aggregate block. Blocks in a
    column are disjoint rep sets, so the first rep is a unique suffix
    whatever resume history packed the batch."""
    return f"agg:{column}:r{batch[0]}-{batch[-1]}"


@dataclasses.dataclass(frozen=True)
class ColumnPlan:
    """One scheduled column: which replicates still need computing and
    how they pack into fixed-width batches (the last batch pads to the
    declared width with duplicate ids whose outputs are discarded — one
    executable shape per column, the compile-count contract)."""

    name: str
    dgp: DGPSpec
    estimator: str
    width: int
    mode: str                      # "vmapped" | "sequential"
    remaining: tuple[int, ...]
    batches: tuple[tuple[int, ...], ...]


def plan_columns(
    spec: MatrixSpec, done: Callable[[str], bool] = lambda _cell: False,
    devices: int = 1,
) -> tuple[list[ColumnPlan], list[str]]:
    """Pure cell-batching planner: pack each column's not-yet-done
    replicate seeds into fixed-width batches. Non-vmappable engines
    (forest-class) pack at width 1 — each cell dispatches through the
    model's own machinery. Sharded runs pad the width up to the device
    count. Returns ``(plans, skipped)`` where ``skipped`` names
    (DGP, estimator) pairs the estimator declared inapplicable
    (e.g. OLS on a p≫n design)."""
    plans: list[ColumnPlan] = []
    skipped: list[str] = []
    shard = _env_shard() if spec.shard is None else spec.shard
    for dgp in spec.dgps:
        for est_name in spec.estimators:
            est = SCENARIO_ESTIMATORS[est_name]
            col = column_name(dgp, est_name)
            if not est.applicable(dgp):
                skipped.append(col)
                continue
            width = min(spec.batch_width, spec.n_reps) if est.vmapped else 1
            if shard and est.vmapped and devices > 1:
                from ate_replication_causalml_tpu.parallel.shardio import (
                    pad_to_multiple,
                )

                width = pad_to_multiple(width, devices)
            remaining = tuple(
                r for r in range(spec.n_reps)
                if not done(cell_row_id(dgp.name, est_name, r))
            )
            batches = tuple(
                remaining[i:i + width]
                for i in range(0, len(remaining), width)
            )
            plans.append(ColumnPlan(
                name=col, dgp=dgp, estimator=est_name, width=width,
                mode="vmapped" if est.vmapped else "sequential",
                remaining=remaining, batches=batches,
            ))
    return plans, skipped


# ── aggregates ────────────────────────────────────────────────────────

#: Shape of the per-column error sketch (ISSUE 16). Estimation errors
#: ``ate - tau_true`` live well inside ±8 for every DGP in the matrix;
#: anything outside lands in the sketch's explicit tails, so mass is
#: conserved either way. 8 bins matches the serving stat-health plane's
#: default, so offline and served sketches stay merge-compatible.
_ERROR_SKETCH_RANGE = (-8.0, 8.0)
_ERROR_SKETCH_BINS = 8


def column_aggregates(rows: Iterable[dict], nominal: float = 0.95) -> dict:
    """Per-column Monte-Carlo summaries from cell rows (pure, jax-free,
    unit-tested): coverage of the per-replicate truth by the 95% CI,
    bias / RMSE of the point estimate, power of the |ate|/se > z test
    against τ=0, and the binomial MC standard errors the validator's
    within-MC-error bands are built from. Failed cells count into
    ``n_failed`` and nothing else; no-SE estimators (LASSO point rows)
    report ``coverage=None``/``power=None``."""
    rows = list(rows)
    ok = [
        r for r in rows
        if r.get("status", "ok") == "ok"
        and isinstance(r.get("ate"), (int, float))
        and math.isfinite(r["ate"])
    ]
    with_se = [
        r for r in ok
        if isinstance(r.get("se"), (int, float)) and math.isfinite(r["se"])
    ]
    out: dict = {
        "n_cells": len(rows),
        "n_ok": len(ok),
        "n_failed": len(rows) - len(ok),
        "coverage": None,
        "power": None,
        "bias": None,
        "rmse": None,
        "coverage_mc_se": None,
        "nominal": nominal,
    }
    if ok:
        errs = [r["ate"] - r["tau_true"] for r in ok]
        out["bias"] = sum(errs) / len(errs)
        out["rmse"] = math.sqrt(sum(e * e for e in errs) / len(errs))
        out["mean_tau_true"] = sum(r["tau_true"] for r in ok) / len(ok)
    if with_se:
        covered = sum(
            1 for r in with_se
            if r["lower_ci"] <= r["tau_true"] <= r["upper_ci"]
        )
        rejected = sum(
            1 for r in with_se if abs(r["ate"]) > _Z95 * r["se"]
        )
        n = len(with_se)
        cov = covered / n
        out["coverage"] = cov
        out["power"] = rejected / n
        # Binomial MC standard error at the NOMINAL rate — the
        # validator's band is nominal ± z·this (using the nominal p
        # keeps the band honest when the observed rate is degenerate).
        out["coverage_mc_se"] = math.sqrt(nominal * (1.0 - nominal) / n)
    # Shared-sketch aggregates (ISSUE 16): the per-column error
    # distribution and CI-coverage reliability expressed through the
    # SAME mergeable sketch types the serving statistical-health plane
    # streams, so offline matrix columns and served traffic report one
    # schema — and sketches from sharded matrix runs merge
    # associatively, exactly like fleet-wide serving sketches.
    err_sketch = FixedBinSketch(*_ERROR_SKETCH_RANGE, _ERROR_SKETCH_BINS)
    if ok:
        err_sketch.update(errs)
    cov_sketch = CalibrationSketch()
    if with_se:
        cov_sketch.update(
            [nominal] * len(with_se),
            [r["lower_ci"] <= r["tau_true"] <= r["upper_ci"]
             for r in with_se],
        )
    out["sketches"] = {
        "error": err_sketch.to_dict(),
        "coverage": cov_sketch.to_dict(),
    }
    return out


def compare_cells(cells_a: Iterable[dict], cells_b: Iterable[dict]) -> dict:
    """Per-column batched-vs-scalar comparison (bench + tests): for each
    column the max deviation of ate/se/tau_true in f32 ULPS at the
    compared magnitude (NaN == NaN). Returns ``{"columns": {col:
    max_ulp}, "max_ulp": float, "exact_columns": [cols at 0 ulp],
    "missing": [cell ids present on one side only]}``."""
    am = {r["method"]: r for r in cells_a}
    bm = {r["method"]: r for r in cells_b}
    missing = sorted(set(am) ^ set(bm))
    per_col: dict[str, float] = {}
    for cell in set(am) & set(bm):
        ra, rb = am[cell], bm[cell]
        worst = per_col.get(ra["column"], 0.0)
        for field in ("ate", "se", "tau_true"):
            a, b = ra.get(field), rb.get(field)
            a_nan = not _finite(a)
            b_nan = not _finite(b)
            if a_nan and b_nan:
                continue
            if a_nan != b_nan:
                worst = float("inf")
                continue
            if a == b:
                continue
            scale = float(np.spacing(np.float32(max(abs(a), abs(b)))))
            worst = max(worst, abs(a - b) / scale)
        per_col[ra["column"]] = worst
    finite_ulps = [u for u in per_col.values() if math.isfinite(u)]
    return {
        "columns": per_col,
        "max_ulp": (float("inf") if len(finite_ulps) < len(per_col)
                    else max(finite_ulps, default=0.0)),
        "exact_columns": sorted(c for c, u in per_col.items() if u == 0.0),
        "missing": missing,
    }


# ── the runner ────────────────────────────────────────────────────────


@dataclasses.dataclass
class MatrixReport:
    """Everything one matrix run produces: per-cell rows (notebook
    order), per-column aggregates, and the perf evidence (wall seconds,
    compile-event delta, executables compiled) the bench record and the
    in-suite O(columns) assertion read."""

    cells: list = dataclasses.field(default_factory=list)
    columns: dict = dataclasses.field(default_factory=dict)
    skipped_columns: list = dataclasses.field(default_factory=list)
    n_resumed: int = 0
    n_computed: int = 0
    n_failed: int = 0
    wall_s: float = 0.0
    compile_events_delta: float = 0.0
    n_columns: int = 0
    n_batches: int = 0
    #: a SIGTERM drain (ISSUE 14) cut this run short: the journal holds
    #: the committed prefix and a rerun resumes cell-exact.
    drained: bool = False
    #: "aggregate" (streaming, the default) or "rows" (per-cell table).
    mode: str = "rows"
    #: streaming mode only: journaled block records this run committed
    #: (``cells`` stays empty — the cell table is never materialized)
    #: and the merged per-column sufficient statistics.
    n_blocks: int = 0
    states: dict = dataclasses.field(default_factory=dict)


def _cells_counter():
    return obs.counter(
        "scenario_cells_total",
        "scenario-matrix cells by column and computed/resumed/failed status",
    )


def _dispatch_counter():
    return obs.counter(
        "scenario_batch_dispatch_total",
        "scenario-matrix batch dispatches by column and vmapped/sequential mode",
    )


def _blocks_counter():
    return obs.counter(
        "scenario_aggregate_blocks_total",
        "streaming aggregate blocks by column and "
        "computed/resumed/failed status",
    )


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


def _cell_record(
    plan: ColumnPlan, rep: int, ate: float, se: float, tau_true: float,
    seconds: float,
) -> dict:
    ate, se, tau_true = float(ate), float(se), float(tau_true)
    status = "ok" if math.isfinite(ate) else "failed"
    rec = {
        "method": cell_row_id(plan.dgp.name, plan.estimator, rep),
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "rep": rep,
        "ate": ate,
        "se": se,
        "lower_ci": ate - _Z95 * se if math.isfinite(se) else ate,
        "upper_ci": ate + _Z95 * se if math.isfinite(se) else ate,
        "tau_true": tau_true,
        "status": status,
        "seconds": round(seconds, 6),
    }
    if status == "failed":
        rec["error"] = f"NonFiniteResult: ate={ate!r}"
    return rec


def _failed_record(plan: ColumnPlan, rep: int, error: str) -> dict:
    nan = float("nan")
    return {
        "method": cell_row_id(plan.dgp.name, plan.estimator, rep),
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "rep": rep,
        "ate": nan, "se": nan, "lower_ci": nan, "upper_ci": nan,
        "tau_true": nan,
        "status": "failed",
        "error": error,
        "seconds": 0.0,
    }


# ── streaming block records ──────────────────────────────────────────


def _pack_reps(batch: tuple[int, ...]) -> list[list[int]]:
    """Run-length ``[[lo, hi], ...]`` encoding of a block's rep set —
    the journal-bytes-O(blocks) guarantee depends on this: a fresh
    block is one contiguous run whatever its width, so the record costs
    O(1) bytes, not O(width). Resume holes can fragment a block into a
    few runs; that stays O(runs), never O(cells)."""
    runs: list[list[int]] = []
    for r in batch:
        if runs and r == runs[-1][1] + 1:
            runs[-1][1] = r
        else:
            runs.append([r, r])
    return runs


def _unpack_reps(packed: list) -> list[int]:
    return [r for lo, hi in packed for r in range(lo, hi + 1)]


def _packed_count(packed: list) -> int:
    return sum(hi - lo + 1 for lo, hi in packed)


def _block_record(plan: ColumnPlan, batch: tuple[int, ...],
                  state: AggState, seconds: float) -> dict:
    return {
        "method": block_row_id(plan.name, batch),
        "schema": AGG_SCHEMA_TAG,
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "reps": _pack_reps(batch),
        "width": plan.width,
        "status": "ok",
        "stats": list(state.stats),
        "seconds": round(seconds, 6),
    }


def _failed_block_record(plan: ColumnPlan, batch: tuple[int, ...],
                         error: str) -> dict:
    return {
        "method": block_row_id(plan.name, batch),
        "schema": AGG_SCHEMA_TAG,
        "column": plan.name,
        "dgp": plan.dgp.name,
        "estimator": plan.estimator,
        "reps": _pack_reps(batch),
        "width": plan.width,
        "status": "failed",
        "error": error,
        "seconds": 0.0,
    }


def _block_resumable(rec: dict) -> bool:
    """A block record the resume scan may trust: schema-tagged, status
    ok, a full finite stat vector, and a well-formed packed rep set.
    Anything else (a failed block, a torn-then-hand-fixed record)
    recomputes."""
    if rec.get("schema") != AGG_SCHEMA_TAG:
        return False
    if rec.get("status", "ok") != "ok":
        return False
    stats = rec.get("stats")
    if not isinstance(stats, list) or len(stats) != N_STATS:
        return False
    if not all(_finite(v) for v in stats):
        return False
    reps = rec.get("reps")
    return (
        isinstance(reps, list) and bool(reps)
        and all(
            isinstance(run, list) and len(run) == 2
            and all(isinstance(r, int) for r in run)
            and run[0] <= run[1]
            for run in reps
        )
    )


def _scan_blocks(ckpt, fingerprint: str, log: Callable[[str], None]) -> dict:
    """Index a block journal's resumable records by column, ASSERTING
    every non-header record's schema tag first (the ISSUE 19 small
    fix): the fingerprint header already stales a rows-mode journal,
    but a hand-edited file whose header lies must ALSO be set aside as
    ``.stale`` — a rows record silently merged as a block would corrupt
    every aggregate downstream. Returns ``{column: {rep: record}}``;
    on a tag violation the journal is renamed and the scan restarts
    empty."""
    from ate_replication_causalml_tpu.pipeline import _unused_stale_path

    foreign = [
        m for m, rec in ckpt.done.items()
        if rec.get("schema") != AGG_SCHEMA_TAG
    ]
    if foreign:
        if ckpt.path and os.path.exists(ckpt.path):
            stale = _unused_stale_path(ckpt.path)
            os.replace(ckpt.path, stale)
            log(
                f"checkpoint {ckpt.path}: {len(foreign)} record(s) "
                f"without the {AGG_SCHEMA_TAG!r} schema tag (e.g. "
                f"{foreign[0]!r}) — not a block journal; moved to "
                f"{stale} and starting fresh"
            )
            # Re-seed the header the rename removed: the journal file
            # must stay self-describing for the NEXT resume.
            obs.atomic_write_text(ckpt.path, json.dumps(
                {"method": "__config__", "fingerprint": fingerprint}
            ) + "\n")
        ckpt.done.clear()
        return {}
    by_column: dict[str, dict[int, dict]] = {}
    for rec in ckpt.done.values():
        if not _block_resumable(rec):
            continue
        col = by_column.setdefault(rec["column"], {})
        for rep in _unpack_reps(rec["reps"]):
            col[rep] = rec
    return by_column


def run_matrix(
    spec: MatrixSpec,
    outdir: str | None = None,
    workers: int | None = None,
    scheduler: str | None = None,
    prefetch: bool | None = None,
    log: Callable[[str], None] = print,
    drain_on_sigterm: bool = False,
) -> MatrixReport:
    """Run the matrix through the real SweepEngine. See module
    docstring for the contracts; telemetry exports to ``outdir`` beside
    ``cells.jsonl`` and ``matrix_report.json``. With
    ``drain_on_sigterm`` (the CLI default), SIGTERM gracefully drains
    the engine (ISSUE 14): in-flight batch stages complete, their
    rows/blocks commit in declared order through the checkpoint
    journal, the process exits 0 — and a resumed run picks up
    cell-exact (rows mode) or block-exact (streaming mode) where the
    drain stopped, exactly like the SIGKILL crash-resume contract but
    without losing the in-flight batches.

    Streaming aggregate mode is the default (ISSUE 19); rows mode —
    ``MatrixSpec(rows=True)`` or ``ATE_TPU_SCENARIO_ROWS=1`` —
    materializes the PR 13 per-cell table."""
    if spec.resolved_rows():
        return _run_matrix_rows(
            spec, outdir=outdir, workers=workers, scheduler=scheduler,
            prefetch=prefetch, log=log, drain_on_sigterm=drain_on_sigterm,
        )
    return _run_matrix_aggregate(
        spec, outdir=outdir, workers=workers, scheduler=scheduler,
        prefetch=prefetch, log=log, drain_on_sigterm=drain_on_sigterm,
    )


def _install_drain(engine, log: Callable[[str], None]):
    """SIGTERM → engine drain (ISSUE 14), returning a restore thunk.
    Restoring matters: a SIGTERM after the run must kill the process
    again, not drain a finished engine (and pin it in memory) forever."""
    import signal

    def _drain(signum, frame, _engine=engine):
        log("SIGTERM: draining scenario matrix "
            "(in-flight batches will commit)")
        _engine.request_drain()

    try:
        prev = signal.signal(signal.SIGTERM, _drain)
    except ValueError:
        return lambda: None  # not the main thread — no signal wiring

    def restore():
        try:
            signal.signal(signal.SIGTERM, prev)
        except ValueError:
            pass

    return restore


def _run_matrix_rows(
    spec: MatrixSpec,
    outdir: str | None = None,
    workers: int | None = None,
    scheduler: str | None = None,
    prefetch: bool | None = None,
    log: Callable[[str], None] = print,
    drain_on_sigterm: bool = False,
) -> MatrixReport:
    """The PR 13 per-cell path: one journal record and one host row per
    cell, cell-granular resume, per-cell degrade."""
    import jax

    from ate_replication_causalml_tpu.pipeline import (
        _Checkpoint,
        _resolve_scheduler,
        _row_resumable,
    )
    from ate_replication_causalml_tpu.scheduler import (
        ArtifactSpec,
        StageSpec,
        SweepEngine,
    )

    obs.install_jax_monitoring()
    n_workers = _resolve_scheduler(scheduler, workers, log)
    t_start = time.monotonic()
    compiles_before = obs.compile_event_count()
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    ckpt = _Checkpoint(
        os.path.join(outdir, "cells.jsonl") if outdir else None,
        spec.fingerprint(), log=log,
    )

    def resumable(cell: str) -> bool:
        rec = ckpt.get(cell)
        return rec is not None and _row_resumable(rec)[0]

    shard = _env_shard() if spec.shard is None else spec.shard
    devices = jax.device_count()
    shard = bool(shard and devices > 1)
    plans, skipped = plan_columns(spec, done=resumable,
                                  devices=devices if shard else 1)

    report = MatrixReport(skipped_columns=skipped, n_columns=len(plans))
    cells_c, disp_c = _cells_counter(), _dispatch_counter()
    root_key = jax.random.key(spec.seed)

    mesh = None
    ids_sharding = None
    root_dispatch = root_key
    if shard:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ate_replication_causalml_tpu.parallel.mesh import (
            DATA_AXIS,
            make_mesh,
        )

        mesh = make_mesh((DATA_AXIS,))
        ids_sharding = NamedSharding(mesh, P(DATA_AXIS))
        # The AOT executable's key operand is lowered replicated — bind
        # the dispatch copy once, not per batch.
        root_dispatch = jax.device_put(root_key, NamedSharding(mesh, P()))
        log(f"scenario matrix: sharded dispatch over {devices} devices")

    # Resumed cells never reach the engine: collect their rows now, in
    # plan order, so the report carries the full grid either way.
    for plan in plans:
        remaining = set(plan.remaining)
        for rep in range(spec.n_reps):
            cell = cell_row_id(plan.dgp.name, plan.estimator, rep)
            if rep in remaining:
                continue
            rec = ckpt.get(cell)
            if rec is not None:
                report.cells.append(rec)
                report.n_resumed += 1
                cells_c.inc(1, column=plan.name, status="resumed")

    artifacts: list = []
    stages: list = []
    lane = "mesh" if shard else None

    def make_exe_artifact(plan: ColumnPlan) -> str:
        name = f"exe:{plan.name}"
        # Fit and warm are the same compile-once call (the executable
        # cache makes the second invocation a lookup) — bind it once.
        fit = lambda c=None, p=plan: column_executable(
            p.dgp, SCENARIO_ESTIMATORS[p.estimator], p.width,
            column=p.name, ids_sharding=ids_sharding,
        )
        artifacts.append(ArtifactSpec(
            name, fit=fit,
            key=(spec.fingerprint(),
                 column_cache_key(plan.dgp, plan.estimator, plan.width)),
            warm=fit,
            exclusive=lane,
        ))
        return name

    def vmapped_stage(plan: ColumnPlan, bi: int, batch: tuple[int, ...],
                      exe_name: str) -> StageSpec:
        def run(cache, plan=plan, batch=batch, exe_name=exe_name):
            t0 = time.perf_counter()
            exe = cache.get(exe_name)
            # Pad the final partial batch to the column's one executable
            # width with duplicate ids; padded outputs are discarded
            # host-side (never journaled).
            ids = pad_ids(plan.dgp.name, batch, plan.width)
            if ids_sharding is not None:
                from ate_replication_causalml_tpu.parallel import shardio

                ids_dev = shardio.commit(ids, ids_sharding,
                                         artifact=plan.name)
                ate, se, tt = exe(root_dispatch, ids_dev)
                ate, se, tt = shardio.gather_host(
                    (ate, se, tt), artifact=plan.name
                )
            else:
                ate, se, tt = exe(root_key, jax.numpy.asarray(ids))
                ate, se, tt = (np.asarray(ate), np.asarray(se),
                               np.asarray(tt))
            dt = time.perf_counter() - t0
            disp_c.inc(1, column=plan.name, mode="vmapped")
            per_cell = dt / max(1, len(batch))
            return [
                _cell_record(plan, rep, ate[i], se[i], tt[i], per_cell)
                for i, rep in enumerate(batch)
            ]

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(exe_name,),
                         exclusive=lane)

    def sequential_stage(plan: ColumnPlan, bi: int,
                         batch: tuple[int, ...]) -> StageSpec:
        def run(cache, plan=plan, batch=batch):
            import jax.numpy as jnp

            est = SCENARIO_ESTIMATORS[plan.estimator]
            gen = scalar_generate_executable(plan.dgp, column=plan.name)
            salt = np.uint32(estimator_salt(est.name))
            rows = []
            for rep in batch:
                t0 = time.perf_counter()
                cid = jnp.asarray(data_cell_id(plan.dgp.name, rep),
                                  jnp.uint32)
                x, w, y, tau_true, est_key = gen(root_key, cid, salt)
                ate, se = est.fn(plan.dgp, x, w, y, est_key)
                disp_c.inc(1, column=plan.name, mode="sequential")
                rows.append(_cell_record(
                    plan, rep, float(ate), float(se), float(tau_true),
                    time.perf_counter() - t0,
                ))
            return rows

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(),
                         exclusive=lane)

    def wrap_degrade(spec_stage: StageSpec, plan: ColumnPlan,
                     batch: tuple[int, ...]) -> StageSpec:
        inner = spec_stage.run

        def run(cache):
            try:
                return inner(cache)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if spec.fail_policy != "degrade":
                    raise
                err = f"{type(e).__name__}: {e}"
                obs.emit("scenario_batch_failed", status="error",
                         column=plan.name, batch=len(batch), error=err)
                return [_failed_record(plan, rep, err) for rep in batch]

        return dataclasses.replace(spec_stage, run=run)

    # Chaos stage faults (ISSUE 15): plan against the declared batch
    # order up front — the pipeline's PR 4 discipline, so worker
    # completion order can never race the ``times`` budget — and inject
    # INSIDE the degrade wrapper, so a faulted batch becomes failed
    # rows for exactly its cells instead of aborting the matrix.
    inj = chaos.active()
    stage_faults: frozenset[str] = frozenset()
    if inj is not None:
        stage_faults = inj.plan_stage_faults([
            f"{p.name}#b{bi}"
            for p in plans for bi in range(len(p.batches))
        ])

    def wrap_stage_fault(spec_stage: StageSpec) -> StageSpec:
        def run(cache, _name=spec_stage.name):
            # Recorded when RAISED (record_stage_fault), never at plan
            # time — a drained/aborted matrix must not report a fault
            # injected on a batch that was skipped.
            inj.record_stage_fault(_name)
            raise ChaosStageFault(
                f"chaos: injected stage fault on {_name!r}"
            )

        return dataclasses.replace(spec_stage, run=run)

    for plan in plans:
        if not plan.batches:
            continue
        exe_name = None
        if plan.mode == "vmapped":
            exe_name = make_exe_artifact(plan)
        for bi, batch in enumerate(plan.batches):
            st = (
                vmapped_stage(plan, bi, batch, exe_name)
                if plan.mode == "vmapped"
                else sequential_stage(plan, bi, batch)
            )
            if st.name in stage_faults:
                st = wrap_stage_fault(st)
            stages.append(wrap_degrade(st, plan, batch))
            report.n_batches += 1

    def commit(spec_stage: StageSpec, rows: list) -> None:
        for rec in rows:
            ckpt.put(rec)
            report.cells.append(rec)
            if rec.get("status", "ok") == "ok":
                report.n_computed += 1
                cells_c.inc(1, column=rec["column"], status="computed")
            else:
                report.n_failed += 1
                cells_c.inc(1, column=rec["column"], status="failed")
        ok = sum(1 for r in rows if r.get("status", "ok") == "ok")
        log(f"  [{spec_stage.name}] {ok}/{len(rows)} cells ok")

    try:
        with obs.span("run_matrix", columns=len(plans),
                      reps=spec.n_reps, out=outdir or "") as root_sp:
            if stages:
                engine = SweepEngine(
                    artifacts, stages, commit=commit, workers=n_workers,
                    prefetch=prefetch,
                    span_parent=getattr(root_sp, "span_id", None),
                )
                restore = (_install_drain(engine, log)
                           if drain_on_sigterm else (lambda: None))
                try:
                    engine.run()
                finally:
                    restore()
                if engine.draining:
                    report.drained = True
    finally:
        report.wall_s = time.monotonic() - t_start
        report.compile_events_delta = (
            obs.compile_event_count() - compiles_before
        )
        # Per-column aggregates over whatever completed — a failed run's
        # partial report is the one that matters for diagnosis.
        by_col: dict[str, list] = {}
        for rec in report.cells:
            by_col.setdefault(rec["column"], []).append(rec)
        report.columns = {
            col: column_aggregates(rows) for col, rows in by_col.items()
        }
        if outdir:
            try:
                obs.atomic_write_json(
                    os.path.join(outdir, "matrix_report.json"),
                    _report_json(spec, report),
                )
                obs.write_run_artifacts(outdir)
            except Exception as e:  # noqa: BLE001 — the export must not
                # replace the run's real exception.
                log(f"matrix export failed: {e!r}")
    log(
        f"scenario matrix: {report.n_computed} computed, "
        f"{report.n_resumed} resumed, {report.n_failed} failed across "
        f"{report.n_columns} columns in {report.wall_s:.1f}s "
        f"(compile events +{report.compile_events_delta:.0f})"
    )
    return report


def _run_matrix_aggregate(
    spec: MatrixSpec,
    outdir: str | None = None,
    workers: int | None = None,
    scheduler: str | None = None,
    prefetch: bool | None = None,
    log: Callable[[str], None] = print,
    drain_on_sigterm: bool = False,
) -> MatrixReport:
    """The ISSUE 19 streaming path: each batch dispatches the column's
    fused aggregate executable and journals ONE block record (merged
    stat vector + rep list); ``report.cells`` stays empty, resume is
    block-granular, and a failed block degrades to a failed-block
    record for exactly its reps.

    Two deliberate divergences from rows mode, both consequences of the
    block being the atomic unit:

    * a resumed block with failed CELLS inside it is still complete —
      cell failure in streaming mode means a non-finite estimate folded
      into ``n_failed`` inside the stats, and recomputing the same
      deterministic program would fold the same value;
    * a failed BLOCK (stage exception) journals with no stats and is
      not resumable — the whole block recomputes on the next run.
    """
    import jax

    from ate_replication_causalml_tpu.pipeline import (
        _Checkpoint,
        _resolve_scheduler,
    )
    from ate_replication_causalml_tpu.scheduler import (
        ArtifactSpec,
        StageSpec,
        SweepEngine,
    )

    obs.install_jax_monitoring()
    n_workers = _resolve_scheduler(scheduler, workers, log)
    t_start = time.monotonic()
    compiles_before = obs.compile_event_count()
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    fingerprint = spec.fingerprint()
    ckpt = _Checkpoint(
        os.path.join(outdir, "cells.jsonl") if outdir else None,
        fingerprint, log=log,
    )
    blocks_by_col = _scan_blocks(ckpt, fingerprint, log)
    trusted, covered = _trusted_blocks(blocks_by_col, spec.n_reps)

    def resumed(cell: str) -> bool:
        col, _, rep = cell.rpartition(":")
        return int(rep) in covered.get(col, ())

    shard = _env_shard() if spec.shard is None else spec.shard
    devices = jax.device_count()
    shard = bool(shard and devices > 1)
    plans, skipped = plan_columns(spec, done=resumed,
                                  devices=devices if shard else 1)
    # Sequential (non-vmapped) columns plan at width 1 for dispatch, but
    # a width-1 BLOCK would journal one record per cell — exactly the
    # O(cells) cost this mode removes. Re-pack their remaining reps into
    # batch_width chunks: each chunk computes its cells eagerly and
    # folds host-side through the same batch_stats epilogue.
    seq_width = min(spec.batch_width, spec.n_reps)
    plans = [
        p if p.mode == "vmapped" else dataclasses.replace(
            p, width=seq_width,
            batches=tuple(
                p.remaining[i:i + seq_width]
                for i in range(0, len(p.remaining), seq_width)
            ),
        )
        for p in plans
    ]

    report = MatrixReport(skipped_columns=skipped, n_columns=len(plans),
                          mode="aggregate")
    cells_c, disp_c = _cells_counter(), _dispatch_counter()
    blocks_c = _blocks_counter()
    failed_by_col: dict[str, int] = {}
    root_key = jax.random.key(spec.seed)

    ids_sharding = None
    root_dispatch = root_key
    if shard:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ate_replication_causalml_tpu.parallel.mesh import (
            DATA_AXIS,
            make_mesh,
        )

        mesh = make_mesh((DATA_AXIS,))
        ids_sharding = NamedSharding(mesh, P(DATA_AXIS))
        root_dispatch = jax.device_put(root_key, NamedSharding(mesh, P()))
        log(f"scenario matrix: sharded dispatch over {devices} devices")

    # Resumed blocks never reach the engine: merge their states now, in
    # plan order, so resumed and straight-through reports agree.
    for plan in plans:
        for rec in trusted.get(plan.name, ()):
            state = AggState.from_array(np.asarray(rec["stats"]))
            report.states[plan.name] = (
                report.states.get(plan.name, AggState.zero()).merge(state)
            )
            n = _packed_count(rec["reps"])
            report.n_resumed += n
            cells_c.inc(n, column=plan.name, status="resumed")
            blocks_c.inc(1, column=plan.name, status="resumed")

    artifacts: list = []
    stages: list = []
    lane = "mesh" if shard else None

    def make_exe_artifact(plan: ColumnPlan) -> str:
        name = f"exe:{plan.name}"
        fit = lambda c=None, p=plan: aggregate_executable(
            p.dgp, SCENARIO_ESTIMATORS[p.estimator], p.width,
            column=p.name, ids_sharding=ids_sharding,
        )
        artifacts.append(ArtifactSpec(
            name, fit=fit,
            key=(fingerprint,
                 column_cache_key(plan.dgp, plan.estimator, plan.width),
                 "agg"),
            warm=fit,
            exclusive=lane,
        ))
        return name

    def vmapped_block(plan: ColumnPlan, bi: int, batch: tuple[int, ...],
                      exe_name: str) -> StageSpec:
        def run(cache, plan=plan, batch=batch, exe_name=exe_name):
            t0 = time.perf_counter()
            exe = cache.get(exe_name)
            ids = pad_ids(plan.dgp.name, batch, plan.width)
            mask = batch_mask(batch, plan.width, plan.dgp.dtype)
            if ids_sharding is not None:
                from ate_replication_causalml_tpu.parallel import shardio

                ids_dev = shardio.commit(ids, ids_sharding,
                                         artifact=plan.name)
                mask_dev = shardio.commit(mask, ids_sharding,
                                          artifact=plan.name)
                stats = exe(root_dispatch, ids_dev, mask_dev)
                stats = shardio.gather_host(stats, artifact=plan.name)
            else:
                stats = np.asarray(exe(
                    root_key, jax.numpy.asarray(ids),
                    jax.numpy.asarray(mask),
                ))
            disp_c.inc(1, column=plan.name, mode="vmapped")
            state = AggState.from_array(np.asarray(stats))
            return [_block_record(plan, batch, state,
                                  time.perf_counter() - t0)]

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(exe_name,),
                         exclusive=lane)

    def sequential_block(plan: ColumnPlan, bi: int,
                         batch: tuple[int, ...]) -> StageSpec:
        def run(cache, plan=plan, batch=batch):
            import jax.numpy as jnp

            est = SCENARIO_ESTIMATORS[plan.estimator]
            gen = scalar_generate_executable(plan.dgp, column=plan.name)
            salt = np.uint32(estimator_salt(est.name))
            t0 = time.perf_counter()
            triples = []
            for rep in batch:
                cid = jnp.asarray(data_cell_id(plan.dgp.name, rep),
                                  jnp.uint32)
                x, w, y, tau_true, est_key = gen(root_key, cid, salt)
                ate, se = est.fn(plan.dgp, x, w, y, est_key)
                disp_c.inc(1, column=plan.name, mode="sequential")
                triples.append((float(ate), float(se), float(tau_true)))
            state = fold_rows(triples, plan.width, plan.dgp.dtype)
            return [_block_record(plan, batch, state,
                                  time.perf_counter() - t0)]

        return StageSpec(f"{plan.name}#b{bi}", run, needs=(),
                         exclusive=lane)

    def wrap_degrade(spec_stage: StageSpec, plan: ColumnPlan,
                     batch: tuple[int, ...]) -> StageSpec:
        inner = spec_stage.run

        def run(cache):
            try:
                return inner(cache)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if spec.fail_policy != "degrade":
                    raise
                err = f"{type(e).__name__}: {e}"
                obs.emit("scenario_batch_failed", status="error",
                         column=plan.name, batch=len(batch), error=err)
                return [_failed_block_record(plan, batch, err)]

        return dataclasses.replace(spec_stage, run=run)

    inj = chaos.active()
    stage_faults: frozenset[str] = frozenset()
    if inj is not None:
        stage_faults = inj.plan_stage_faults([
            f"{p.name}#b{bi}"
            for p in plans for bi in range(len(p.batches))
        ])

    def wrap_stage_fault(spec_stage: StageSpec) -> StageSpec:
        def run(cache, _name=spec_stage.name):
            inj.record_stage_fault(_name)
            raise ChaosStageFault(
                f"chaos: injected stage fault on {_name!r}"
            )

        return dataclasses.replace(spec_stage, run=run)

    for plan in plans:
        if not plan.batches:
            continue
        exe_name = None
        if plan.mode == "vmapped":
            exe_name = make_exe_artifact(plan)
        for bi, batch in enumerate(plan.batches):
            st = (
                vmapped_block(plan, bi, batch, exe_name)
                if plan.mode == "vmapped"
                else sequential_block(plan, bi, batch)
            )
            if st.name in stage_faults:
                st = wrap_stage_fault(st)
            stages.append(wrap_degrade(st, plan, batch))
            report.n_batches += 1

    def commit(spec_stage: StageSpec, recs: list) -> None:
        for rec in recs:
            ckpt.put(rec)
            report.n_blocks += 1
            col = rec["column"]
            n = _packed_count(rec["reps"])
            if rec.get("status", "ok") == "ok":
                state = AggState.from_array(np.asarray(rec["stats"]))
                report.states[col] = (
                    report.states.get(col, AggState.zero()).merge(state)
                )
                report.n_computed += n
                cells_c.inc(n, column=col, status="computed")
                blocks_c.inc(1, column=col, status="computed")
                log(f"  [{spec_stage.name}] block ok ({n} cells)")
            else:
                failed_by_col[col] = failed_by_col.get(col, 0) + n
                report.n_failed += n
                cells_c.inc(n, column=col, status="failed")
                blocks_c.inc(1, column=col, status="failed")
                log(f"  [{spec_stage.name}] block FAILED ({n} cells)")

    try:
        with obs.span("run_matrix", columns=len(plans),
                      reps=spec.n_reps, out=outdir or "",
                      mode="aggregate") as root_sp:
            if stages:
                engine = SweepEngine(
                    artifacts, stages, commit=commit, workers=n_workers,
                    prefetch=prefetch,
                    span_parent=getattr(root_sp, "span_id", None),
                )
                restore = (_install_drain(engine, log)
                           if drain_on_sigterm else (lambda: None))
                try:
                    engine.run()
                finally:
                    restore()
                if engine.draining:
                    report.drained = True
    finally:
        report.wall_s = time.monotonic() - t_start
        report.compile_events_delta = (
            obs.compile_event_count() - compiles_before
        )
        # Column summaries from merged sums — schema-compatible with
        # rows-mode column_aggregates. Failed-BLOCK cells never folded
        # into any stat vector, so account them into the summary
        # explicitly (rows mode counts its failed rows the same way).
        report.columns = {}
        for col, st in report.states.items():
            summ = st.summary()
            extra = failed_by_col.pop(col, 0)
            summ["n_cells"] += extra
            summ["n_failed"] += extra
            report.columns[col] = summ
        for col, extra in failed_by_col.items():
            summ = AggState.zero().summary()
            summ["n_cells"] = extra
            summ["n_failed"] = extra
            report.columns[col] = summ
        if outdir:
            try:
                obs.atomic_write_json(
                    os.path.join(outdir, "matrix_report.json"),
                    _report_json(spec, report),
                )
                obs.write_run_artifacts(outdir)
            except Exception as e:  # noqa: BLE001 — the export must not
                # replace the run's real exception.
                log(f"matrix export failed: {e!r}")
    log(
        f"scenario matrix [streaming]: {report.n_computed} computed, "
        f"{report.n_resumed} resumed, {report.n_failed} failed across "
        f"{report.n_columns} columns / {report.n_blocks} blocks in "
        f"{report.wall_s:.1f}s "
        f"(compile events +{report.compile_events_delta:.0f})"
    )
    return report


def _trusted_blocks(
    blocks_by_col: dict, n_reps: int,
) -> tuple[dict, dict]:
    """From the resume scan's ``{column: {rep: record}}``, the block
    records a run at ``n_reps`` may merge: all reps inside the grid and
    no overlap with an already-accepted block (overlaps can only come
    from journals written at DIFFERENT rep counts — e.g. shrinking
    ``n_reps`` after a run left blocks that straddle the new boundary —
    and merging one twice would double-count every cell). Deterministic:
    records process in min-rep order. Returns ``(trusted, covered)`` =
    ``{column: [records]}``, ``{column: set(reps)}``; reps NOT covered
    recompute."""
    trusted: dict[str, list[dict]] = {}
    covered: dict[str, set[int]] = {}
    for col, by_rep in blocks_by_col.items():
        uniq = {rec["method"]: rec for rec in by_rep.values()}
        cov: set[int] = set()
        keep: list[dict] = []
        for rec in sorted(uniq.values(), key=lambda r: r["reps"][0][0]):
            reps = set(_unpack_reps(rec["reps"]))
            if max(reps) >= n_reps or reps & cov:
                continue
            keep.append(rec)
            cov |= reps
        trusted[col] = keep
        covered[col] = cov
    return trusted, covered


def _report_json(spec: MatrixSpec, report: MatrixReport) -> dict:
    def _san(v):
        if isinstance(v, float) and not math.isfinite(v):
            return None
        if isinstance(v, dict):
            return {k: _san(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [_san(x) for x in v]
        return v

    return _san({
        "fingerprint": spec.fingerprint(),
        "mode": report.mode,
        "n_reps": spec.n_reps,
        "batch_width": spec.batch_width,
        "n_blocks": report.n_blocks,
        "columns": report.columns,
        "skipped_columns": report.skipped_columns,
        "n_computed": report.n_computed,
        "n_resumed": report.n_resumed,
        "n_failed": report.n_failed,
        "wall_s": round(report.wall_s, 3),
        "compile_events_delta": report.compile_events_delta,
        "drained": report.drained,
        "cells": report.cells,
    })


#: per-column compiled DGP-draw program for the sequential (forest)
#: path — the data generation still compiles once per column even when
#: the fit cannot ride a vmap axis.
def scalar_generate_executable(dgp: DGPSpec, column: str = ""):
    import jax
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.scenarios.batched import cached_executable
    from ate_replication_causalml_tpu.scenarios.dgp import generate

    key = ("scenario-gen", dgp.fields())

    def build():
        def gen(root_key, cid, salt):
            data_key = jax.random.fold_in(root_key, cid)
            x, w, y, tau_true = generate(dgp, data_key)
            return x, w, y, tau_true, jax.random.fold_in(data_key, salt)

        return jax.jit(gen).lower(
            jax.random.key(0), jnp.zeros((), jnp.uint32),
            jnp.zeros((), jnp.uint32),
        ).compile()

    return cached_executable(key, build, column or dgp.name, "generate")


def run_scalar_replay(
    spec: MatrixSpec, log: Callable[[str], None] = print
) -> MatrixReport:
    """The sequential scalar baseline for the VMAPPED columns: every
    cell through the per-column SCALAR executable (same cell function,
    unvmapped) — one compile per column, one dispatch per CELL. This is
    the leg the bench's batched-vs-sequential wall/compile comparison
    and the bit-identity assertion run against. Non-vmapped (forest)
    columns have no batched-vs-scalar distinction — their cells already
    dispatch one at a time in ``run_matrix`` — so they are excluded
    here and reported as ``skipped_columns``, keeping ``n_columns``
    consistent with the cells this report actually carries."""
    import jax
    import jax.numpy as jnp

    obs.install_jax_monitoring()
    t0 = time.monotonic()
    compiles_before = obs.compile_event_count()
    plans, skipped = plan_columns(spec)
    skipped = list(skipped) + [
        f"{p.name}: non-vmapped — no scalar-replay leg"
        for p in plans
        if not SCENARIO_ESTIMATORS[p.estimator].vmapped
    ]
    plans = [p for p in plans if SCENARIO_ESTIMATORS[p.estimator].vmapped]
    report = MatrixReport(skipped_columns=skipped, n_columns=len(plans))
    root_key = jax.random.key(spec.seed)
    for plan in plans:
        est = SCENARIO_ESTIMATORS[plan.estimator]
        exe = scalar_executable(plan.dgp, est, column=plan.name)
        for rep in range(spec.n_reps):
            tc = time.perf_counter()
            cid = jnp.asarray(data_cell_id(plan.dgp.name, rep), jnp.uint32)
            try:
                ate, se, tt = exe(root_key, cid)
                rec = _cell_record(
                    plan, rep, float(ate), float(se), float(tt),
                    time.perf_counter() - tc,
                )
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # Mirror the batched leg's degrade-don't-abort: the two
                # legs of the bench comparison must account cells the
                # same way or their ok/failed columns contradict.
                if spec.fail_policy != "degrade":
                    raise
                rec = _failed_record(plan, rep, f"{type(e).__name__}: {e}")
            report.cells.append(rec)
            if rec["status"] == "ok":
                report.n_computed += 1
            else:
                report.n_failed += 1
    report.wall_s = time.monotonic() - t0
    report.compile_events_delta = obs.compile_event_count() - compiles_before
    by_col: dict[str, list] = {}
    for rec in report.cells:
        by_col.setdefault(rec["column"], []).append(rec)
    report.columns = {c: column_aggregates(r) for c, r in by_col.items()}
    log(f"scalar replay: {report.n_computed} cells "
        f"({report.n_failed} failed) in {report.wall_s:.1f}s")
    return report


def main(argv: list[str] | None = None) -> MatrixReport:
    import argparse

    ap = argparse.ArgumentParser(
        description="Run a Monte-Carlo scenario matrix (ISSUE 13)")
    ap.add_argument("--out", default=None, help="output directory "
                    "(cells.jsonl + matrix_report.json + telemetry)")
    ap.add_argument("--dgps", default="calibration,hetero_confounded",
                    help=f"comma list from {sorted(STOCK_DGPS)}")
    ap.add_argument("--estimators", default="naive,ipw_logit,aipw_logit")
    ap.add_argument("--reps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sequential", action="store_true")
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--rows", action="store_true",
                    help="materialize the per-cell row table (the PR 13 "
                    "path) instead of streaming aggregate blocks; same "
                    "as ATE_TPU_SCENARIO_ROWS=1")
    args = ap.parse_args(argv)
    spec = MatrixSpec(
        dgps=tuple(STOCK_DGPS[d] for d in args.dgps.split(",") if d),
        estimators=tuple(e for e in args.estimators.split(",") if e),
        n_reps=default_reps() if args.reps is None else args.reps,
        batch_width=(default_batch_width() if args.batch is None
                     else args.batch),
        seed=args.seed,
        rows=True if args.rows else None,
    )
    return run_matrix(
        spec, outdir=args.out,
        scheduler="sequential" if args.sequential else None,
        workers=args.workers,
        drain_on_sigterm=True,
    )


if __name__ == "__main__":
    main()

"""Device-resident streaming aggregates for the scenario matrix
(ISSUE 19, tentpole part a).

The PR 13 runner materializes one host row per (DGP × estimator × seed)
cell — journal bytes, host transfers and Python-side record building
all O(cells), which caps the grid in the thousands. This module folds
the coverage/bias/RMSE/power SUFFICIENT STATISTICS inside each column's
vmapped executable instead: a width-W batch returns one fixed-length
f32 stat vector (:data:`N_STATS` sums — counts, Σerr, Σerr², cover
hits, reject hits, error-histogram cells), so the host sees O(1) bytes
per block and O(blocks) journal records however many cells the block
carries.

Exactness discipline (the PR 13 ``cell_fn`` contract, one level up):

* :func:`batch_stats` is the ONE segment-reduce epilogue — the fused
  streaming executable (:func:`aggregate_executable`, which traces
  ``batch_stats(vmap(cell_fn)(...))``) and the materialized-rows
  reference fold (:func:`fold_executable` + :func:`fold_rows`, the same
  function jitted standalone over journaled row values) share it
  VERBATIM. Streaming-vs-rows bit-identity is therefore an assertion
  about XLA fusing a tiny epilogue onto an unchanged vmapped column,
  not about two aggregate implementations agreeing.
* Every stat is a plain per-lane sum with masked lanes excluded by
  ``where``-selection (never by multiplying — ``0·NaN`` is NaN), so
  block states merge by ADDITION on the host (:meth:`AggState.merge`),
  in declared block order, exactly — counts and histogram cells are
  small-integer-exact in f32 per block and merge in f64.
* The reference fold must chunk rows into the SAME width-W blocks the
  streaming run dispatched (``fold_rows(..., width=W)``): f32 sums are
  chunking-dependent, and bit-identity is only a meaningful claim when
  both sides reduce the same lanes in the same segments.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
)
from ate_replication_causalml_tpu.scenarios.batched import (
    ScenarioEstimator,
    cached_executable,
    cell_fn,
    column_cache_key,
)
from ate_replication_causalml_tpu.scenarios.dgp import DGPSpec

#: bump when the stat-vector layout or the epilogue numerics change —
#: old block journals must not merge into new aggregates. Rides the
#: checkpoint fingerprint (mode suffix) AND every block record's
#: ``schema`` field (the ISSUE 19 defense-in-depth resume assert).
AGG_SCHEMA_TAG = "scenarios-agg-v1"

#: 95% normal critical value — matching estimators.base.Z_95 and the
#: rows-mode host recipe in scenarios/matrix.py.
Z95 = 1.96

#: Error-sketch shape shared with the rows-mode column aggregates and
#: the ISSUE 16 stat-health plane: estimation errors ``ate - tau_true``
#: live well inside ±8 for every stock DGP; outliers land in the
#: explicit tails so mass is conserved either way.
ERROR_SKETCH_RANGE = (-8.0, 8.0)
ERROR_SKETCH_BINS = 8

#: Stat-vector layout, fixed order. The first 8 are the moment/count
#: sums; the remaining ``ERROR_SKETCH_BINS + 2`` are the error
#: histogram's extended cells ``[underflow, *bins, overflow]`` (the
#: FixedBinSketch cell convention). Everything is a sum over the
#: block's unmasked lanes — mergeable by addition, order-fixed.
STAT_FIELDS = (
    "n_cells",      # unmasked lanes dispatched
    "n_ok",         # finite point estimate
    "n_se",         # finite point estimate AND finite SE
    "sum_err",      # Σ (ate - tau_true)        over ok lanes
    "sum_err2",     # Σ (ate - tau_true)²       over ok lanes
    "sum_tau",      # Σ tau_true                over ok lanes
    "cover_hits",   # Σ 1[|ate - tau| <= z·se]  over se lanes
    "reject_hits",  # Σ 1[|ate| > z·se]         over se lanes
)
N_STATS = len(STAT_FIELDS) + ERROR_SKETCH_BINS + 2


def batch_stats(ate, se, tau_true, mask):
    """The segment-reduce epilogue: ``(W,) × 4 -> (N_STATS,)`` f32.

    ``mask`` marks the real lanes (the final partial batch pads to the
    column's one executable width — padded lanes must not count).
    Shared verbatim by the fused streaming executable and the
    standalone reference fold; see the module docstring for why that
    sharing IS the bit-identity contract."""
    dtype = ate.dtype
    live = mask.astype(jnp.bool_)
    ok = live & jnp.isfinite(ate)
    has_se = ok & jnp.isfinite(se)
    z = jnp.asarray(Z95, dtype)
    err = jnp.where(ok, ate - tau_true, jnp.zeros((), dtype))
    covered = has_se & (ate - z * se <= tau_true) & (tau_true <= ate + z * se)
    rejected = has_se & (jnp.abs(ate) > z * se)

    def count(flags):
        return jnp.sum(flags.astype(dtype))

    lo, hi = ERROR_SKETCH_RANGE
    width = (hi - lo) / ERROR_SKETCH_BINS
    # Extended-cell index: -1 = underflow, n_bins = overflow — the
    # FixedBinSketch cells() convention, so merged histogram sums
    # reconstruct a merge-compatible sketch dict without rebinning.
    idx = jnp.clip(
        jnp.floor((err - lo) / jnp.asarray(width, dtype)).astype(jnp.int32),
        -1, ERROR_SKETCH_BINS,
    )
    hist = [
        count(ok & (idx == cell - 1))
        for cell in range(ERROR_SKETCH_BINS + 2)
    ]
    return jnp.stack([
        count(live), count(ok), count(has_se),
        jnp.sum(jnp.where(ok, err, jnp.zeros((), dtype))),
        jnp.sum(jnp.where(ok, err * err, jnp.zeros((), dtype))),
        jnp.sum(jnp.where(ok, tau_true, jnp.zeros((), dtype))),
        count(covered), count(rejected), *hist,
    ])


@dataclasses.dataclass(frozen=True)
class AggState:
    """One column's merged sufficient statistics — the O(1) object a
    streaming block journals and a resumed run re-merges. Host-side
    state is f64 (exact for the f32-integer counts and far past any
    realistic Σerr² magnitude); merge is plain addition in declared
    block order, so resumed and straight-through runs agree exactly."""

    stats: tuple[float, ...]

    def __post_init__(self) -> None:
        if len(self.stats) != N_STATS:
            raise ValueError(
                f"AggState wants {N_STATS} stats, got {len(self.stats)}"
            )

    @classmethod
    def zero(cls) -> "AggState":
        return cls((0.0,) * N_STATS)

    @classmethod
    def from_array(cls, arr) -> "AggState":
        return cls(tuple(float(v) for v in np.asarray(arr).reshape(-1)))

    def merge(self, other: "AggState") -> "AggState":
        return AggState(tuple(
            a + b for a, b in zip(self.stats, other.stats)
        ))

    def __getattr__(self, name: str):
        try:
            return self.stats[STAT_FIELDS.index(name)]
        except ValueError:
            raise AttributeError(name) from None

    def hist_cells(self) -> list[int]:
        """``[underflow, *bins, overflow]`` as exact ints."""
        return [int(v) for v in self.stats[len(STAT_FIELDS):]]

    def summary(self, nominal: float = 0.95) -> dict:
        """The per-column aggregate dict, schema-compatible with the
        rows-mode ``column_aggregates`` recipe (coverage/power/bias/
        RMSE/MC-SE + the ISSUE 16 mergeable sketches) — computed from
        sums instead of a materialized cell table."""
        n_cells = int(self.n_cells)
        n_ok = int(self.n_ok)
        n_se = int(self.n_se)
        out: dict = {
            "n_cells": n_cells,
            "n_ok": n_ok,
            "n_failed": n_cells - n_ok,
            "coverage": None,
            "power": None,
            "bias": None,
            "rmse": None,
            "coverage_mc_se": None,
            "nominal": nominal,
        }
        if n_ok:
            out["bias"] = self.sum_err / n_ok
            out["rmse"] = math.sqrt(max(0.0, self.sum_err2 / n_ok))
            out["mean_tau_true"] = self.sum_tau / n_ok
        if n_se:
            out["coverage"] = self.cover_hits / n_se
            out["power"] = self.reject_hits / n_se
            out["coverage_mc_se"] = math.sqrt(
                nominal * (1.0 - nominal) / n_se
            )
        err_sketch = FixedBinSketch(*ERROR_SKETCH_RANGE, ERROR_SKETCH_BINS)
        cells = self.hist_cells()
        err_sketch.underflow = cells[0]
        err_sketch.overflow = cells[-1]
        err_sketch.counts = cells[1:-1]
        cov_sketch = CalibrationSketch()
        if n_se:
            # Every se-lane is one (predicted=nominal, covered) pair —
            # identical to the rows-mode update, just pre-counted.
            bucket = min(cov_sketch.n_buckets - 1,
                         int(nominal * cov_sketch.n_buckets))
            cov_sketch.counts[bucket] = n_se
            cov_sketch.positives[bucket] = int(self.cover_hits)
        out["sketches"] = {
            "error": err_sketch.to_dict(),
            "coverage": cov_sketch.to_dict(),
        }
        return out


# ── executables ──────────────────────────────────────────────────────


def aggregate_executable(
    spec: DGPSpec, est: ScenarioEstimator, width: int, column: str = "",
    ids_sharding=None,
):
    """The column's ONE fused streaming executable:
    ``compiled(root_key, cell_ids[W], mask[W]) -> stats[N_STATS]`` —
    ``batch_stats`` traced directly onto the vmapped cell outputs, so a
    block's W rows never reach the host. Same cache/compile-counter
    discipline as the rows-mode column executable (one compile per
    column per process, ``kind="aggregate"``); ``ids_sharding`` shards
    the lane axis over the mesh with replicated outputs — the per-lane
    sums become a single small cross-device reduction, dispatched
    inside the mesh lane like every other collective."""
    if not est.vmapped:
        raise ValueError(
            f"estimator {est.name!r} is not vmappable — fold its eager "
            "cells host-side through fold_rows instead"
        )
    key = column_cache_key(spec, est.name, width) + ("agg", ids_sharding)

    def build():
        cells = jax.vmap(cell_fn(spec, est), in_axes=(None, 0))

        def agg(root_key, ids, mask):
            ate, se, tau_true = cells(root_key, ids)
            return batch_stats(ate, se, tau_true, mask)

        root = jax.random.key(0)
        ids = jnp.zeros((width,), jnp.uint32)
        mask = jnp.zeros((width,), jnp.dtype(spec.dtype))
        if ids_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(ids_sharding.mesh, P())
            jitted = jax.jit(
                agg, in_shardings=(rep, ids_sharding, ids_sharding),
                out_shardings=rep,
            )
            ids = jax.device_put(np.zeros((width,), np.uint32), ids_sharding)
            mask = jax.device_put(
                np.zeros((width,), spec.dtype), ids_sharding
            )
            root = jax.device_put(root, rep)
        else:
            jitted = jax.jit(agg)
        return jitted.lower(root, ids, mask).compile()

    return cached_executable(
        key, build, column or f"{spec.name}:{est.name}", "aggregate")


def fold_executable(width: int, dtype: str = "float32"):
    """The reference fold: the SAME ``batch_stats`` epilogue jitted
    standalone over width-W row arrays — what the bit-identity tests
    and the non-vmapped (eager-engine) path fold materialized rows
    through. One compile per width, shared across columns (the epilogue
    has no column in its shape)."""
    key = ("scenario-agg-fold", width, dtype)

    def build():
        arr = jnp.zeros((width,), jnp.dtype(dtype))
        return jax.jit(batch_stats).lower(arr, arr, arr, arr).compile()

    return cached_executable(key, build, f"fold:w{width}", "aggregate_fold")


def fold_rows(
    rows, width: int, dtype: str = "float32",
) -> AggState:
    """Fold materialized ``(ate, se, tau_true)`` triples into an
    :class:`AggState` through :func:`fold_executable`, chunked into the
    same width-W mask-padded blocks a streaming run dispatches (f32
    sums are segment-dependent — the reference must reduce the same
    lanes in the same segments to be comparable at the bit level).
    ``rows`` is an iterable of 3-tuples in replicate order."""
    rows = list(rows)
    exe = fold_executable(width, dtype)
    state = AggState.zero()
    np_dtype = np.dtype(dtype)
    for i in range(0, len(rows), width):
        chunk = rows[i:i + width]
        pad = width - len(chunk)
        ate = np.asarray(
            [r[0] for r in chunk] + [0.0] * pad, np_dtype)
        se = np.asarray([r[1] for r in chunk] + [0.0] * pad, np_dtype)
        tau = np.asarray([r[2] for r in chunk] + [0.0] * pad, np_dtype)
        mask = np.asarray([1.0] * len(chunk) + [0.0] * pad, np_dtype)
        state = state.merge(AggState.from_array(exe(ate, se, tau, mask)))
    return state

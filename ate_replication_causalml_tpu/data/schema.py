"""Dataset schema: typed covariate roles.

The reference keeps covariate lists in notebook globals
(``ate_replication.Rmd:49-58``) and several estimators silently read the
``covariates`` global (``ate_functions.R:91, 113, 135, 289, 394-396``).
Here that hidden state becomes an explicit, immutable schema object that
travels with the data (SURVEY.md §5.6).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class DatasetSchema:
    """Names and roles of the columns of a causal dataset.

    Attributes:
      continuous: covariates that are z-scored during preprocessing
        (``ate_replication.Rmd:72-74``).
      binary: indicator covariates, passed through unscaled
        (``ate_replication.Rmd:77-79``).
      outcome: outcome column name (renamed to ``Y`` in the reference,
        ``ate_replication.Rmd:90-93``).
      treatment: treatment column name (renamed to ``W``).
    """

    continuous: tuple[str, ...]
    binary: tuple[str, ...]
    outcome: str = "Y"
    treatment: str = "W"

    @property
    def covariates(self) -> tuple[str, ...]:
        """All covariates, continuous first — the reference's column order
        (``ate_replication.Rmd:57``)."""
        return self.continuous + self.binary

    @property
    def all_columns(self) -> tuple[str, ...]:
        return self.covariates + (self.outcome, self.treatment)

    @property
    def n_covariates(self) -> int:
        return len(self.covariates)

    def column_index(self, names: Sequence[str] | str) -> list[int]:
        if isinstance(names, str):
            names = [names]
        cov = list(self.covariates)
        return [cov.index(n) for n in names]

    def replace(self, **kwargs) -> "DatasetSchema":
        return dataclasses.replace(self, **kwargs)


# The Gerber–Green–Larimer 2008 social-pressure schema used by the
# reference notebook (``ate_replication.Rmd:49-58``): 15 continuous +
# 6 binary covariates, outcome ``outcome_voted``, treatment
# ``treat_neighbors``.
GGL_CONTINUOUS = (
    "yob",
    "city",
    "hh_size",
    "totalpopulation_estimate",
    "percent_male",
    "median_age",
    "percent_62yearsandover",
    "percent_white",
    "percent_black",
    "percent_asian",
    "median_income",
    "employ_20to64",
    "highschool",
    "bach_orhigher",
    "percent_hispanicorlatino",
)
GGL_BINARY = ("sex", "g2000", "g2002", "p2000", "p2002", "p2004")

GGL_SCHEMA = DatasetSchema(
    continuous=GGL_CONTINUOUS,
    binary=GGL_BINARY,
    outcome="outcome_voted",
    treatment="treat_neighbors",
)

# After the reference's rename step (``ate_replication.Rmd:90-93``) the
# outcome/treatment are literally called Y/W; estimator code operates on
# this schema.
GGL_SCHEMA_WY = GGL_SCHEMA.replace(outcome="Y", treatment="W")

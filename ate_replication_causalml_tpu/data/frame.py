"""Columnar causal dataset container.

The reference passes an R ``data.frame`` plus string column names into every
estimator (``ate_functions.R`` throughout). The TPU-native equivalent is a
dense, statically-shaped struct-of-arrays: one ``(n, p)`` covariate matrix
(covariates in schema order), plus ``w``/``y`` vectors. It is a registered
pytree, so a ``CausalFrame`` flows through ``jit``/``vmap``/``shard_map``
unchanged while the schema rides along as static metadata.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.schema import DatasetSchema


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CausalFrame:
    """Dense causal dataset: covariates ``x`` ~ (n, p), treatment ``w`` ~ (n,),
    outcome ``y`` ~ (n,). Columns of ``x`` follow ``schema.covariates`` order
    (continuous first, then binary — ``ate_replication.Rmd:57``)."""

    x: jax.Array
    w: jax.Array
    y: jax.Array
    schema: DatasetSchema = dataclasses.field(
        metadata=dict(static=True),
        default=None,
    )

    @property
    def n(self) -> int:
        return self.x.shape[0]

    @property
    def p(self) -> int:
        return self.x.shape[1]

    def column(self, name: str) -> jax.Array:
        """A single covariate column by schema name."""
        (idx,) = self.schema.column_index(name)
        return self.x[:, idx]

    def select(self, names) -> jax.Array:
        """Covariate submatrix in the order of ``names``."""
        idx = jnp.asarray(self.schema.column_index(names))
        return self.x[:, idx]

    def take(self, indices) -> "CausalFrame":
        """Row-subset (R ``df[idx, ]``) — also the bootstrap gather."""
        indices = jnp.asarray(indices)
        return CausalFrame(
            x=self.x[indices], w=self.w[indices], y=self.y[indices], schema=self.schema
        )

    def astype(self, dtype) -> "CausalFrame":
        return CausalFrame(
            x=self.x.astype(dtype),
            w=self.w.astype(dtype),
            y=self.y.astype(dtype),
            schema=self.schema,
        )

    @classmethod
    def from_columns(
        cls, columns: Mapping[str, np.ndarray], schema: DatasetSchema, dtype=jnp.float32
    ) -> "CausalFrame":
        """Build from a dict of 1-D numpy columns (host-side ingest path)."""
        x = np.stack([np.asarray(columns[c], dtype=np.float64) for c in schema.covariates], axis=1)
        w = np.asarray(columns[schema.treatment], dtype=np.float64)
        y = np.asarray(columns[schema.outcome], dtype=np.float64)
        return cls(
            x=jnp.asarray(x, dtype=dtype),
            w=jnp.asarray(w, dtype=dtype),
            y=jnp.asarray(y, dtype=dtype),
            schema=schema,
        )

    def design_matrix(self, include_treatment: bool = True, intercept: bool = True) -> jax.Array:
        """[1, X, W] design matrix used by the regression estimators
        (R formula ``Y ~ .`` — ``ate_functions.R:26``).

        Column order matches R's ``lm(Y ~ .)`` on a frame laid out
        [covariates..., W]: intercept, covariates in schema order, then W.
        """
        cols = [self.x]
        if include_treatment:
            cols.append(self.w[:, None])
        m = jnp.concatenate(cols, axis=1)
        if intercept:
            m = jnp.concatenate([jnp.ones((m.shape[0], 1), m.dtype), m], axis=1)
        return m

"""Data preparation and bias injection — the reference notebook's L0 stages.

Reproduces ``ate_replication.Rmd`` exactly (quirks included, SURVEY.md §7.4):

* ``prepare_dataset``: subsample ``n_obs`` rows with R's RNG
  (``Rmd:41-44, 66-68``), z-score the 15 continuous covariates with the
  n-1 sd (R ``scale()``, ``Rmd:72-74``), pass binaries through, rename
  outcome/treatment to Y/W (``Rmd:90-93``), drop NA rows (``Rmd:93``).
* ``inject_bias``: construct confounding from the RCT (``Rmd:97-123``) —
  drop the first ``round(p * k)`` treated units likely to vote and
  control units likely not to vote. The treated-side condition tests
  ``p2002`` twice and never tests ``p2004`` (``Rmd:104``) — a reference
  quirk reproduced verbatim in compat mode because it shapes ``df_mod``.

All of this is host-side NumPy (one-shot ingest); the resulting
``CausalFrame`` is what lands on the TPU mesh.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.data.schema import GGL_SCHEMA, DatasetSchema
from ate_replication_causalml_tpu.utils.rrandom import RCompatRNG


@dataclasses.dataclass(frozen=True)
class PrepConfig:
    """Notebook-global constants, made explicit (SURVEY.md §5.6)."""

    n_obs: int = 50_000          # ate_replication.Rmd:43
    seed: int = 1991             # ate_replication.Rmd:42
    pt: float = 0.85             # drop fraction, treated side (Rmd:99)
    pc: float = 0.85             # drop fraction, control side (Rmd:100)
    sample_kind: str = "rounding"  # R <= 3.5 sample.int default (2018-era)


def load_raw_csv(path: str, schema: DatasetSchema = GGL_SCHEMA) -> dict[str, np.ndarray]:
    """Load the reference's CSV (``read.csv``, ``ate_replication.Rmd:33``)
    into raw columns keyed by the schema's names.

    The real ``socialpresswgeooneperhh_NEIGH.csv`` is gitignored in the
    reference and downloaded separately (``Rmd:30``); this loader accepts
    it — or any CSV with the schema's columns. Non-numeric entries (R's
    ``NA`` strings, blanks) become NaN and are dropped later by
    ``prepare_dataset``'s na.omit stage. Parsing uses the native C++
    reader when available (the 229k-row GGL panel in ~0.1 s), with
    ``np.genfromtxt`` as the fallback.
    """
    from ate_replication_causalml_tpu.native import native_available, read_csv_native

    if native_available():
        header, data = read_csv_native(path)
        header = [h.strip() for h in header]
    else:
        with open(path, "r") as f:
            header = [h.strip().strip('"') for h in f.readline().rstrip("\n").split(",")]
        data = None
    wanted = set(schema.all_columns)
    missing = wanted - set(header)
    if missing:
        raise ValueError(f"CSV {path} is missing columns: {sorted(missing)}")
    usecols = [i for i, h in enumerate(header) if h in wanted]
    if data is None:
        data = np.genfromtxt(
            path, delimiter=",", skip_header=1, usecols=usecols,
            dtype=np.float64, missing_values=("NA", "", "NaN"), filling_values=np.nan,
        )
        data = np.atleast_2d(data)
        return {header[c]: data[:, j] for j, c in enumerate(usecols)}
    return {header[c]: np.ascontiguousarray(data[:, c]) for c in usecols}


def _zscore(col: np.ndarray) -> np.ndarray:
    """R ``scale()``: (x - mean) / sd with the n-1 denominator. NA-
    tolerant like R (``colMeans(na.rm=TRUE)`` / per-column sd over
    complete values): an NA row must not poison the whole column — it
    stays NA and is dropped by the later na.omit stage."""
    mu = np.nanmean(col)
    sd = np.nanstd(col, ddof=1)
    return (col - mu) / sd


def prepare_dataset(
    raw: dict[str, np.ndarray],
    config: PrepConfig = PrepConfig(),
    schema: DatasetSchema = GGL_SCHEMA,
    rng: RCompatRNG | None = None,
    dtype=None,
) -> CausalFrame:
    """Raw columns -> scaled, renamed, NA-free ``CausalFrame`` (the notebook's ``df``).

    ``rng`` defaults to a fresh R-compatible stream seeded with
    ``config.seed`` — matching ``set.seed(1991)`` followed immediately by
    ``sample_n`` in the notebook.
    """
    if dtype is None:
        # float64 under x64 (strict-parity tests), float32 otherwise —
        # avoids silent-truncation warnings on the TPU fast path.
        import jax

        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
    n_raw = len(raw[schema.treatment])
    if rng is None:
        from ate_replication_causalml_tpu.native import make_rcompat_rng

        rng = make_rcompat_rng(config.seed, sample_kind=config.sample_kind)
    idx = rng.sample_n_rows(n_raw, min(config.n_obs, n_raw))

    cols: dict[str, np.ndarray] = {}
    for c in schema.continuous:
        cols[c] = _zscore(np.asarray(raw[c], dtype=np.float64)[idx])
    for c in schema.binary:
        cols[c] = np.asarray(raw[c], dtype=np.float64)[idx]
    cols["Y"] = np.asarray(raw[schema.outcome], dtype=np.float64)[idx]
    cols["W"] = np.asarray(raw[schema.treatment], dtype=np.float64)[idx]

    # na.omit (ate_replication.Rmd:93): drop rows with NA/NaN. R keeps
    # +/-Inf rows (Inf is not NA), so isnan — not isfinite — matches.
    keep = np.ones(len(idx), dtype=bool)
    for v in cols.values():
        keep &= ~np.isnan(v)
    cols = {k: v[keep] for k, v in cols.items()}

    out_schema = schema.replace(outcome="Y", treatment="W")
    return CausalFrame.from_columns(cols, out_schema, dtype=dtype)


def bias_drop_indices(frame: CausalFrame, config: PrepConfig = PrepConfig()) -> np.ndarray:
    """Row indices the bias injection removes (``ate_replication.Rmd:97-119``).

    Returns 0-based indices into ``frame`` in the reference's order
    (treated drops first, then control drops) — ``print(length(drop_idx))``
    in the notebook reports 41,062 on the real data (BASELINE.md).
    """
    col = lambda name: np.asarray(frame.column(name))
    w = np.asarray(frame.w)

    # Likely voters, dropped from TREATMENT (Rmd:103-105). Note the
    # reference quirk: p2002 appears twice and p2004 not at all.
    drop_from_treat = (
        (col("g2000") == 1) | (col("g2002") == 1)
        | (col("p2000") == 1) | (col("p2002") == 1) | (col("p2002") == 1)
        | (col("city") > 2) | (col("yob") > 2)
    )
    # Likely non-voters, dropped from CONTROL (Rmd:108-110).
    drop_from_control = (
        (col("g2000") == 0) | (col("g2002") == 0)
        | (col("p2000") == 0) | (col("p2002") == 0) | (col("p2004") == 0)
        | (col("city") < -2) | (col("yob") < -2)
    )

    # which() returns ascending indices; the notebook keeps the FIRST
    # round(p*k) of each (Rmd:113-117). R round() is half-to-even, as is
    # np.round.
    drop_treat_idx = np.nonzero((w == 1) & drop_from_treat)[0]
    drop_control_idx = np.nonzero((w == 0) & drop_from_control)[0]
    n_t = int(np.round(config.pt * len(drop_treat_idx)))
    n_c = int(np.round(config.pc * len(drop_control_idx)))
    drop = np.concatenate([drop_treat_idx[:n_t], drop_control_idx[:n_c]])
    # unique(c(...)) — the two sets are disjoint (W==1 vs W==0) so this
    # only dedups, never reorders in practice.
    _, first = np.unique(drop, return_index=True)
    return drop[np.sort(first)]


def inject_bias(
    frame: CausalFrame, config: PrepConfig = PrepConfig()
) -> tuple[CausalFrame, np.ndarray]:
    """The notebook's ``df_mod <- df[-drop_idx, ]`` (``Rmd:121``).

    Returns (biased frame, dropped indices).
    """
    drop = bias_drop_indices(frame, config)
    keep = np.setdiff1d(np.arange(frame.n), drop, assume_unique=False)
    return frame.take(keep), drop

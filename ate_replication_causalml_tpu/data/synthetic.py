"""Synthetic Gerber–Green–Larimer-like data generator.

The real dataset (``socialpresswgeooneperhh_NEIGH.csv``) is gitignored in
the reference (``/root/reference/.gitignore``) and must be downloaded
separately (``ate_replication.Rmd:30``), so the framework ships a
synthetic generator producing the same shape: the GGL_SCHEMA columns, a
randomized treatment (the RCT property the oracle relies on,
``ate_replication.Rmd:127-135``), and a binary turnout outcome whose
true ATE is configurable (the published oracle is ≈0.095, BASELINE.md).

Covariates are generated with the correlation structure the reference's
bias injection exploits (``ate_replication.Rmd:97-123``): past-vote flags
(g2000/g2002/p2000/p2002/p2004) strongly predict turnout, and ``city`` /
``yob`` carry wide tails so the ``> 2`` / ``< -2`` z-score conditions
select real subpopulations.

Generation is columnar NumPy on host (this is L0 ingest, not the TPU hot
path); the result feeds ``prepare_dataset`` exactly like a loaded CSV.
"""

from __future__ import annotations

import numpy as np

from ate_replication_causalml_tpu.data.schema import GGL_SCHEMA, DatasetSchema


def make_ggl_like(
    n: int,
    seed: int = 0,
    true_ate: float = 0.095,
    treat_frac: float = 1.0 / 6.0,
    schema: DatasetSchema = GGL_SCHEMA,
) -> dict[str, np.ndarray]:
    """Generate raw (unscaled) columns mimicking the GGL one-per-household file.

    Returns a dict of 1-D float64 arrays keyed by ``schema.all_columns``.
    The treatment is completely randomized (Bernoulli ``treat_frac``),
    so a difference-in-means on the full sample is an unbiased oracle for
    ``true_ate`` — the reference's validation strategy (SURVEY.md §4.1).
    """
    rng = np.random.default_rng(seed)

    # Latent "civic engagement" score drives both past votes and turnout —
    # this is the confounder the bias injection turns into selection.
    civic = rng.normal(0.0, 1.0, n)

    cols: dict[str, np.ndarray] = {}
    # Demographics (raw scales roughly matching the census-block fields).
    cols["yob"] = np.clip(rng.normal(1956.0, 14.0, n) - 0.8 * civic, 1900, 1990).round()
    cols["city"] = rng.integers(1, 9, n).astype(np.float64) + np.round(
        np.clip(0.9 * civic, -3, 3)
    )
    cols["hh_size"] = np.clip(rng.poisson(2.0, n) + 1, 1, 8).astype(np.float64)
    cols["totalpopulation_estimate"] = rng.lognormal(7.8, 0.7, n).round()
    cols["percent_male"] = np.clip(rng.normal(49.5, 3.0, n), 30, 70)
    cols["median_age"] = np.clip(rng.normal(38.0, 6.0, n) + 1.5 * civic, 18, 80)
    cols["percent_62yearsandover"] = np.clip(rng.normal(14.0, 6.0, n) + 2.0 * civic, 0, 60)
    pw = np.clip(rng.normal(85.0, 15.0, n) + 3.0 * civic, 0, 100)
    cols["percent_white"] = pw
    cols["percent_black"] = np.clip(rng.normal(8.0, 10.0, n) - 0.2 * (pw - 85.0), 0, 100)
    cols["percent_asian"] = np.clip(rng.normal(2.0, 3.0, n), 0, 60)
    cols["median_income"] = rng.lognormal(10.8, 0.4, n).round() + 4000.0 * np.clip(civic, -2, 2)
    cols["employ_20to64"] = np.clip(rng.normal(75.0, 8.0, n) + 2.0 * civic, 20, 100)
    cols["highschool"] = np.clip(rng.normal(88.0, 7.0, n) + 2.0 * civic, 30, 100)
    cols["bach_orhigher"] = np.clip(rng.normal(24.0, 12.0, n) + 4.0 * civic, 0, 100)
    cols["percent_hispanicorlatino"] = np.clip(rng.normal(4.0, 6.0, n), 0, 100)
    cols["sex"] = (rng.random(n) < 0.5).astype(np.float64)

    # Past participation: general elections high base rate, primaries low,
    # all loaded on the civic confounder.
    def vote_flag(base_logit: float, load: float) -> np.ndarray:
        p = 1.0 / (1.0 + np.exp(-(base_logit + load * civic)))
        return (rng.random(n) < p).astype(np.float64)

    cols["g2000"] = vote_flag(1.2, 1.4)
    cols["g2002"] = vote_flag(0.9, 1.4)
    cols["p2000"] = vote_flag(-1.2, 1.2)
    cols["p2002"] = vote_flag(-0.8, 1.2)
    cols["p2004"] = vote_flag(-0.6, 1.2)

    # Randomized treatment (the RCT) and potential outcomes.
    w = (rng.random(n) < treat_frac).astype(np.float64)
    base_logit = -0.7 + 1.1 * civic + 0.4 * (cols["g2002"] - 0.5) + 0.3 * (cols["p2004"] - 0.5)
    p0 = 1.0 / (1.0 + np.exp(-base_logit))
    p1 = np.clip(p0 + true_ate, 0.0, 1.0)
    u = rng.random(n)
    y0 = (u < p0).astype(np.float64)
    y1 = (u < p1).astype(np.float64)  # shared uniform => monotone potential outcomes
    cols[schema.outcome] = np.where(w == 1.0, y1, y0)
    cols[schema.treatment] = w
    return cols

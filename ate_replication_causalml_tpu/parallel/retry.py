"""Failure detection & elastic recovery (SURVEY.md §5.3, hardened in
ISSUE 3).

The reference has no systems-level fault tolerance (a single R process;
its only robustness is numerical — propensity clipping, ``na.rm``). The
TPU framework's unit of work *is* fault-tolerant by construction: every
parallel axis (bootstrap replicate batches, CV folds, tree chunks) is
stateless and idempotent, so recovery is re-execution:

* :func:`probe_devices` — failure detection: run a tiny addition on
  every visible device, report the healthy subset. A dropped axon
  tunnel / preempted slice shows up here instead of as a hang deep in
  an estimator. Chaos scope ``device:drop=k`` injects here.
* :func:`run_shards` — hardened shard runner: executes independent
  shard thunks sequentially with **classified** retry — transient
  failures (``JaxRuntimeError``, ``OSError``) retried with capped
  exponential backoff and deterministic per-``(pool, shard, attempt)``
  jitter; programming errors (``TypeError``, ``ValueError``,
  ``AssertionError``) raise immediately instead of burning retry
  budget on a bug. A per-pool wall-clock ``deadline_s`` bounds the
  whole pool; repeated device-origin failures trigger a
  :func:`probe_devices` re-probe and, via ``redispatch``, move the
  remaining shards onto the healthy subset. Deterministic: each shard
  owns its RNG key, so a retried shard reproduces exactly what the
  failed attempt would have produced. Both forest fitters drive their
  tree-chunk loops through this.
* :func:`inject_failures` — plan-based fault injection, now a thin
  front for :func:`resilience.chaos.plan_faults`; probabilistic
  injection comes from the ``ATE_TPU_CHAOS`` shard scope, which
  :func:`run_shards` arms automatically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.backoff import (
    BACKOFF_CAP_MULT,
    jittered_backoff_delay,
)
from ate_replication_causalml_tpu.resilience.deadline import Budget
from ate_replication_causalml_tpu.resilience.errors import (
    ChaosFault,
    DeadlineExceeded,
    classify,
)


def probe_devices(devices: Sequence | None = None) -> list:
    """Return the subset of ``devices`` (default: all) that complete a
    trivial computation. Failures are caught, not raised — detection,
    not crash. Under ``ATE_TPU_CHAOS`` ``device:drop=k`` the last ``k``
    healthy devices are reported dead (deterministically, so they stay
    dead on re-probe)."""
    healthy = []
    for d in devices if devices is not None else jax.devices():
        try:
            r = jax.device_put(jnp.ones(()), d) + 1.0
            if float(r) == 2.0:
                healthy.append(d)
        except Exception:
            continue
    inj = chaos.active()
    if inj is not None:
        healthy = inj.drop_devices(healthy)
    return healthy


@dataclasses.dataclass
class ShardOutcome:
    """Bookkeeping for one shard's execution. ``deadline`` marks a
    shard the pool deadline cut (vs one that exhausted its retries)."""

    index: int
    result: object = None
    attempts: int = 0
    ok: bool = False
    error: str | None = None
    deadline: bool = False


def backoff_delay(pool: str, shard: int, attempt: int,
                  base_s: float) -> float:
    """Backoff before retrying ``shard``'s ``attempt``-th failure:
    exponential in the attempt, jittered, capped at
    ``BACKOFF_CAP_MULT × base_s``. The jitter is a pure function of
    ``(pool, shard, attempt)`` (crc32 → [0, 0.25)) — retries de-herd
    across shards without any nondeterminism, so tests can assert the
    exact sleep schedule. The formula lives in
    ``resilience/backoff.py``, shared with the serving client and the
    retrain supervisor."""
    return jittered_backoff_delay(
        f"{pool}|{shard}|{attempt}", attempt, base_s
    )


def run_shards(
    shard_fn: Callable[[int], object],
    n_shards: int,
    max_attempts: int = 3,
    backoff_s: float = 0.25,
    log: Callable[[str], None] | None = None,
    retriable: tuple[type[BaseException], ...] | None = None,
    pool: str = "shards",
    deadline_s: float | None = None,
    probe: Callable[[], list] | None = None,
    redispatch: Callable[[list], Callable[[int], object]] | None = None,
    reprobe_after: int = 2,
) -> list[ShardOutcome]:
    """Run ``shard_fn(i)`` for every shard ``i`` with classified retry.

    Shards must be independent and idempotent (they are: bootstrap
    batches, folds and tree chunks carry their own fold-in keys). A
    shard that exhausts ``max_attempts`` is reported failed in its
    :class:`ShardOutcome`; the others still complete — callers decide
    whether partial coverage is acceptable (e.g. 9/10 bootstrap batches
    still estimate an SE) or raise via :func:`require_all`.

    Error handling (``retriable=None``, the default) classifies via
    :func:`resilience.errors.classify`: transient failures
    (``JaxRuntimeError``, ``OSError``, plain ``RuntimeError``) retry;
    programming errors (``TypeError``, ``ValueError``,
    ``AssertionError``, …) raise immediately — a bug replayed three
    times with backoff is still the same bug, reported late.
    ``KeyboardInterrupt`` is never caught. Passing an explicit
    ``retriable`` tuple restores opt-in semantics: listed types retry,
    everything else propagates.

    ``deadline_s`` bounds the POOL's wall clock: once it passes, no new
    attempt starts and no backoff sleep begins; unfinished shards are
    marked failed with a ``DeadlineExceeded`` error string (events:
    ``shard_deadline``). Completed shards keep their results — deadline
    pressure degrades coverage, it does not void finished work.

    After ``reprobe_after`` device-origin failures
    (``JaxRuntimeError``) across the pool, the runner re-probes
    (``probe``, default :func:`probe_devices`) and emits
    ``device_reprobe`` with the healthy count; with ``redispatch`` it
    swaps in ``redispatch(healthy)`` as the shard function, moving the
    REMAINING shards onto the surviving devices.

    ``pool`` labels this call's telemetry: attempts / retries /
    failures / backoff-seconds counters (observability/), created at
    zero up front so a healthy run still exports the keys — "no
    retries" is a reported fact, not a missing metric. Retries and
    exhaustions additionally land in the event log with the error
    string. Under ``ATE_TPU_CHAOS`` the shard scope is armed here:
    injected faults raise ``ChaosShardFault`` (transient) before the
    thunk runs, each one a ``chaos_inject`` event.
    """
    attempts_c = obs.counter("shard_attempts_total", "run_shards attempts")
    retries_c = obs.counter("shard_retries_total", "failed attempts that will retry")
    failures_c = obs.counter("shard_failures_total", "shards that exhausted retries")
    backoff_c = obs.counter("shard_backoff_seconds_total", "backoff sleep time")
    for c in (attempts_c, retries_c, failures_c, backoff_c):
        c.inc(0, pool=pool)

    inj = chaos.active()
    if inj is not None:
        shard_fn = inj.wrap_shard(shard_fn, pool=pool)
    catch = retriable if retriable is not None else (Exception,)
    if inj is not None and retriable is not None:
        # Injections must stay transient under the explicit-tuple mode
        # too: a ChaosShardFault stands in for a preemption (which would
        # raise one of the caller's listed types), so it walks the same
        # retry path instead of escaping the pool on attempt 1.
        catch = tuple(catch) + (ChaosFault,)
    # The pool deadline is the shared resilience Budget type (ISSUE
    # 14): the same arithmetic the serving deadline plane and the drain
    # bound use, so sweep and serving speak one deadline vocabulary.
    budget = None if deadline_s is None else Budget.after(deadline_s)
    device_failures = 0
    deadline_shards = 0

    outcomes = [ShardOutcome(index=i) for i in range(n_shards)]
    for out in outcomes:
        cut = False
        while out.attempts < max_attempts and not out.ok:
            if budget is not None and budget.expired():
                cut = True
                break
            out.attempts += 1
            attempts_c.inc(1, pool=pool)
            try:
                out.result = shard_fn(out.index)
                out.ok = True
                out.error = None
            except catch as e:  # noqa: PERF203 — retry loop
                if retriable is None and classify(e) == "fatal":
                    # Programming error: re-execution replays the bug.
                    obs.emit(
                        "shard_fatal", status="error", pool=pool,
                        shard=out.index, attempt=out.attempts,
                        error=f"{type(e).__name__}: {e}",
                    )
                    raise
                out.error = f"{type(e).__name__}: {e}"
                if log:
                    log(f"shard {out.index} attempt {out.attempts} failed: {out.error}")
                if _is_device_origin(e):
                    device_failures += 1
                    if reprobe_after and device_failures >= reprobe_after:
                        device_failures = 0
                        healthy = (probe or probe_devices)()
                        obs.emit(
                            "device_reprobe", status="ok", pool=pool,
                            healthy=len(healthy), after_shard=out.index,
                        )
                        if redispatch is not None:
                            shard_fn = redispatch(healthy)
                            if inj is not None:
                                shard_fn = inj.wrap_shard(shard_fn, pool=pool)
                if out.attempts < max_attempts:
                    delay = backoff_delay(pool, out.index, out.attempts, backoff_s)
                    if budget is not None and not budget.affords(delay):
                        # The backoff recovery needs does not fit before
                        # the deadline: cut the shard now instead of
                        # spin-retrying with no backoff at all. No retry
                        # is counted — none will run.
                        cut = True
                        break
                    retries_c.inc(1, pool=pool)
                    obs.emit(
                        "shard_retry", status="retrying", pool=pool,
                        shard=out.index, attempt=out.attempts, error=out.error,
                    )
                    backoff_c.inc(delay, pool=pool)
                    time.sleep(delay)
        if not out.ok:
            failures_c.inc(1, pool=pool)
            if cut:
                out.deadline = True
                tail = f"; last error: {out.error}" if out.error else ""
                out.error = (
                    f"DeadlineExceeded: pool {pool!r} deadline of "
                    f"{deadline_s}s reached after {out.attempts} attempt(s)"
                    f"{tail}"
                )
                deadline_shards += 1
                obs.emit(
                    "shard_deadline", status="error", pool=pool,
                    shard=out.index, attempt=out.attempts, error=out.error,
                )
            else:
                obs.emit(
                    "shard_failed", status="error", pool=pool,
                    shard=out.index, attempt=out.attempts, error=out.error,
                )
    if deadline_shards:
        obs.emit(
            "pool_deadline", status="error", pool=pool,
            deadline_s=deadline_s, shards_cut=deadline_shards,
        )
    return outcomes


_DEVICE_ORIGIN_TYPES: tuple[type[BaseException], ...] | None = None


def _is_device_origin(e: BaseException) -> bool:
    """Failures that implicate the device/runtime rather than the shard:
    worth a re-probe. Chaos shard faults count — they stand in for
    preemptions, and the re-probe path is exactly what they test."""
    global _DEVICE_ORIGIN_TYPES
    if _DEVICE_ORIGIN_TYPES is None:
        types: list[type[BaseException]] = [ChaosFault]
        jax_rt = getattr(getattr(jax, "errors", None), "JaxRuntimeError", None)
        if isinstance(jax_rt, type):
            types.append(jax_rt)
        _DEVICE_ORIGIN_TYPES = tuple(types)
    return isinstance(e, _DEVICE_ORIGIN_TYPES)


def require_all(outcomes: Iterable[ShardOutcome]) -> list:
    """Results of fully successful runs; raises if any shard failed —
    :class:`~..resilience.errors.DeadlineExceeded` (a RuntimeError
    subclass, so broad handlers still work) when the pool deadline cut
    any of them, plain RuntimeError otherwise, so callers can route
    deadline pressure (a capacity decision) separately from exhausted
    retries (a health problem)."""
    outcomes = list(outcomes)
    failed = [o for o in outcomes if not o.ok]
    if failed:
        detail = "; ".join(f"shard {o.index}: {o.error}" for o in failed[:5])
        msg = f"{len(failed)}/{len(outcomes)} shards failed: {detail}"
        if any(o.deadline for o in failed):
            raise DeadlineExceeded(msg)
        raise RuntimeError(msg)
    return [o.result for o in outcomes]


def inject_failures(
    shard_fn: Callable[[int], object],
    fail_plan: dict[int, int],
) -> Callable[[int], object]:
    """Plan-based fault injection: ``fail_plan[i] = k`` makes shard
    ``i``'s first ``k`` attempts raise. Kept as the historical name for
    :func:`resilience.chaos.plan_faults` — one injection engine, one
    ``chaos_inject`` event channel."""
    return chaos.plan_faults(shard_fn, fail_plan)

"""Failure detection & elastic recovery (SURVEY.md §5.3).

The reference has no systems-level fault tolerance (a single R process;
its only robustness is numerical — propensity clipping, ``na.rm``). The
TPU framework's unit of work *is* fault-tolerant by construction: every
parallel axis (bootstrap replicate batches, CV folds, tree chunks) is
stateless and idempotent, so recovery is re-execution:

* :func:`probe_devices` — failure detection: run a tiny addition on
  every visible device, report the healthy subset. A dropped axon
  tunnel / preempted slice shows up here instead of as a hang deep in
  an estimator.
* :func:`run_shards` — elastic shard runner: executes independent
  shard thunks sequentially, retrying failures (transient
  ``JaxRuntimeError``, tunnel drops) with exponential backoff.
  Deterministic: each shard owns its RNG key, so a retried shard
  reproduces exactly what the failed attempt would have produced.
  Both forest fitters drive their tree-chunk loops through this.
* :func:`inject_failures` — fault injection for tests: wraps a shard
  function so chosen attempts raise, proving the recovery path.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu import observability as obs


def probe_devices(devices: Sequence | None = None) -> list:
    """Return the subset of ``devices`` (default: all) that complete a
    trivial computation. Failures are caught, not raised — detection,
    not crash."""
    healthy = []
    for d in devices if devices is not None else jax.devices():
        try:
            r = jax.device_put(jnp.ones(()), d) + 1.0
            if float(r) == 2.0:
                healthy.append(d)
        except Exception:
            continue
    return healthy


@dataclasses.dataclass
class ShardOutcome:
    """Bookkeeping for one shard's execution."""

    index: int
    result: object = None
    attempts: int = 0
    ok: bool = False
    error: str | None = None


def run_shards(
    shard_fn: Callable[[int], object],
    n_shards: int,
    max_attempts: int = 3,
    backoff_s: float = 0.25,
    log: Callable[[str], None] | None = None,
    retriable: tuple[type[BaseException], ...] = (Exception,),
    pool: str = "shards",
) -> list[ShardOutcome]:
    """Run ``shard_fn(i)`` for every shard ``i`` with per-shard retry.

    Shards must be independent and idempotent (they are: bootstrap
    batches, folds and tree chunks carry their own fold-in keys). A
    shard that exhausts ``max_attempts`` is reported failed in its
    :class:`ShardOutcome`; the others still complete — callers decide
    whether partial coverage is acceptable (e.g. 9/10 bootstrap batches
    still estimate an SE) or raise via :func:`require_all`.

    ``pool`` labels this call's telemetry: attempts / retries /
    failures / backoff-seconds counters (observability/), created at
    zero up front so a healthy run still exports the keys — "no
    retries" is a reported fact, not a missing metric. Retries and
    exhaustions additionally land in the event log with the error
    string, which is how a transient-tunnel-drop diagnosis stops
    requiring print archaeology.
    """
    attempts_c = obs.counter("shard_attempts_total", "run_shards attempts")
    retries_c = obs.counter("shard_retries_total", "failed attempts that will retry")
    failures_c = obs.counter("shard_failures_total", "shards that exhausted retries")
    backoff_c = obs.counter("shard_backoff_seconds_total", "backoff sleep time")
    for c in (attempts_c, retries_c, failures_c, backoff_c):
        c.inc(0, pool=pool)
    outcomes = [ShardOutcome(index=i) for i in range(n_shards)]
    for out in outcomes:
        delay = backoff_s
        while out.attempts < max_attempts and not out.ok:
            out.attempts += 1
            attempts_c.inc(1, pool=pool)
            try:
                out.result = shard_fn(out.index)
                out.ok = True
            except retriable as e:  # noqa: PERF203 — retry loop
                out.error = f"{type(e).__name__}: {e}"
                if log:
                    log(f"shard {out.index} attempt {out.attempts} failed: {out.error}")
                if out.attempts < max_attempts:
                    retries_c.inc(1, pool=pool)
                    obs.emit(
                        "shard_retry", status="retrying", pool=pool,
                        shard=out.index, attempt=out.attempts, error=out.error,
                    )
                    backoff_c.inc(delay, pool=pool)
                    time.sleep(delay)
                    delay *= 2.0
                else:
                    failures_c.inc(1, pool=pool)
                    obs.emit(
                        "shard_failed", status="error", pool=pool,
                        shard=out.index, attempt=out.attempts, error=out.error,
                    )
    return outcomes


def require_all(outcomes: Iterable[ShardOutcome]) -> list:
    """Results of fully successful runs; raises if any shard failed."""
    outcomes = list(outcomes)
    failed = [o for o in outcomes if not o.ok]
    if failed:
        detail = "; ".join(f"shard {o.index}: {o.error}" for o in failed[:5])
        raise RuntimeError(f"{len(failed)}/{len(outcomes)} shards failed: {detail}")
    return [o.result for o in outcomes]


def inject_failures(
    shard_fn: Callable[[int], object],
    fail_plan: dict[int, int],
) -> Callable[[int], object]:
    """Fault injection: ``fail_plan[i] = k`` makes shard ``i``'s first
    ``k`` attempts raise. For testing recovery paths."""
    remaining = dict(fail_plan)

    def wrapped(i: int):
        if remaining.get(i, 0) > 0:
            remaining[i] -= 1
            raise RuntimeError(f"injected fault on shard {i}")
        return shard_fn(i)

    return wrapped

"""Device-mesh configuration — the framework's communication "backend".

The reference has no distributed execution at all (one R process,
SURVEY.md §2.4); the TPU build's parallel axes are the reference's
embarrassingly parallel structures mapped onto a ``jax.sharding.Mesh``:

  * ``boot`` — bootstrap replicates (``ate_functions.R:192-194``)
  * ``tree`` — forest trees (randomForest / grf tree loops)
  * ``fold`` — CV / cross-fitting folds (``cv.glmnet``; ``double_ml``)
  * ``data`` — row sharding for the 1M-row regime, with ``psum``
    reductions for X'X / gradient sums over ICI

XLA compiles the collectives; there is no hand-written transport layer
(the scaling-book recipe: pick a mesh, annotate shardings, let XLA
insert collectives).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# ``jax.shard_map`` landed as a top-level API after the experimental
# namespace; this image's jax (0.4.37) only has the experimental one,
# and the replication-check kwarg was renamed check_rep → check_vma
# across the same span. Every in-tree caller imports the symbol from
# here; the wrapper translates whichever spelling the installed jax
# does not accept.
def _resolve_shard_map():
    import inspect

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = set(inspect.signature(sm).parameters)
    except (TypeError, ValueError):
        return sm

    def compat(*args, **kwargs):
        if "check_vma" in kwargs and "check_vma" not in params:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        elif "check_rep" in kwargs and "check_rep" not in params:
            kwargs["check_vma"] = kwargs.pop("check_rep")
        # graftlint: disable=JGL018 — not a launch site: this shim IS the `shard_map` symbol shardio's laned launchers call under the lane lock
        return sm(*args, **kwargs)

    return compat


shard_map = _resolve_shard_map()

# Canonical axis names used across the framework.
BOOT_AXIS = "boot"
TREE_AXIS = "tree"
FOLD_AXIS = "fold"
DATA_AXIS = "data"

# The process-wide default mesh (``set_mesh`` / lazy ``make_mesh``)
# plus a per-thread ``use_mesh`` override. The override is thread-local
# on purpose (ISSUE 4): the concurrent sweep runs stage bodies on
# worker threads, and a mesh-lane stage sitting inside
# ``use_mesh(fold_mesh)`` must not hand the fold mesh to an unlaned
# stage calling ``get_mesh()`` from another thread — that caller would
# launch a collective outside the lane, exactly the rendezvous
# interleaving the lane serializes against.
_DEFAULT_MESH: Mesh | None = None
_DEFAULT_MESH_LOCK = threading.Lock()
_TLS = threading.local()


def make_mesh(
    axis_names: Sequence[str] = (BOOT_AXIS,),
    axis_sizes: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Default: one axis spanning every device — right for the
    embarrassingly parallel estimator loops. Multi-axis shapes (e.g.
    ``("data", "boot")``) reshape the device array accordingly.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = [len(devs)] + [1] * (len(axis_names) - 1)
    devs = devs[: int(np.prod(axis_sizes))].reshape(tuple(axis_sizes))
    return Mesh(devs, tuple(axis_names))


def set_mesh(mesh: Mesh) -> None:
    global _DEFAULT_MESH
    with _DEFAULT_MESH_LOCK:
        _DEFAULT_MESH = mesh


def get_mesh() -> Mesh:
    """The active mesh: this thread's ``use_mesh`` override if one is
    live, else the process default (a single-axis mesh over all
    devices, built lazily)."""
    override = getattr(_TLS, "mesh", None)
    if override is not None:
        return override
    global _DEFAULT_MESH
    with _DEFAULT_MESH_LOCK:
        if _DEFAULT_MESH is None:
            _DEFAULT_MESH = make_mesh()
        return _DEFAULT_MESH


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    prev = getattr(_TLS, "mesh", None)
    _TLS.mesh = mesh
    try:
        yield mesh
    finally:
        _TLS.mesh = prev


def shard_axis_size(mesh: Mesh, axis_name: str) -> int:
    return mesh.shape[axis_name]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) dimension of an array across ``axis_name``."""
    return NamedSharding(mesh, P(axis_name))

"""Device-resident sharded artifact I/O — the metered host/device
boundary of the nuisance-artifact plane (ISSUE 8).

PR 4's scheduler deliberately HOST-materialized every mesh-lane
artifact before releasing the lane (``pipeline.materialized()``: a
``np.asarray`` → ``jnp.asarray`` double copy per artifact). That was
correct — a sharded array consumed by an unlaned stage would compile
its ops into collectives outside the lane — but it makes every
producer→consumer handoff pay host bandwidth twice and caps the
cross-fitting data axis at what one host can stream. This module is
the replacement: artifacts live on device as ``NamedSharding``
-annotated arrays, and every byte that crosses a layout boundary moves
through one of the functions below, which

* compile each shard/gather/reshard path ONCE per (pytree-structure,
  sharding) pair — a process-global cache of ``jax.jit`` identities in
  the style of SNIPPETS [1] (``make_shard_and_gather_fns``) and [3]
  (paired in/out shardings on compiled fns) — and
* meter every call into ``artifact_transfer_bytes_total{artifact,path}``
  (bytes moved, by path) and ``artifact_reshard_total{artifact,status}``
  (calls, by compile status), the two counter families
  ``scripts/check_metrics_schema.py`` requires on every instrumented
  run.

Byte paths (``path=`` label values):

* ``host_upload``   — host → device commit of a host-resident leaf
  (``jax.device_put`` onto the declared sharding; no XLA program).
* ``device_reshard`` — device → device layout change (compiled
  identity with ``out_shardings``; a COLLECTIVE program — callers that
  own a mesh lane run it inside ``lane_lock``, see scheduler/cache.py).
* ``device_handoff`` — a consumer took the device-resident form as-is:
  bytes that stayed on device, the zero-host-byte laned→laned edge.
* ``host_gather``   — device → host: compiled all-gather to replicated
  (collective, lane discipline as above) then ONE ``device_get``. The
  single host crossing an unlaned consumer pays.
* ``host_bounce``   — the LEGACY materialized() double copy (full host
  materialization immediately re-uploaded), kept only as the metered
  "before" number for ``bench.py --mesh-scaling``; the sweep itself
  must never hit this path (regression-tested).

Lane discipline is the CALLER's job (``scheduler/cache.py`` wraps the
collective paths in ``lane_lock``); this module is policy-free data
movement. Everything here is synchronous: :func:`commit` blocks until
the transfer/collective has drained, preserving ``materialized()``'s
second job — a mesh lane is released only after the artifact's device
work completed, not merely enqueued.
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.parallel.mesh import DATA_AXIS

BYTES_FAMILY = "artifact_transfer_bytes_total"
CALLS_FAMILY = "artifact_reshard_total"

PATH_UPLOAD = "host_upload"
PATH_RESHARD = "device_reshard"
PATH_HANDOFF = "device_handoff"
PATH_GATHER = "host_gather"
PATH_BOUNCE = "host_bounce"

#: compiled identity per target sharding + signatures already compiled,
#: so each reshard path compiles once (status=compiled vs cached).
_JITS: dict[Any, Any] = {}
_SEEN: set[tuple] = set()
_LOCK = threading.Lock()


def _bytes_counter():
    return obs.counter(
        BYTES_FAMILY,
        "artifact-plane bytes moved by path (host_upload / device_reshard"
        " / device_handoff / host_gather / host_bounce)",
    )


def _calls_counter():
    return obs.counter(
        CALLS_FAMILY,
        "artifact-plane shard/gather/reshard calls by compile status",
    )


def leaf_nbytes(leaf) -> int:
    """Payload bytes of one array-like leaf without touching device
    memory (``np.asarray`` on a jax array would be a device_get)."""
    size = getattr(leaf, "size", None)
    dtype = getattr(leaf, "dtype", None)
    if size is None or dtype is None:
        arr = np.asarray(leaf)
        size, dtype = arr.size, arr.dtype
    return int(size) * np.dtype(dtype).itemsize


def tree_nbytes(tree) -> int:
    """Total payload bytes of a pytree of arrays."""
    return sum(leaf_nbytes(l) for l in jax.tree_util.tree_leaves(tree))


def row_sharding(mesh, n: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard dim 0 of an n-row array over ``axis`` — falling back to
    replicated when ``n`` does not divide the axis size: this image's
    jax (0.4.37) rejects uneven shards at the ``device_put`` /
    ``out_shardings`` API level, and a replicated declaration is still
    device-resident (the lane discipline and zero-host-byte handoffs
    are unchanged; only the per-device memory footprint differs).

    The replicated fallback is wasteful at scale (NEXT §4: a 100M-row
    panel replicated 8× is 8× the memory for zero parallelism) — for
    shape-owning callers, :func:`shard_rows_padded` lifts it: pad dim 0
    to the next axis multiple, shard evenly, and carry the row mask.
    This function keeps the fallback because it declares a LAYOUT for
    an existing value whose shape its consumers already depend on
    (padding here would silently change every consumer's row count)."""
    if n % mesh.shape[axis] == 0:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of ``k`` that is >= ``n`` (and >= k: zero rows
    still occupy one empty shard per device)."""
    return max(1, -(-n // k)) * k


def pad_rows(tree, multiple: int):
    """Zero-pad dim 0 of every leaf up to the next ``multiple`` —
    host-side (numpy) so the padding itself never touches the device;
    the single upload happens in :func:`shard_rows_padded`'s metered
    commit. Returns ``(padded_tree, n)`` where ``n`` is the original
    row count (leaves must agree on it)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return tree, 0
    ns = {int(np.shape(l)[0]) for l in leaves}
    if len(ns) != 1:
        raise ValueError(f"pad_rows: leaves disagree on row count: {sorted(ns)}")
    (n,) = ns
    target = pad_to_multiple(n, multiple)

    def per_leaf(leaf):
        host = np.asarray(leaf)
        if target == n:
            return host
        pad = [(0, target - n)] + [(0, 0)] * (host.ndim - 1)
        return np.pad(host, pad)

    return jax.tree_util.tree_map(per_leaf, tree), n


def row_mask(n: int, padded: int, dtype=np.float32) -> np.ndarray:
    """The (padded,) 0/1 row mask: 1.0 for the first ``n`` real rows,
    exact zeros on the pad — the round-5 traced-0/1-flag discipline
    (``mask·x ≡ x`` exactly on real rows, pad contributions vanish
    exactly under ``sum(mask * ...)``)."""
    mask = np.zeros((padded,), dtype=dtype)
    mask[:n] = 1.0
    return mask


def shard_rows_padded(tree, mesh, axis: str = DATA_AXIS, artifact: str = ""):
    """The pad-to-divisible row shard lifting :func:`row_sharding`'s
    replicated fallback (ISSUE 13 satellite): pad dim 0 of every leaf
    to the next ``axis``-size multiple, commit the padded tree onto an
    EVEN row sharding (metered ``host_upload``, blocked until drained),
    and return ``(device_tree, mask, n)`` where ``mask`` is the sharded
    (padded,) 0/1 row mask and ``n`` the real row count. Compute over
    the shards must gate row contributions on the mask (exact: the pad
    rows are exact zeros and the mask is exact 0/1);
    :func:`gather_rows_padded` inverts the transform bit-identically."""
    padded, n = pad_rows(tree, mesh.shape[axis])
    sh = NamedSharding(mesh, P(axis))
    dev = commit(padded, sh, artifact=artifact)
    first = jax.tree_util.tree_leaves(padded)
    target = int(np.shape(first[0])[0]) if first else 0
    mask = commit(row_mask(n, target), sh,
                  artifact=f"{artifact}_mask" if artifact else "row_mask")
    return dev, mask, n


def gather_rows_padded(tree, n: int, artifact: str = ""):
    """Inverse of :func:`shard_rows_padded`'s data leg: one metered
    host gather of the padded device tree, then strip the pad rows.
    Returns read-only numpy leaves of exactly ``n`` rows, bit-identical
    to the unpadded input (asserted at 1/2/4/8 devices in
    tests/test_shardio.py)."""
    host = gather_host(tree, artifact=artifact)

    def per_leaf(leaf):
        if not isinstance(leaf, np.ndarray):
            return leaf
        out = leaf[:n]
        out.flags.writeable = False
        return out

    return jax.tree_util.tree_map(per_leaf, host)


def _spec_tree(tree, sharding):
    """Broadcast a single Sharding over the value's pytree; a matching
    pytree of shardings passes through."""
    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(lambda _: sharding, tree)
    return sharding


def _jit_to(dst):
    with _LOCK:
        fn = _JITS.get(dst)
        if fn is None:
            fn = _JITS[dst] = jax.jit(lambda a: a, out_shardings=dst)
        return fn


def _block(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return tree


def _move_leaf(leaf, dst, artifact: str, calls, moved: list) -> Any:
    """One leaf onto sharding ``dst`` via the compiled identity for that
    path; ``moved`` accumulates bytes that actually changed layout."""
    if getattr(leaf, "sharding", None) == dst:
        calls.inc(1, artifact=artifact, status="noop")
        return leaf
    sig = (
        tuple(getattr(leaf, "shape", ())),
        str(getattr(leaf, "dtype", "")),
        getattr(leaf, "sharding", None),
        dst,
    )
    with _LOCK:
        seen = sig in _SEEN
        _SEEN.add(sig)
    out = _jit_to(dst)(leaf)
    calls.inc(1, artifact=artifact, status="cached" if seen else "compiled")
    moved.append(leaf_nbytes(leaf))
    return out


def commit(tree, sharding, artifact: str = "") -> Any:
    """Commit a fit's output onto its DECLARED device-resident sharding
    and block until the transfer/collective drained (the lane-release
    discipline). Host leaves upload via ``device_put`` (metered
    ``host_upload``); device leaves reshard through the compiled path
    (metered ``device_reshard``); leaves already in layout are noops."""
    specs = _spec_tree(tree, sharding)
    b, c = _bytes_counter(), _calls_counter()
    moved: list[int] = []
    uploaded: list[int] = []

    def per_leaf(leaf, dst):
        if not isinstance(leaf, jax.Array):
            out = jax.device_put(np.asarray(leaf), dst)
            c.inc(1, artifact=artifact, status="upload")
            uploaded.append(leaf_nbytes(leaf))
            return out
        return _move_leaf(leaf, dst, artifact, c, moved)

    out = _block(jax.tree_util.tree_map(per_leaf, tree, specs))
    if uploaded:
        b.inc(sum(uploaded), artifact=artifact, path=PATH_UPLOAD)
    if moved:
        b.inc(sum(moved), artifact=artifact, path=PATH_RESHARD)
    return out


def reshard(tree, sharding, artifact: str = "") -> Any:
    """Device → device layout change onto ``sharding`` (a collective —
    lane-owning callers run it inside ``lane_lock``)."""
    specs = _spec_tree(tree, sharding)
    b, c = _bytes_counter(), _calls_counter()
    moved: list[int] = []
    out = _block(jax.tree_util.tree_map(
        lambda leaf, dst: _move_leaf(leaf, dst, artifact, c, moved),
        tree, specs,
    ))
    if moved:
        b.inc(sum(moved), artifact=artifact, path=PATH_RESHARD)
    return out


def handoff(tree, artifact: str = "") -> Any:
    """Meter a zero-host-byte device-resident handoff: the consumer
    declared the stored layout, so the bytes recorded under
    ``device_handoff`` are bytes that did NOT cross the host bus — the
    laned→laned edge the mesh-scaling record pins at zero host bytes."""
    _bytes_counter().inc(tree_nbytes(tree), artifact=artifact,
                         path=PATH_HANDOFF)
    return tree


def _replicated_like(leaf) -> NamedSharding | None:
    sh = getattr(leaf, "sharding", None)
    if isinstance(sh, NamedSharding) and not sh.is_fully_replicated:
        return NamedSharding(sh.mesh, P())
    return None


def gather_host(tree, artifact: str = "") -> Any:
    """Device → host: all-gather each sharded leaf to replicated
    through the compiled path (a collective — lane discipline applies),
    then ONE ``device_get``. Returns a host (numpy) pytree: the single
    metered host crossing an unlaned consumer pays, replacing the
    legacy double copy."""
    b, c = _bytes_counter(), _calls_counter()
    moved: list[int] = []

    def per_leaf(leaf):
        if not isinstance(leaf, jax.Array):
            return leaf
        rep = _replicated_like(leaf)
        if rep is not None:
            leaf = _move_leaf(leaf, rep, artifact, c, moved)
        host = np.asarray(jax.device_get(leaf))
        # Read-only: the host form is CACHED and shared by every host
        # consumer (scheduler/cache.py) — an in-place write in one stage
        # body must fail loudly, not corrupt the others' inputs.
        host.flags.writeable = False
        b.inc(host.nbytes, artifact=artifact, path=PATH_GATHER)
        return host

    out = jax.tree_util.tree_map(per_leaf, tree)
    if moved:
        # The all-gather's own device traffic: every byte the plane
        # moves is metered, including the collective feeding a gather.
        b.inc(sum(moved), artifact=artifact, path=PATH_RESHARD)
    return out


def host_bounce(tree, artifact: str = "") -> Any:
    """The LEGACY ``materialized()`` path, kept only as the metered
    before-number for ``bench.py --mesh-scaling``: full host
    materialization (``np.asarray`` — a per-shard fetch and host
    assemble) immediately re-uploaded via ``jnp.asarray``. Pays host
    bandwidth TWICE per call (metered ``host_bounce`` = 2×payload).
    The sweep must never reach this path — tests assert its counter
    stays zero on every scheduled run."""
    import jax.numpy as jnp

    b = _bytes_counter()

    def per_leaf(leaf):
        host = np.asarray(leaf)
        b.inc(2 * host.nbytes, artifact=artifact, path=PATH_BOUNCE)
        return jnp.asarray(host)

    return _block(jax.tree_util.tree_map(per_leaf, tree))


def edge_byte_plan(nbytes: int, producer_lane: str | None,
                   consumer_lane: str | None) -> dict:
    """Deterministic per-edge host/device byte accounting — the
    quantity that IS the multi-chip bandwidth win when devices are
    physical, pinned by ``tests/test_mesh_scaling.py`` without running
    a backend (the dispatch-plan pattern of ``plan_tree_dispatch``).

    A laned→laned edge (producer and consumer share a mesh lane, the
    consumer declared the device layout) hands the artifact off fully
    on-device: zero host bytes. Any other edge pays exactly one
    device→host gather. The legacy PR-4 ``materialized()`` path paid
    ``2×nbytes`` host bytes on EVERY edge — the before column."""
    laned_to_laned = producer_lane is not None and producer_lane == consumer_lane
    return {
        "host_bytes": 0 if laned_to_laned else nbytes,
        "device_bytes": nbytes if laned_to_laned else 0,
        "legacy_host_bytes": 2 * nbytes,
    }

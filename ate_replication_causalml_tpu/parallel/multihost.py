"""Multi-host (multi-slice) initialization — the DCN scale-out path.

The reference has no communication backend at all (one R process,
SURVEY.md §2.4/§5.8). This framework's equivalent of an NCCL/MPI world
is JAX's distributed runtime: every host calls
:func:`init_multihost`, after which ``jax.devices()`` spans the pod and
the same mesh/shard_map code compiles to ICI collectives within a slice
and DCN transfers across slices.

The framework's parallel axes place cleanly on a multi-slice mesh:

* ``boot`` / ``tree`` / ``fold`` — embarrassingly parallel, zero
  tight coupling: put these on the OUTER (DCN) mesh dimension, so
  cross-slice traffic is one result-gather per estimator.
* ``data`` — row sharding with psum reductions: keep within a slice
  (ICI) via the inner mesh dimension.

``make_pod_mesh`` encodes exactly that layout.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.parallel.mesh import BOOT_AXIS, DATA_AXIS


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize JAX's distributed runtime when running multi-process.

    On TPU pods the arguments are discovered from the environment, so
    bare ``init_multihost()`` is correct there — call it BEFORE anything
    touches ``jax.devices()`` (``jax.distributed.initialize`` refuses to
    run once the backend exists, which is also why this function never
    probes the backend before initializing). Single-process runs
    (tests, one chip, CPU meshes) return False and everything else
    works identically.
    """
    if num_processes == 1:
        return False  # explicit single-process: documented no-op
    explicit = (coordinator_address, num_processes, process_id) != (None, None, None)
    kwargs = (
        dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        if explicit
        else {}
    )
    def _done(ok: bool, how: str) -> bool:
        # World-shape telemetry: the event log records how this process
        # joined (or didn't), and the gauges make a mis-sized world
        # visible in metrics.json without grepping launcher logs.
        obs.emit("multihost_init", status="ok" if ok else "skipped", how=how)
        if ok:
            obs.gauge("process_count", "jax.process_count()").set(
                jax.process_count()
            )
            obs.gauge("device_count", "jax.device_count()").set(
                jax.device_count()
            )
        return ok

    try:
        jax.distributed.initialize(**kwargs)
        return _done(True, "initialized")
    except RuntimeError as e:
        if "already" in str(e).lower():
            # A launcher (or an early devices() call) initialized first;
            # report whether a multi-process world actually exists.
            return _done(jax.process_count() > 1, "already_initialized")
        raise
    except ValueError:
        if explicit:
            raise  # misconfigured explicit args must not be swallowed
        # Auto-detection found no multi-host environment.
        return _done(False, "not_detected")


def make_pod_mesh(
    replicate_axis: str = BOOT_AXIS,
    data_axis: str = DATA_AXIS,
    data_parallel_per_slice: int | None = None,
) -> Mesh:
    """Two-axis pod mesh: (replicates over DCN+remaining ICI, rows over
    ICI). The replicate axis carries bootstrap/tree/fold work — pure
    fan-out, so it tolerates DCN latency; the data axis carries psum
    reductions, so it stays inside a slice.

    ``data_parallel_per_slice`` defaults to the size of the first
    slice, read from the devices' ``slice_index`` attribute (TPU
    multi-slice); when the platform has no slice notion (CPU meshes,
    single slice) it is all devices. Pass it explicitly to subdivide.
    """
    from ate_replication_causalml_tpu.parallel.mesh import make_mesh

    devs = list(jax.devices())
    if data_parallel_per_slice is None:
        s0 = getattr(devs[0], "slice_index", None)
        if s0 is not None:
            data_parallel_per_slice = sum(
                1 for d in devs if getattr(d, "slice_index", None) == s0
            )
        else:
            data_parallel_per_slice = len(devs)
    data_parallel_per_slice = min(max(1, data_parallel_per_slice), len(devs))
    n_rep = len(devs) // data_parallel_per_slice
    used = n_rep * data_parallel_per_slice
    if used < len(devs):
        import warnings

        warnings.warn(
            f"make_pod_mesh: {len(devs) - used} of {len(devs)} devices idle "
            f"(device count not divisible by data_parallel_per_slice="
            f"{data_parallel_per_slice})",
            RuntimeWarning,
            stacklevel=2,
        )
    return make_mesh(
        (replicate_axis, data_axis), (n_rep, data_parallel_per_slice)
    )

"""Admission control and the daemon lifecycle (ISSUE 6 — no jax).

Three small, separately testable pieces of the serving core:

* :class:`AdmissionController` — a bounded in-flight request count.
  Past ``max_depth`` new work is REJECTED with a typed retry-after
  instead of queued: an unbounded queue converts overload into
  unbounded latency for every client; a bounded one converts it into an
  explicit, immediately visible backpressure signal the client can act
  on. Depth is requests, not rows — the row budget is the coalescer's
  bucket plan.
* :class:`ServingLifecycle` — the ``starting → serving ⇄ degraded →
  draining → stopped`` state machine. Transitions are explicit and
  invalid ones raise: a daemon that silently serves from the wrong
  state is the failure mode this class exists to make impossible.
  ``draining`` (ISSUE 14) is the graceful-shutdown window: admission
  rejects new work typed (``draining`` + retry-after), in-flight
  batches complete, artifacts dump, and the process exits within the
  configured bound.
* :class:`ReloadSupervisor` — degraded-mode recovery. Concurrent fault
  reports coalesce into ONE reload attempt (first reporter wins, the
  rest see False), the reload re-verifies the checkpoint before any
  swap, and a failed reload leaves the lifecycle DEGRADED — a corrupt
  checkpoint must never rotate back into service. The reload callable
  is injected, so the whole recovery state machine is provable without
  jax or a real checkpoint (tests drive it with stubs that fail then
  succeed).
"""

from __future__ import annotations

import threading
from typing import Callable

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry

#: Lifecycle states.
STARTING = "starting"
SERVING = "serving"
DEGRADED = "degraded"
DRAINING = "draining"
STOPPED = "stopped"


class InvalidTransition(RuntimeError):
    """A lifecycle method was called from a state it is not legal in."""


class AdmissionController:
    """Bounded in-flight request count with reject-on-overload."""

    def __init__(self, max_depth: int):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        self._depth = 0
        self._gauge = _registry.gauge(
            "serving_queue_depth", "admitted in-flight serving requests"
        )

    def try_admit(self) -> bool:
        """Admit one request, or refuse (caller rejects typed —
        ``overloaded`` + retry-after). Never blocks."""
        with self._lock:
            if self._depth >= self.max_depth:
                return False
            self._depth += 1
            depth = self._depth
        self._gauge.set(depth)
        return True

    def release(self) -> None:
        with self._lock:
            if self._depth <= 0:
                raise RuntimeError("release() without a matching admit")
            self._depth -= 1
            depth = self._depth
        self._gauge.set(depth)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth


class ServingLifecycle:
    """The daemon's state machine; every transition is an event."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = STARTING
        self._fault_count = 0
        self._reload_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def can_serve(self) -> bool:
        return self.state == SERVING

    def _transition(self, to: str, allowed: tuple[str, ...]) -> None:
        with self._lock:
            if self._state not in allowed:
                raise InvalidTransition(
                    f"cannot move {self._state!r} -> {to!r} "
                    f"(legal from: {', '.join(allowed)})"
                )
            frm, self._state = self._state, to
        _events.emit("serving_state", status="ok", frm=frm, to=to)

    def mark_ready(self) -> None:
        """Startup complete (checkpoint verified, executables compiled,
        warm dispatches done): STARTING → SERVING."""
        self._transition(SERVING, (STARTING,))

    def mark_fault(self, reason: str) -> bool:
        """Report a serving fault. Returns True to exactly one caller —
        the one that moved SERVING → DEGRADED and therefore owns
        recovery; concurrent reporters (and reports while already
        degraded) get False and must only reject-with-retry-after."""
        with self._lock:
            self._fault_count += 1
            if self._state != SERVING:
                return False
            self._state = DEGRADED
        _events.emit("serving_state", status="error", frm=SERVING,
                     to=DEGRADED, reason=reason)
        return True

    def mark_recovered(self) -> None:
        """Recovery verified: DEGRADED → SERVING."""
        self._transition(SERVING, (DEGRADED,))  # raises before counting
        with self._lock:
            self._reload_count += 1

    def mark_draining(self) -> bool:
        """Begin graceful drain (ISSUE 14): legal from any live state
        (a degraded or still-starting daemon can be told to go away
        too). Returns True to exactly one caller — the one that moved
        the lifecycle into DRAINING and therefore owns the drain;
        concurrent calls (and calls once stopped) get False."""
        with self._lock:
            if self._state in (DRAINING, STOPPED):
                return False
            frm, self._state = self._state, DRAINING
        _events.emit("serving_state", status="ok", frm=frm, to=DRAINING)
        return True

    def mark_stopped(self) -> None:
        """Terminal from any state (idempotent — a double stop is not
        an error worth crashing a shutdown path over)."""
        with self._lock:
            if self._state == STOPPED:
                return
            frm, self._state = self._state, STOPPED
        _events.emit("serving_state", status="ok", frm=frm, to=STOPPED)

    @property
    def fault_count(self) -> int:
        with self._lock:
            return self._fault_count

    @property
    def reload_count(self) -> int:
        with self._lock:
            return self._reload_count


class ReloadSupervisor:
    """Owns degraded-mode recovery AND zero-downtime rotation: one
    reload/rotation at a time, verified before swap, failure stays on
    the last good model.

    ``reload_fn`` re-loads AND re-verifies the model source (the
    daemon wires the SHA-256-verified ``load_fitted``); ``on_reloaded``
    installs the result (the daemon swaps its model reference under its
    own lock). ``inline=True`` runs recovery on the reporting thread
    (deterministic tests); the daemon uses a background thread so the
    request path only ever sees typed rejects, never a reload's
    latency.

    :meth:`rotate` (ISSUE 11) shares the same single-flight claim —
    a rotation can never race a degraded-mode reload into two
    concurrent installs — but differs in failure semantics: a reload
    failure STAYS degraded (the served model was already suspect); a
    rotation refusal keeps SERVING on the last good model (the served
    model was never the problem — only the candidate was).
    """

    def __init__(
        self,
        lifecycle: ServingLifecycle,
        reload_fn: Callable[[], object],
        on_reloaded: Callable[[object], None],
        inline: bool = False,
    ):
        self._lifecycle = lifecycle
        self._reload_fn = reload_fn
        self._on_reloaded = on_reloaded
        self._inline = inline
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        # Single-flight guard: exactly one reload may be in flight. Set
        # under ONE lock acquisition before any spawn (a check-then-act
        # split across acquisitions would let report_fault and retry()
        # race each other into two concurrent reloads, the loser dying
        # on the DEGRADED->SERVING double-transition).
        self._running = False
        self._counter = _registry.counter(
            "serving_reloads_total", "degraded-mode reload attempts by status"
        )
        self._rotations = _registry.counter(
            "serving_rotations_total",
            "checkpoint hot-swap rotations by model and status",
        )

    def _try_begin(self) -> bool:
        with self._lock:
            if self._running:
                return False
            self._running = True
            return True

    def _launch(self, reason: str) -> None:
        """Caller holds the single-flight claim (_try_begin)."""
        if self._inline:
            self._run(reason)
            return
        with self._lock:
            t = threading.Thread(
                target=self._run, args=(reason,),
                name="serving-reload", daemon=True,
            )
            self._thread = t
        t.start()

    def report_fault(self, reason: str) -> bool:
        """Fault entry point for the request path. Returns True when
        this report triggered recovery (it coalesces otherwise)."""
        if not self._lifecycle.mark_fault(reason):
            return False
        if not self._try_begin():
            # A recovery is already in flight (e.g. an operator retry);
            # this fault report coalesces into it.
            return False
        self._launch(reason)
        return True

    def join(self, timeout: float | None = None) -> None:
        """Wait for an in-flight background recovery (tests and
        shutdown; no-op inline or when none ran)."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout)

    def _run(self, reason: str) -> None:
        recovered = False
        try:
            with _events.span("serving_reload", reason=reason) as sp:
                try:
                    obj = self._reload_fn()
                    self._on_reloaded(obj)
                except Exception as e:
                    # The typed refusal path: the lifecycle STAYS
                    # degraded (requests keep getting retry-after), the
                    # failure is recorded, and the next retry() may try
                    # again — a corrupt checkpoint must never rotate
                    # into service.
                    sp.set_status("error")
                    self._counter.inc(1, status="failed")
                    _events.emit(
                        "serving_reload_failed", status="error",
                        reason=reason, error=f"{type(e).__name__}: {e}",
                    )
                    return
                self._counter.inc(1, status="reloaded")
                self._lifecycle.mark_recovered()
                recovered = True
        finally:
            with self._lock:
                self._running = False
            # A fault reported between mark_recovered and the claim
            # release found the lifecycle SERVING (it owns recovery)
            # but the claim still held (its launch coalesced into
            # nothing) — pick that orphaned recovery up now. Only
            # after a SUCCESSFUL run: a failed reload staying degraded
            # without relaunching is the deliberate refusal contract.
            if recovered and self._lifecycle.state == DEGRADED:
                self.retry()

    def retry(self) -> bool:
        """Explicitly retry a failed recovery (an operator action or a
        timer): runs a reload if the lifecycle is degraded and no
        recovery is in flight. Returns whether a reload ran."""
        if self._lifecycle.state != DEGRADED:
            return False
        if not self._try_begin():
            return False
        # The lifecycle can only have LEFT degraded through the reload
        # that just released the claim; re-check before spawning so a
        # retry racing a successful recovery is a no-op, not a crash.
        if self._lifecycle.state != DEGRADED:
            with self._lock:
                self._running = False
            return False
        self._launch("retry")
        return True

    def rotate(
        self,
        loader: Callable[[], object],
        installer: Callable[[object], None] | None = None,
        reason: str = "rotate",
        model: str = "",
    ) -> str:
        """Zero-downtime verified hot-swap (ISSUE 11). Runs on the
        CALLING thread (rotation callers are the retrain supervisor or
        an operator op — never the request path): ``loader`` loads and
        re-verifies the candidate checkpoint, ``installer`` (default
        ``on_reloaded``) swaps it in atomically. Returns a status
        string:

        * ``"rotated"`` — verified and installed; new admissions bind
          the new model, in-flight batches complete against the old
          reference, and the lifecycle never leaves SERVING (a
          rotation that lands while DEGRADED doubles as recovery).
        * ``"refused"`` — the candidate failed verification (corrupt
          digest, changed geometry, a fault mid-swap): NOTHING was
          installed and the last good model keeps serving. A corrupt
          published checkpoint can never rotate into service.
        * ``"busy"`` — another reload/rotation holds the single-flight
          claim; the caller retries later. One reload, one verify.
        """
        install = installer if installer is not None else self._on_reloaded
        if not self._try_begin():
            self._rotations.inc(1, model=model, status="busy")
            _events.emit("serving_rotation_busy", status="error",
                         model=model, reason=reason)
            return "busy"
        try:
            with _events.span("serving_rotation", reason=reason,
                              model=model) as sp:
                try:
                    obj = loader()
                    install(obj)
                except Exception as e:
                    # Typed refusal: last good model keeps serving, the
                    # lifecycle is untouched (rotation is not a fault).
                    sp.set_status("error")
                    self._rotations.inc(1, model=model, status="refused")
                    _events.emit(
                        "serving_rotation_refused", status="error",
                        model=model, reason=reason,
                        error=f"{type(e).__name__}: {e}",
                    )
                    return "refused"
                self._rotations.inc(1, model=model, status="rotated")
                # The swap instant: rendered as an instant marker on
                # the rotating thread's trace track.
                _events.emit("serving_rotated", status="ok", model=model,
                             reason=reason)
                if self._lifecycle.state == DEGRADED:
                    self._lifecycle.mark_recovered()
                return "rotated"
        finally:
            with self._lock:
                self._running = False
            # A fault reported WHILE this rotation held the claim owned
            # recovery but could not launch it (its report coalesced
            # into the rotation, and a refused rotation does not
            # recover anything). Orphaned-degraded here would otherwise
            # persist until an operator retry — launch the reload now
            # that the claim is free.
            if self._lifecycle.state == DEGRADED:
                self.retry()

"""CATE serving daemon (ISSUE 6): AOT-compiled predict-as-a-service.

The subsystem splits along the jax boundary:

* no-jax core (importable anywhere, unit-tested in tier-1):
  :mod:`.protocol` (length-prefixed framing), :mod:`.coalescer`
  (deadline-window micro-batching onto compiled buckets, plus the
  per-request lifecycle marks), :mod:`.admission` (bounded-depth
  admission control + the lifecycle/reload state machine),
  :mod:`.client`, :mod:`.admin` (the read-only HTTP endpoint) and
  :mod:`.loadgen` (the seeded open-loop load-replay harness);
* the daemon itself (:mod:`.daemon`): verified checkpoint load, one
  AOT-compiled predict executable per declared batch bucket, a
  dispatcher whose steady state provably never compiles, degraded-mode
  serving under the ``serve:`` chaos scope, and the observability
  plane (ISSUE 7): per-request phase decomposition, serving trace +
  ``serving_report.json`` + ``slo_report.json`` export, live admin
  endpoint.

Entry points: ``scripts/serve.py`` (daemon CLI),
``scripts/serve_client.py`` (load-gen/demo client),
``scripts/loadgen.py`` (deterministic load replay), ``bench.py
--serving`` (the ``serving_quick`` record).
"""

from ate_replication_causalml_tpu.serving.admission import (
    AdmissionController,
    InvalidTransition,
    ReloadSupervisor,
    ServingLifecycle,
)
from ate_replication_causalml_tpu.serving.client import (
    CateClient,
    ServingError,
    ServingUnavailable,
)
from ate_replication_causalml_tpu.serving.coalescer import (
    Batch,
    BucketPlan,
    Coalescer,
    PendingRequest,
)
from ate_replication_causalml_tpu.serving.fleet import (
    BurnShedder,
    ModelFleet,
    ModelLifecycle,
    parse_fleet_spec,
)
from ate_replication_causalml_tpu.serving.protocol import (
    ProtocolError,
    encode_frame,
    decode_frame,
    read_frame,
    write_frame,
)

__all__ = [
    "AdmissionController", "Batch", "BucketPlan", "BurnShedder",
    "CateClient", "CateServer", "Coalescer", "InvalidTransition",
    "ModelFleet", "ModelLifecycle", "PendingRequest", "ProtocolError",
    "RejectedRequest", "ReloadSupervisor", "RetrainSupervisor",
    "ServeConfig", "ServingError", "ServingLifecycle",
    "ServingUnavailable", "decode_frame", "encode_frame",
    "parse_fleet_spec", "read_frame", "write_frame",
]


def __getattr__(name):
    # The daemon pulls in jax at startup; resolve it lazily so the
    # no-jax core (client hosts, tier-1 protocol tests) stays light.
    if name in ("CateServer", "ServeConfig", "RejectedRequest"):
        from ate_replication_causalml_tpu.serving import daemon

        return getattr(daemon, name)
    if name == "RetrainSupervisor":
        from ate_replication_causalml_tpu.serving import retrain

        return retrain.RetrainSupervisor
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""CATE serving daemon (ISSUE 6, the tentpole).

The predict path does 1M rows of CATE + variance in ~1.4 s steady, but
every fresh process pays a ~25-30 s trace/deserialize tail (NEXT.md §3:
"irreducible without ahead-of-time tracing or a persistent daemon").
This is the daemon: a long-lived process that pays the tail ONCE, as an
explicit startup phase, and then serves τ̂(x) (+ variance) queries whose
steady state provably never traces or compiles.

Startup phases (each a span + a ``serving_startup_seconds`` gauge):

1. **load** — ``utils/checkpoint.load_fitted`` with SHA-256
   verification; a torn or tampered forest checkpoint refuses to serve.
2. **aot** — one ``jax.jit(...).lower().compile()`` predict executable
   per declared batch bucket (``lower_predict_cate``; the same AOT
   machinery as ``scheduler/prefetch.py``), forest as a *runtime*
   argument so reloads reuse executables.
3. **warm** — one zero-batch dispatch per bucket, absorbing the
   first-dispatch transfer/conversion compiles.

After warm, the compile-event counter (``jax_compiles_total``, bridged
from ``jax.monitoring``) is marked; :meth:`CateServer.stop` asserts the
serving window left it unchanged — the no-compile guarantee is enforced
from the metrics registry, not hoped.

The serving core is the no-jax trio this module wires together:
admission (bounded depth, typed reject-on-overload), the coalescer
(micro-batch within a deadline window, pad to the nearest compiled
bucket), and the lifecycle/reload supervisor (degraded-mode serving:
on a fault — injected via the ``serve:`` chaos scope or real — requests
get typed retry-after rejects while the checkpoint is re-verified and
reloaded, then serving resumes; values after recovery are bit-identical
because the model is the same verified bytes).

Every protocol request gets a ``serving_request`` span; latencies ride
the ``serving_request_seconds`` bucket histogram, queue depth and batch
fill the registry, and everything exports through the same atomic
``metrics.json`` path as the sweep.

The observability plane (ISSUE 7) rides the same machinery:

* every request carries monotonic lifecycle marks (admission →
  coalescer close → dispatcher pickup → device entry/exit → reply), so
  its latency decomposes into ``coalesce_wait / queue_wait / dispatch /
  device / reply`` — per-phase bucket histograms + span attrs whose sum
  IS the end-to-end latency;
* ``stop()`` (and the ``dump`` op) export the serving window's
  ``trace.json`` (one track per connection, a dispatcher/device track,
  request→batch→reply flow arrows) plus ``serving_report.json`` (a pure
  function of the trace — ``scripts/analyze_trace.py`` recomputes it
  bit-for-bit) and ``slo_report.json`` (multi-window burn rates from
  ``observability/slo.py``);
* an optional read-only admin endpoint (``serving/admin.py``,
  ``ATE_TPU_SERVE_ADMIN_PORT``) serves ``/metrics`` / ``/healthz`` /
  ``/readyz`` / ``/varz`` live — degraded serving is a 503 on readyz.

None of it traces or compiles jax — the zero-compile window assertion
in :meth:`CateServer.stop` holds with the whole plane active.

The train-to-serve fleet layer (ISSUE 11) rides on top:

* many models (``ATE_TPU_SERVE_FLEET``), routed by the request
  header's ``model`` field; same-shape models share one AOT executable
  set (the forest is a runtime argument), unknown/retired ids get
  typed rejects, and each model carries its own lifecycle — one
  tenant's degradation never 503s another;
* zero-downtime rotation (:meth:`CateServer.rotate`, the ``rotate``
  wire op, and the retrain supervisor in :mod:`.retrain`): the
  candidate checkpoint is SHA-256 re-verified and geometry-checked,
  then swapped atomically — in-flight batches complete against the
  old forest, the next dispatch binds the new one, ``readyz`` stays
  200 throughout, and a corrupt candidate is a typed refusal that
  keeps the last good model serving;
* SLO-burn-driven shedding: per-model multi-window burn rates from the
  SLO engine gate admission (typed ``shed`` rejects with retry-after)
  instead of one global depth alone.

The ``rotate:`` chaos scope (corrupt candidate, fault mid-swap,
retrain failure, slow verify) proves every refusal path in tier-1.

The deadline-and-liveness plane (ISSUE 14) rides through everything
above:

* **end-to-end deadlines** — the predict header's optional
  ``deadline_ms`` becomes a shared :class:`~..resilience.deadline.
  Budget` at admission, checked at every hand-off (admission, batch
  close, dispatch pickup); an expired request is a typed retryable
  ``deadline_exceeded`` reject *before* device dispatch, metered per
  phase in ``serving_deadline_exceeded_total{phase}`` so the report
  says where the budget died, and a batch containing only expired
  requests is never dispatched;
* **heartbeat watchdog** — the dispatcher thread stamps a monotonic
  heartbeat around every unit of work; a watchdog thread
  (``resilience/watchdog.py``, ``ATE_TPU_WATCHDOG_DISPATCH_S``) flips
  the daemon to degraded when the heartbeat goes stale — readyz AND
  healthz 503, typed rejects — instead of queueing into a black hole,
  and recovery (heartbeat resumes → verified reload) returns to
  serving. The ``hang:scope=dispatch`` chaos scope injects
  deterministic stalls at the stamped site to prove the whole path;
* **graceful drain** — SIGTERM (``scripts/serve.py``) or the ``drain``
  wire op moves the lifecycle through ``draining``: admission rejects
  new work typed with retry-after, in-flight batches complete,
  artifacts dump, and :meth:`CateServer.drain` returns within
  ``ATE_TPU_SERVE_DRAIN_S`` (``drain_total{outcome}``; a bound
  overrun is a recorded ``drain_timeout`` event and a forced exit in
  the CLI).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
from typing import Callable

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability import stathealth
from ate_replication_causalml_tpu.observability.slo import (
    DEFAULT_WINDOWS,
    SLOEngine,
    default_serving_slos,
    fleet_slos,
    stat_health_slos,
)
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.deadline import Budget
from ate_replication_causalml_tpu.resilience.watchdog import (
    HeartbeatRegistry,
    Watchdog,
    lane_bound_s,
    poll_s_from_env,
)
from ate_replication_causalml_tpu.serving import protocol
from ate_replication_causalml_tpu.serving.admission import (
    STOPPED,
    AdmissionController,
    ReloadSupervisor,
    ServingLifecycle,
)
from ate_replication_causalml_tpu.serving.coalescer import (
    Batch,
    BucketPlan,
    Coalescer,
    FusionPlan,
    PendingRequest,
)
from ate_replication_causalml_tpu.serving.fleet import (
    BurnShedder,
    ModelFleet,
    parse_fleet_spec,
)

ENV_BUCKETS = "ATE_TPU_SERVE_BUCKETS"
ENV_WINDOW_MS = "ATE_TPU_SERVE_WINDOW_MS"
ENV_DEPTH = "ATE_TPU_SERVE_DEPTH"
ENV_RETRY_AFTER_MS = "ATE_TPU_SERVE_RETRY_AFTER_MS"
ENV_ADMIN_PORT = "ATE_TPU_SERVE_ADMIN_PORT"
ENV_SLO_MS = "ATE_TPU_SERVE_SLO_MS"
ENV_FLEET = "ATE_TPU_SERVE_FLEET"
ENV_SHED_BURN = "ATE_TPU_SERVE_FLEET_SHED_BURN"
ENV_FUSE = "ATE_TPU_SERVE_FUSE"
ENV_DRAIN_S = "ATE_TPU_SERVE_DRAIN_S"
ENV_STAT_WINDOW = "ATE_TPU_STAT_WINDOW"
ENV_STAT_DRIFT_BURN = "ATE_TPU_STAT_DRIFT_BURN"
ENV_STAT_CALIBRATION = "ATE_TPU_STAT_CALIBRATION"

DEFAULT_BUCKETS = "1,8,64,256"
DEFAULT_WINDOW_MS = 2.0
DEFAULT_DEPTH = 64
DEFAULT_RETRY_AFTER_MS = 50.0
DEFAULT_SLO_LATENCY_MS = 250.0
#: graceful-drain bound: in-flight work must complete (and the process
#: be ready to exit 0) within this many seconds of SIGTERM/`drain`.
DEFAULT_DRAIN_S = 30.0
#: dispatcher heartbeat staleness bound — far above any sane batch
#: dispatch, far below "an operator notices the wedge". 0 disables.
DEFAULT_WATCHDOG_DISPATCH_S = 30.0

#: the dispatcher's watchdog lane name.
DISPATCH_LANE = "dispatch"

#: the model id requests without a ``model`` header route to — the
#: ``--checkpoint`` model every pre-fleet client already speaks to.
DEFAULT_MODEL = "default"

#: how often the dispatcher refreshes the shedder's burn cache (full
#: SLO evaluation — throttled off the per-batch path).
SHED_REFRESH_S = 0.25


def _parse_calibration_cols(spec: str) -> tuple[int, int] | None:
    """``"pcol:tcol"`` → (propensity column, treatment column); empty =
    unarmed. Malformed values raise at config time, like every other
    serve knob."""
    spec = spec.strip()
    if not spec:
        return None
    pcol_s, sep, tcol_s = spec.partition(":")
    try:
        if not sep:
            raise ValueError(spec)
        return int(pcol_s), int(tcol_s)
    except ValueError as e:
        raise ValueError(
            f"{ENV_STAT_CALIBRATION} wants 'pcol:tcol' ints, got {spec!r}"
        ) from e


class RejectedRequest(RuntimeError):
    """A typed reject: carries the wire ``error`` code and the
    retry-after hint. Raised out of :meth:`CateServer.serve_one` only
    for callers that asked (``raise_rejects=True``); the protocol layer
    turns it into a reject frame instead."""

    def __init__(self, code: str, message: str, retry_after_s: float | None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Daemon configuration; :meth:`from_env` reads the
    ``ATE_TPU_SERVE_*`` knobs documented in the README."""

    checkpoint: str
    buckets: BucketPlan = dataclasses.field(
        default_factory=lambda: BucketPlan.parse(DEFAULT_BUCKETS)
    )
    window_s: float = DEFAULT_WINDOW_MS / 1e3
    max_depth: int = DEFAULT_DEPTH
    retry_after_s: float = DEFAULT_RETRY_AFTER_MS / 1e3
    row_backend: str | None = None
    variance_compat: str = "unbiased"
    donate: bool | None = None
    tree_chunk: int = 32
    #: stop() raises if the serving window recorded any compile event;
    #: the enforcement knob exists for diagnostics, not for production.
    strict_no_compile: bool = True
    #: admin endpoint (ISSUE 7): None = off (the default); an int binds
    #: that TCP port on startup (0 = ephemeral, for tests).
    admin_port: int | None = None
    #: latency-SLO threshold: requests over this spend the error budget.
    slo_latency_s: float = DEFAULT_SLO_LATENCY_MS / 1e3
    #: multi-window burn-rate ladder (ascending; see observability/slo).
    slo_windows_s: tuple[float, ...] = DEFAULT_WINDOWS
    #: extra served models (ISSUE 11): ``(model_id, checkpoint)`` pairs
    #: beyond the ``checkpoint`` field (which serves as DEFAULT_MODEL).
    #: Same-shape fleets share one AOT executable set.
    fleet: tuple[tuple[str, str], ...] = ()
    #: SLO-burn-driven per-model shedding threshold: a model sheds new
    #: admissions (typed ``shed`` reject) while its two fastest burn
    #: windows both exceed this. <= 0 disables shedding.
    shed_burn_threshold: float = 0.0
    #: Serve-time bucket fusion (ISSUE 12): adjacent buckets share ONE
    #: masked AOT executable per fusion group (``compiled(forest, x,
    #: mask, None)``) — fewer executables per model, deterministic
    #: exact-zero masked rows, and the dispatcher back-fills the masked
    #: region with queued same-model requests. Off by default: the
    #: per-bucket signature ``compiled(forest, x, None)`` is the
    #: documented pre-fusion contract.
    fuse_buckets: bool = False
    #: graceful-drain bound (ISSUE 14): seconds in-flight work gets to
    #: complete after SIGTERM/`drain` before the drain is recorded as a
    #: timeout (and the CLI force-exits).
    drain_timeout_s: float = DEFAULT_DRAIN_S
    #: dispatcher heartbeat staleness bound (seconds; <= 0 disables the
    #: watchdog). A stalled dispatcher flips the daemon to degraded —
    #: readyz AND healthz 503 — instead of queueing into a black hole.
    watchdog_dispatch_s: float = DEFAULT_WATCHDOG_DISPATCH_S
    #: watchdog poll cadence (detection latency, not age resolution).
    watchdog_poll_s: float = 0.25
    #: statistical-health plane (ISSUE 16): the drift-evaluation window
    #: width — per-model CATE/covariate/propensity sketches seal on
    #: this clock grid and sealed pairs are PSI/KS-compared.
    stat_window_s: float = stathealth.DEFAULT_WINDOW_S
    #: objective of the per-model ``stat_drift``/``stat_calibration``
    #: SLOs — the tolerated good fraction of sealed windows.
    stat_drift_objective: float = 0.9
    #: optional calibration feed, ``(propensity_col, treatment_col)``
    #: feature indices (``ATE_TPU_STAT_CALIBRATION=pcol:tcol``); None
    #: leaves the calibration channel unarmed (zero burn).
    stat_calibration_cols: tuple[int, int] | None = None

    @classmethod
    def from_env(cls, checkpoint: str, **overrides) -> "ServeConfig":
        env = os.environ
        base = dict(
            buckets=BucketPlan.parse(env.get(ENV_BUCKETS, DEFAULT_BUCKETS)),
            window_s=float(env.get(ENV_WINDOW_MS, DEFAULT_WINDOW_MS)) / 1e3,
            max_depth=int(env.get(ENV_DEPTH, DEFAULT_DEPTH)),
            retry_after_s=float(
                env.get(ENV_RETRY_AFTER_MS, DEFAULT_RETRY_AFTER_MS)
            ) / 1e3,
            slo_latency_s=float(
                env.get(ENV_SLO_MS, DEFAULT_SLO_LATENCY_MS)
            ) / 1e3,
            fleet=parse_fleet_spec(env.get(ENV_FLEET, "")),
            shed_burn_threshold=float(env.get(ENV_SHED_BURN, 0.0)),
            fuse_buckets=env.get(ENV_FUSE, "0").strip().lower()
            in ("1", "true", "on"),
            drain_timeout_s=float(env.get(ENV_DRAIN_S, DEFAULT_DRAIN_S)),
            watchdog_dispatch_s=lane_bound_s(
                DISPATCH_LANE, DEFAULT_WATCHDOG_DISPATCH_S
            ),
            watchdog_poll_s=poll_s_from_env(),
            stat_window_s=float(
                env.get(ENV_STAT_WINDOW, stathealth.DEFAULT_WINDOW_S)
            ),
            stat_drift_objective=float(env.get(ENV_STAT_DRIFT_BURN, 0.9)),
            stat_calibration_cols=_parse_calibration_cols(
                env.get(ENV_STAT_CALIBRATION, "")
            ),
        )
        if env.get(ENV_ADMIN_PORT):
            base["admin_port"] = int(env[ENV_ADMIN_PORT])
        base.update(overrides)
        return cls(checkpoint=checkpoint, **base)

    @property
    def model_ids(self) -> tuple[str, ...]:
        """Every served model id, DEFAULT_MODEL first."""
        ids = (DEFAULT_MODEL,) + tuple(m for m, _ in self.fleet)
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"fleet model ids collide with {DEFAULT_MODEL!r}: {ids}"
            )
        return ids


class CateServer:
    """The serving core: verified load → AOT → warm → steady dispatch.

    Thread model: any number of producer threads call
    :meth:`serve_one` / :meth:`submit`; ONE dispatcher thread owns the
    device (jax dispatch is serialized by design — the scheduler PR
    established that concurrent device entry buys nothing on one chip
    and can deadlock collectives). Shared state (the model reference,
    the executable table) is mutated only under ``self._lock``
    (graftlint JGL008 covers ``serving/``).
    """

    def __init__(self, config: ServeConfig):
        self.config = config
        self.lifecycle = ServingLifecycle()
        self.admission = AdmissionController(config.max_depth)
        self.coalescer = Coalescer(
            config.buckets, config.window_s,
            on_expired=self._on_expired_waiters,
        )
        #: liveness plane (ISSUE 14): the dispatcher stamps this lane's
        #: heartbeat around every unit of work; the watchdog (started
        #: with the dispatcher) flips the daemon to degraded on a stale
        #: heartbeat. The retrain supervisor stamps its own lane here
        #: too, so /healthz reports every lane's age in one place.
        self.heartbeats = HeartbeatRegistry()
        self._watchdog: Watchdog | None = None
        self._stopped = False
        #: drain rendezvous: set (with the outcome recorded) only after
        #: the OWNING drain has fully finished, so concurrent drain
        #: callers — e.g. SIGTERM landing while a wire `drain` op is in
        #: flight — block for the real outcome instead of being told
        #: "drained" mid-drain.
        self._drain_done = threading.Event()
        self._drain_outcome: str | None = None
        #: the OWNING drain's bound — what non-owning waiters must ride
        #: out (their own bound may be shorter; exiting on it would drop
        #: the owner's still-budgeted in-flight work).
        self._drain_bound: float | None = None
        #: bucket-fusion plan (ISSUE 12): None = per-bucket executables
        #: (the pre-fusion contract); a plan = one masked executable per
        #: group of adjacent buckets.
        self._fusion = (
            FusionPlan.pair_adjacent(config.buckets)
            if config.fuse_buckets else None
        )
        #: (geometry signature, panel row count) pairs whose sharded
        #: leaf-index build executables are already traced
        #: (startup/pre-mark builds) — a rotation prewarm only builds
        #: in-window when it is a cache hit, preserving the
        #: zero-compile proof (see rotate()). The sig is part of the
        #: key: the build executable is shaped by the FOREST too, so a
        #: row-count collision across different-geometry models must
        #: not read as warm.
        self._index_shapes: set[tuple] = set()
        self._lock = threading.RLock()
        #: the fleet routing table (ISSUE 11): model id -> entry with
        #: the forest reference, version, geometry signature and the
        #: per-model lifecycle + reload/rotation supervisor.
        self.fleet = ModelFleet()
        #: AOT executables keyed by (geometry signature, bucket) —
        #: same-shape models share, because the forest is a RUNTIME
        #: argument of the lowered predict.
        self._executables: dict[tuple, object] = {}
        # None until startup completes: a daemon stopped before its
        # warm phase has no serving window to enforce.
        self._compile_mark: float | None = None
        self._startup_s: dict[str, float] = {}
        self._dispatcher: threading.Thread | None = None
        # Everything the serving trace exports is filtered to records
        # at/after this mark — the event log is a process-global ring
        # shared with whatever ran before the daemon. The phase-count
        # mark (set at startup) is the metrics-side twin for the
        # reconciliation's baseline.
        self._born_mono = time.monotonic()
        self._phase_mark = 0
        # The daemon-wide reloader: serve-scope faults degrade the
        # WHOLE daemon (readyz 503) and re-verify the default model's
        # checkpoint — the pre-fleet contract. Per-MODEL faults go
        # through each entry's own supervisor instead and never touch
        # this lifecycle.
        self._reloader = ReloadSupervisor(
            self.lifecycle, self._load_checkpoint, self._install_model
        )
        self.slo = SLOEngine(
            default_serving_slos(
                latency_threshold_s=config.slo_latency_s,
                windows_s=config.slo_windows_s,
            )
            + fleet_slos(config.model_ids, windows_s=config.slo_windows_s)
            + stat_health_slos(
                config.model_ids,
                objective=config.stat_drift_objective,
                windows_s=config.slo_windows_s,
            )
        )
        #: statistical-health plane (ISSUE 16): per-model streaming
        #: sketches over served CATE / covariate / propensity channels,
        #: window-pair drift detectors, optional calibration feed. Fed
        #: host-side by the dispatcher AFTER device results are already
        #: materialized numpy — nothing here can trace.
        self.stat = stathealth.StatHealthMonitor(
            config.model_ids,
            window_s=config.stat_window_s,
            registry=obs.REGISTRY,
            calibration_cols=config.stat_calibration_cols,
        )
        self._shedder = BurnShedder(
            self.slo, threshold=config.shed_burn_threshold
        )
        self._shed_next_update = float("-inf")
        self._admin = None
        self._sampler: obs.MetricSampler | None = None
        self._requests = obs.counter(
            "serving_requests_total", "CATE serving requests by terminal status"
        )
        self._rejects = obs.counter(
            "serving_rejected_total", "CATE serving rejections by reason"
        )
        self._batches = obs.counter(
            "serving_batches_total", "dispatched micro-batches by bucket"
        )
        self._latency = obs.bucket_histogram(
            "serving_request_seconds", "served request latency (enqueue to reply)"
        )
        self._fill = obs.bucket_histogram(
            "serving_batch_fill",
            "micro-batch fill ratio (real rows / bucket rows)",
            bounds=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        # Lifecycle decomposition (ISSUE 7): one bucket-histogram family
        # labeled by phase (quantiles) plus a counter mirror (the
        # schema-contract family — "no phase was ever recorded" must be
        # an explicit 0 in metrics.json) and the batch close reasons.
        self._phase_hist = obs.bucket_histogram(
            "serving_phase_seconds",
            "per-request lifecycle phase durations",
        )
        self._phase_total = obs.counter(
            "serving_phase_seconds_total",
            "summed per-request lifecycle phase seconds",
        )
        self._close_reasons = obs.counter(
            "serving_batch_close_total", "micro-batch close reasons"
        )
        # Pad/masked split (ISSUE 12 satellite): ``pad`` is TRUE waste —
        # unmasked garbage rows a per-bucket dispatch computes and
        # discards; ``masked`` is a fused dispatch's deterministic
        # exact-zero region (partially reclaimed by take_fill). The
        # row-count counter mirrors are the schema-contract families
        # (REQUIRED_COUNTERS): "no row was ever padded" is a recorded 0.
        self._pad = obs.bucket_histogram(
            "serving_pad_fraction",
            "unmasked pad fraction of per-bucket dispatches (true waste)",
            bounds=obs.PAD_FRACTION_BOUNDS,
        )
        self._masked = obs.bucket_histogram(
            "serving_masked_fraction",
            "masked fraction of fused-bucket dispatches (exact zeros)",
            bounds=obs.PAD_FRACTION_BOUNDS,
        )
        self._pad_rows = obs.counter(
            "serving_pad_rows_total",
            "unmasked pad rows dispatched by per-bucket executables",
        )
        self._masked_rows = obs.counter(
            "serving_masked_rows_total",
            "masked (exact-zero) rows dispatched by fused executables",
        )
        # Fleet routing outcomes (ISSUE 11): every terminal, per model —
        # the family the per-model SLOs and the shedder read.
        self._fleet_requests = obs.counter(
            "serving_fleet_requests_total",
            "fleet-routed serving requests by model and terminal status",
        )
        # Deadline plane (ISSUE 14): expired requests rejected typed
        # BEFORE device dispatch, by the phase their budget died in
        # (admission / queue / dispatch) — and drain outcomes.
        self._deadline_rejects = obs.counter(
            "serving_deadline_exceeded_total",
            "requests rejected typed for an expired deadline, by phase",
        )
        self._drains = obs.counter(
            "drain_total", "graceful-drain outcomes"
        )

    # ── startup ──────────────────────────────────────────────────────

    def _load_forest(self, path: str):
        """SHA-256-verified model load; accepts a ``FittedCausalForest``
        or a bare ``CausalForest`` checkpoint. Raises
        ``CheckpointCorrupt`` (startup: refuse to serve; degraded
        reload: stay degraded; rotation: refuse the candidate) on any
        integrity failure."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            CausalForest,
            FittedCausalForest,
        )
        from ate_replication_causalml_tpu.utils.checkpoint import load_fitted

        obj = load_fitted(path, verify=True)
        forest = obj.forest if isinstance(obj, FittedCausalForest) else obj
        if not isinstance(forest, CausalForest):
            raise TypeError(
                f"checkpoint {path!r} holds "
                f"{type(obj).__name__}, not a causal forest"
            )
        return forest

    def _load_model(self, path: str):
        """:meth:`_load_forest` keeping the training panel too:
        → ``(forest, train_x | None)``. A ``FittedCausalForest``
        checkpoint carries the matrix its in-sample (oob) predictions
        score — the rows whose leaf-index cache the rotation path
        pre-builds (ISSUE 12)."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            CausalForest,
            FittedCausalForest,
        )
        from ate_replication_causalml_tpu.utils.checkpoint import load_fitted

        obj = load_fitted(path, verify=True)
        if isinstance(obj, FittedCausalForest):
            return obj.forest, obj.x
        if not isinstance(obj, CausalForest):
            raise TypeError(
                f"checkpoint {path!r} holds "
                f"{type(obj).__name__}, not a causal forest"
            )
        return obj, None

    def _build_leaf_index(self, model_id: str, forest, train_x):
        """The mesh-sharded leaf-index build (ISSUE 12, tentpole a):
        one metered sharded routing sweep over the training panel —
        the 8.0 s serial prefix of BENCH_r05, spread over the data
        axis. Called at startup (before the no-compile mark) and from
        the rotation path BEFORE the swap instant."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            compute_leaf_index_sharded,
        )

        t0 = time.perf_counter()
        li = compute_leaf_index_sharded(forest, train_x)
        obs.gauge(
            "serving_leaf_index_build_seconds",
            "pre-swap sharded leaf-index build duration",
        ).set(time.perf_counter() - t0, model=model_id)
        with self._lock:
            self._index_shapes.add(
                (self._forest_signature(forest), int(np.shape(train_x)[0]))
            )
        return li

    def _load_checkpoint(self):
        """The daemon-wide reloader's reload_fn: re-verify the DEFAULT
        model's LAST GOOD checkpoint — the fleet entry's, which a
        rotation advances. Re-loading the startup ``config.checkpoint``
        here would silently roll a rotated default model back to its
        pre-rotation bytes on the next degraded recovery."""
        entry = self.fleet.get(DEFAULT_MODEL)
        path = (
            entry.checkpoint if entry is not None
            else self.config.checkpoint
        )
        return self._load_forest(path)

    @staticmethod
    def _forest_signature(forest) -> tuple:
        """The geometry key AOT executables are shared under: the full
        pytree structure plus every leaf's (shape, dtype) — exactly the
        avals a compiled executable accepts. Same signature ⇒ same
        executable set; a candidate with a different signature needs a
        re-AOT, which rotation refuses."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(forest)
        return (
            str(treedef),
            tuple(
                (tuple(np.shape(l)), str(np.asarray(l).dtype))
                for l in leaves
            ),
        )

    def _install_model(self, forest) -> None:
        """Reinstall the DEFAULT model (the daemon-wide degraded reload
        path): the re-verified LAST GOOD bytes go back in WITHOUT a
        version bump — a recovery is not a rotation, and the reply's
        ``model_version`` partitions bit-identity across rotations
        only. The executables are keyed to the forest's SHAPES — a
        reload with a different geometry would need a re-AOT, which
        degraded mode refuses (same-shape redeploys are the hot
        path)."""
        entry = self.fleet.get(DEFAULT_MODEL)
        if entry is None:
            raise RuntimeError("default model was never installed")
        sig = self._forest_signature(forest)
        if sig != entry.sig:
            raise ValueError(
                "reloaded checkpoint changed forest geometry "
                f"for model {DEFAULT_MODEL!r}; restart the daemon to re-AOT"
            )
        self.fleet.reinstall(DEFAULT_MODEL, forest)

    def _wire_model_supervisor(self, entry) -> None:
        """Per-model degraded recovery (ISSUE 11): a model-scoped fault
        re-verifies and reloads that model's LAST GOOD checkpoint in
        the background while only that model's requests are refused
        typed — one tenant's degradation never 503s another.

        The DEFAULT model keeps the daemon-wide reloader as its ONE
        supervisor: its faults degrade the whole daemon (the pre-fleet
        contract — readyz 503), and, critically, its rotations share
        that reloader's single-flight claim, so a global degraded
        reload and a default-model rotation can never race two
        installs into the same entry."""
        if entry.model_id == DEFAULT_MODEL:
            entry.supervisor = self._reloader
            return

        def reload_last_good():
            forest = self._load_forest(entry.checkpoint)
            if self._forest_signature(forest) != entry.sig:
                raise ValueError(
                    f"model {entry.model_id!r} last-good checkpoint "
                    "changed geometry on reload"
                )
            return forest

        def reinstall(forest):
            self.fleet.reinstall(entry.model_id, forest)

        entry.supervisor = ReloadSupervisor(
            entry.lifecycle, reload_last_good, reinstall
        )

    def startup(self) -> dict[str, float]:
        """Run the three startup phases; returns their seconds (also
        exported as ``serving_startup_seconds{phase=}`` gauges). With a
        fleet configured, *load* verifies and installs every model and
        *aot*/*warm* run once per DISTINCT geometry signature — a
        same-shape fleet pays for one executable set."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            lower_predict_cate,
        )

        obs.install_jax_monitoring()
        import jax

        # Reconciliation baseline (ISSUE 11): the phase histogram is
        # process-global, but this daemon's trace window starts here —
        # requests decomposed by an EARLIER daemon in the same process
        # must not be misreported as this session's silent drops. The
        # mark rides the exported trace's otherData so the analyzer
        # subtracts the same baseline.
        with self._lock:
            self._phase_mark = self._phase_device_count()
        phases: dict[str, float] = {}
        specs = [(DEFAULT_MODEL, self.config.checkpoint)]
        specs += list(self.config.fleet)
        with obs.span("serving_startup", checkpoint=self.config.checkpoint,
                      models=",".join(m for m, _ in specs)):
            t0 = time.perf_counter()
            panels: dict[str, object] = {}
            with obs.span("serving_load"):
                for model_id, path in specs:
                    forest, train_x = self._load_model(path)
                    entry = self.fleet.install(
                        model_id, forest, self._forest_signature(forest),
                        int(forest.bin_edges.shape[0]), path,
                    )
                    self._wire_model_supervisor(entry)
                    if train_x is not None:
                        panels[model_id] = train_x
            phases["load"] = time.perf_counter() - t0

            # One AOT + warm pass per distinct geometry signature (in
            # install order), shared by every same-shape model.
            reps: dict[tuple, object] = {}
            for model_id, _ in specs:
                entry = self.fleet.get(model_id)
                reps.setdefault(entry.sig, entry.forest)

            t0 = time.perf_counter()
            from ate_replication_causalml_tpu.models.causal_forest import (
                lower_predict_cate_masked,
            )

            for sig, model in reps.items():
                if self._fusion is not None:
                    # ONE masked executable per fusion group (ISSUE 12):
                    # the executable count per model DROPS from
                    # len(buckets) to len(groups).
                    for width in self._fusion.widths:
                        with obs.span("serving_aot_compile", bucket=width,
                                      fused=1):
                            compiled = lower_predict_cate_masked(
                                model,
                                width,
                                oob=False,
                                tree_chunk=self.config.tree_chunk,
                                row_backend=self.config.row_backend,
                                variance_compat=self.config.variance_compat,
                                donate=self.config.donate,
                            ).compile()
                        with self._lock:
                            self._executables[(sig, "fused", width)] = (
                                compiled
                            )
                else:
                    for bucket in self.config.buckets.sizes:
                        with obs.span("serving_aot_compile", bucket=bucket):
                            compiled = lower_predict_cate(
                                model,
                                bucket,
                                oob=False,
                                tree_chunk=self.config.tree_chunk,
                                row_backend=self.config.row_backend,
                                variance_compat=self.config.variance_compat,
                                donate=self.config.donate,
                            ).compile()
                        with self._lock:
                            self._executables[(sig, bucket)] = compiled
            phases["aot"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            with obs.span("serving_warm"):
                for sig, model in reps.items():
                    p = int(model.bin_edges.shape[0])
                    if self._fusion is not None:
                        for width in self._fusion.widths:
                            zeros = jax.device_put(
                                np.zeros((width, p), np.float32)
                            )
                            ones = jax.device_put(
                                np.ones((width,), np.float32)
                            )
                            out = self._executables[(sig, "fused", width)](
                                model, zeros, ones, None
                            )
                            np.asarray(out.cate), np.asarray(out.variance)
                    else:
                        for bucket in self.config.buckets.sizes:
                            zeros = jax.device_put(
                                np.zeros((bucket, p), np.float32)
                            )
                            out = self._executables[(sig, bucket)](
                                model, zeros, None
                            )
                            np.asarray(out.cate), np.asarray(out.variance)
            phases["warm"] = time.perf_counter() - t0

            if panels:
                # Fitted checkpoints: pre-build each training panel's
                # leaf-index cache SHARDED over the mesh (ISSUE 12) —
                # inside the startup window, so the build executables
                # are traced BEFORE the no-compile mark and a same-shape
                # rotation's pre-swap rebuild is a pure cache hit.
                t0 = time.perf_counter()
                with obs.span("serving_leaf_index",
                              models=",".join(sorted(panels))):
                    for model_id, train_x in panels.items():
                        entry = self.fleet.get(model_id)
                        entry.leaf_index = self._build_leaf_index(
                            model_id, entry.forest, train_x
                        )
                phases["index"] = time.perf_counter() - t0

        g = obs.gauge(
            "serving_startup_seconds", "daemon startup phase durations"
        )
        for phase, secs in phases.items():
            g.set(secs, phase=phase)
        self._start_observability_plane()
        with self._lock:
            self._startup_s = dict(phases)
            self._compile_mark = obs.compile_event_count()
        self.lifecycle.mark_ready()
        self._start_dispatcher()
        self._start_watchdog()
        return phases

    def _start_watchdog(self) -> None:
        """Arm the dispatcher-liveness watchdog (ISSUE 14). jax-free —
        starting it inside the no-compile window is the point."""
        if self.config.watchdog_dispatch_s <= 0:
            return
        wd = Watchdog(
            self.heartbeats,
            {DISPATCH_LANE: self.config.watchdog_dispatch_s},
            poll_s=self.config.watchdog_poll_s,
            on_stall=self._on_lane_stall,
            on_recover=self._on_lane_recover,
        )
        with self._lock:
            self._watchdog = wd
        wd.start()

    def _on_lane_stall(self, lane: str, age_s: float) -> None:
        """A stalled dispatcher flips the daemon to DEGRADED — readyz
        (and healthz) 503, new admissions shed typed with retry-after —
        instead of queueing into a black hole behind a wedged device
        call. Deliberately NO reload here: the model was never suspect
        and a reload cannot unwedge a thread — the daemon STAYS
        degraded for the whole stall (the load-balancer-visible
        window), and recovery waits for the heartbeat itself."""
        if lane != DISPATCH_LANE:
            return
        self.lifecycle.mark_fault(
            f"watchdog:{lane} heartbeat stale {age_s:.3f}s"
        )

    def _on_lane_recover(self, lane: str, stalled_s: float) -> None:
        """The heartbeat resumed: run the verified-reload recovery
        (retry() — the reload re-verifies the last-good checkpoint and
        DEGRADED → SERVING only on success), unless some concurrent
        recovery already brought the daemon back."""
        if lane != DISPATCH_LANE:
            return
        self._reloader.retry()

    def heartbeat_ages(self) -> dict[str, float]:
        """Per-lane heartbeat ages — the /healthz body's liveness
        detail."""
        return self.heartbeats.ages()

    def stalled_lanes(self) -> tuple[str, ...]:
        """Lanes currently inside a watchdog stall episode ((), when
        the watchdog is off). /healthz answers 503 while the dispatcher
        lane is in here."""
        with self._lock:
            wd = self._watchdog
        return wd.stalled() if wd is not None else ()

    def _start_observability_plane(self) -> None:
        """The ISSUE 7 plane: background counter sampling for the
        serving trace, and the optional admin endpoint. Both are
        jax-free — starting them inside the no-compile window is the
        point (the window assertion proves they stay that way)."""
        if obs.enabled() and obs.trace_enabled():
            sampler = obs.MetricSampler(
                metrics=obs.MetricSampler.SERVING_METRICS
            )
            sampler.start()
            with self._lock:
                self._sampler = sampler
        if self.config.admin_port is not None:
            from ate_replication_causalml_tpu.serving.admin import AdminServer

            admin = AdminServer(self)
            try:
                bound = admin.start(self.config.admin_port)
            except BaseException:
                # A failed admin bind (port taken, privileged) aborts
                # startup — but must not leak the sampler thread into a
                # process that will never call stop().
                with self._lock:
                    sampler, self._sampler = self._sampler, None
                if sampler is not None:
                    sampler.stop()
                raise
            with self._lock:
                self._admin = admin
            obs.gauge("serving_admin_port", "bound admin HTTP port").set(bound)
            obs.emit("serving_admin_started", status="ok", port=bound)

    def _start_dispatcher(self) -> None:
        with self._lock:
            t = threading.Thread(
                target=self._dispatch_loop, name="serving-dispatch",
                daemon=True,
            )
            self._dispatcher = t
        t.start()

    # ── request path (producers) ─────────────────────────────────────

    def _reject(self, code: str, message: str,
                retry_after_s: float | None = None,
                request_id: str = "", model: str = "") -> RejectedRequest:
        self._rejects.inc(1, reason=code)
        self._requests.inc(1, status=f"rejected_{code}")
        if model:
            # Per-model terminal (ISSUE 11) — the family the fleet SLOs
            # and the shedder read. Unknown ids are folded into one
            # label so a hostile client cannot mint label cardinality.
            self._fleet_requests.inc(
                1, model=model, status=f"rejected_{code}"
            )
        # The reject timeline (ISSUE 7): one instant per refusal, so
        # the serving trace/report show WHEN admission pushed back, not
        # just how often. Covers every entry path — serve_one spans and
        # raw submit() callers alike.
        obs.emit("serving_reject", status="error", reason=code,
                 request_id=str(request_id), model=model)
        return RejectedRequest(code, message, retry_after_s)

    def submit(self, request_id: str, x: np.ndarray,
               model: str | None = None,
               deadline_ms: float | None = None) -> PendingRequest:
        """Admission + routing + chaos + coalesce. ``model`` selects
        the fleet entry (None/"" routes to DEFAULT_MODEL — the
        pre-fleet wire contract). ``deadline_ms`` is the caller's
        REMAINING budget (the wire header field, ISSUE 14): it becomes
        a shared :class:`Budget` checked at every hand-off, and a
        request that expires anywhere before device dispatch is a
        typed retryable ``deadline_exceeded`` reject. Returns the
        pending handle the caller waits on; raises
        :class:`RejectedRequest` for every typed refusal (the protocol
        layer converts those to reject frames). The admission slot is
        released by the dispatcher on resolve."""
        model_id = model if model else DEFAULT_MODEL
        try:
            x = np.ascontiguousarray(x, dtype=np.float32)
        except (TypeError, ValueError) as e:
            # String/object/datetime queries must become a typed reject,
            # not a connection-killing exception.
            raise self._reject(
                "bad_request", f"x does not convert to float32 ({e})",
                request_id=request_id,
            ) from e
        if x.ndim != 2:
            raise self._reject("bad_request", f"x must be 2-D, got {x.shape}",
                               request_id=request_id)
        entry = self.fleet.get(model_id)
        if entry is None:
            if not self.fleet.ids():
                # Nothing installed yet: the daemon is still starting —
                # a retryable state reject, not an unknown-model typo.
                state = self.lifecycle.state
                raise self._reject(
                    state, f"daemon is {state}",
                    self.config.retry_after_s, request_id=request_id,
                )
            raise self._reject(
                "unknown_model",
                f"unknown model {model_id!r} "
                f"(serving: {', '.join(sorted(self.fleet.ids()))})",
                request_id=request_id, model="_unknown_",
            )
        if entry.lifecycle.state == "retired":
            raise self._reject(
                "retired_model", f"model {model_id!r} is retired",
                request_id=request_id, model=model_id,
            )
        p = entry.n_features
        if x.shape[1] != p:
            raise self._reject(
                "bad_request", f"x has {x.shape[1]} features, model wants {p}",
                request_id=request_id, model=model_id,
            )
        rows = x.shape[0]
        if rows < 1 or rows > self.config.buckets.max_rows:
            raise self._reject(
                "bad_request",
                f"rows must be in [1, {self.config.buckets.max_rows}], "
                f"got {rows} (chunk larger queries client-side)",
                request_id=request_id, model=model_id,
            )
        budget = None
        if deadline_ms is not None:
            try:
                budget = Budget.from_ms(deadline_ms)
            except (TypeError, ValueError) as e:
                raise self._reject(
                    "bad_request",
                    f"deadline_ms {deadline_ms!r} is not a number ({e})",
                    request_id=request_id, model=model_id,
                ) from e
            if budget.expired():
                # The admission hand-off check: a request that arrives
                # already past its caller's deadline never takes a
                # queue slot, never holds a batch open, never touches
                # the device.
                self._deadline_rejects.inc(1, phase="admission")
                raise self._reject(
                    "deadline_exceeded",
                    f"deadline of {deadline_ms}ms expired at admission",
                    self.config.retry_after_s, request_id=request_id,
                    model=model_id,
                )
        inj = chaos.active()
        if inj is not None and inj.take_serve_fault(request_id):
            # The injected fault walks the REAL degraded path: recovery
            # re-verifies and reloads the checkpoint in the background
            # while this (and any concurrent) request is refused typed.
            self._reloader.report_fault(f"chaos:req/{request_id}")
            raise self._reject(
                "serve_fault",
                "injected serving fault; degraded-mode recovery running",
                self.config.retry_after_s, request_id=request_id,
                model=model_id,
            )
        if not entry.lifecycle.can_serve():
            # Model-scoped degradation (ISSUE 11): only THIS tenant's
            # requests are refused; the daemon lifecycle — and readyz —
            # never flip for a per-model fault.
            raise self._reject(
                "model_degraded",
                f"model {model_id!r} is {entry.lifecycle.state}; "
                "recovery running",
                self.config.retry_after_s, request_id=request_id,
                model=model_id,
            )
        if self._shedder.should_shed(model_id):
            # SLO-burn-driven admission (ISSUE 11): this model's error
            # budget is burning in both fast windows — shed new load
            # typed instead of queueing more of it.
            raise self._reject(
                "shed",
                f"model {model_id!r} is shedding load "
                "(SLO burn over threshold)",
                self.config.retry_after_s, request_id=request_id,
                model=model_id,
            )
        if not self.lifecycle.can_serve():
            state = self.lifecycle.state
            raise self._reject(
                "degraded" if state == "degraded" else state,
                f"daemon is {state}",
                self.config.retry_after_s, request_id=request_id,
                model=model_id,
            )
        if not self.admission.try_admit():
            raise self._reject(
                "overloaded",
                f"admission queue at max depth {self.config.max_depth}",
                self.config.retry_after_s, request_id=request_id,
                model=model_id,
            )
        req = PendingRequest(
            str(request_id), x, rows, time.monotonic(), model=model_id,
            budget=budget,
        )
        try:
            self.coalescer.submit(req)
        except BaseException:
            self.admission.release()
            raise
        return req

    # ── deadline plane (ISSUE 14) ────────────────────────────────────

    def _expire_requests(self, requests, phase: str, now: float) -> None:
        """Fail ``requests`` with the typed retryable
        ``deadline_exceeded`` reject (metered by the phase their budget
        died in) and release their admission slots — the one reject
        recipe every post-admission expiry path shares."""
        for req in requests:
            self._deadline_rejects.inc(1, phase=phase)
            rej = self._reject(
                "deadline_exceeded",
                f"deadline expired in {phase} "
                f"(waited {now - req.enqueued_mono:.6f}s)",
                self.config.retry_after_s,
                request_id=req.request_id, model=req.model,
            )
            req.fail(rej, now)
            self.admission.release()

    def _on_expired_waiters(self, requests, now: float) -> None:
        """Coalescer hand-off (batch close / window math): waiters the
        harvest removed because their budget expired in queue."""
        self._expire_requests(requests, "queue", now)

    def serve_request(
        self, request_id: str, x: np.ndarray,
        timeout: float | None = 30.0, model: str | None = None,
        deadline_ms: float | None = None,
    ) -> PendingRequest:
        """Blocking request path: submit, wait, return the resolved
        :class:`PendingRequest` (result + the model version it was
        served by — the bit-identity partition key across a hot-swap).
        Every call gets a ``serving_request`` span; rejects raise
        :class:`RejectedRequest`, dispatch failures re-raise the
        dispatcher's error."""
        with obs.span("serving_request", request_id=str(request_id),
                      rows=int(np.shape(x)[0]) if np.ndim(x) == 2 else -1,
                      model=model or DEFAULT_MODEL,
                      ) as sp:
            try:
                req = self.submit(request_id, x, model=model,
                                  deadline_ms=deadline_ms)
            except RejectedRequest as rej:
                sp.set_status("rejected")
                sp.set_attr("reject", rej.code)
                raise
            if not req.wait(timeout):
                sp.set_status("error")
                self._requests.inc(1, status="timeout")
                # NOT mirrored into serving_fleet_requests_total: the
                # dispatcher still resolves this batch later and books
                # the request's one terminal (ok/error) there — a
                # second sample here would double-count the request in
                # the per-model SLO totals the shedder reads.
                raise TimeoutError(
                    f"request {request_id!r} not served in {timeout}s"
                )
            if req.error is not None:
                if isinstance(req.error, RejectedRequest):
                    # A post-admission typed reject (deadline expired in
                    # queue / at dispatch pickup): already metered by
                    # _reject when it was minted — re-raising it here
                    # must not double-count a terminal.
                    sp.set_status("rejected")
                    sp.set_attr("reject", req.error.code)
                    raise req.error
                sp.set_status("error")
                self._requests.inc(1, status="error")
                self._latency.observe(
                    req.resolved_mono - req.enqueued_mono, status="error"
                )
                raise req.error
            self._requests.inc(1, status="ok")
            self._latency.observe(
                req.resolved_mono - req.enqueued_mono, status="ok"
            )
            # Lifecycle decomposition on the span (ISSUE 7): the phase
            # attrs whose sum is the end-to-end latency, plus the batch
            # linkage the trace exporter turns into request→batch→reply
            # flow arrows and serving_report.json aggregates.
            ph = req.phase_seconds()
            if ph is not None:
                for phase, secs in ph.items():
                    sp.set_attr(f"{phase}_s", round(secs, 9))
                sp.set_attr(
                    "e2e_s",
                    round(req.resolved_mono - req.enqueued_mono, 9),
                )
                sp.set_attr("batch_seq", req.batch_seq)
                sp.set_attr("bucket", req.batch_bucket)
                sp.set_attr("pad_fraction", round(1.0 - req.batch_fill, 6))
                sp.set_attr("model_version", req.model_version)
            return req

    def serve_one(
        self, request_id: str, x: np.ndarray,
        timeout: float | None = 30.0, model: str | None = None,
        deadline_ms: float | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """:meth:`serve_request`, returning just ``(cate, variance)``
        for exactly the submitted rows."""
        return self.serve_request(
            request_id, x, timeout=timeout, model=model,
            deadline_ms=deadline_ms,
        ).result

    # ── dispatch (the single device-owning thread) ───────────────────

    def _dispatch_loop(self) -> None:
        # The idle block must stay well under the watchdog bound, or an
        # IDLE dispatcher would read as stalled (the heartbeat is
        # stamped once per loop pass).
        idle_s = 0.25
        if self.config.watchdog_dispatch_s > 0:
            idle_s = min(
                idle_s, max(0.005, self.config.watchdog_dispatch_s / 4.0)
            )
        while True:
            # The liveness stamp (ISSUE 14): one beat per loop pass —
            # a healthy dispatcher never lets the age past the idle
            # block + one dispatch; a wedged device call lets it grow
            # past the watchdog bound.
            self.heartbeats.beat(DISPATCH_LANE)
            batch = self.coalescer.next_batch(timeout=idle_s)
            if batch is None:
                if self.lifecycle.state == "stopped":
                    self.heartbeats.clear(DISPATCH_LANE)
                    return
                continue
            self._dispatch(batch)
            self.heartbeats.beat(DISPATCH_LANE)

    def _dispatch(self, batch: Batch) -> None:
        import jax

        picked = time.monotonic()
        # Dispatch-pickup deadline check (ISSUE 14): requests whose
        # budget died between batch close and pickup are rejected typed
        # HERE — and a batch left with only expired requests is never
        # dispatched (no device time for answers nobody can use).
        expired = tuple(
            r for r in batch.requests
            if r.budget is not None and r.budget.expired()
        )
        if expired:
            self._expire_requests(expired, "dispatch", picked)
            gone = set(map(id, expired))
            live = tuple(
                r for r in batch.requests if id(r) not in gone
            )
            if not live:
                obs.emit("serving_batch_all_expired", status="error",
                         seq=batch.seq, requests=len(batch.requests),
                         model=batch.model)
                return
            rows = sum(r.rows for r in live)
            batch = batch._replace(
                requests=live, rows=rows, fill=rows / batch.bucket
            )
            for req in live:
                req.batch_fill = batch.fill
        inj = chaos.active()
        if inj is not None:
            # hang: chaos (ISSUE 14) — a deterministic stall INSIDE the
            # heartbeat-stamped unit of work, keyed on the batch's
            # first request id (client-stable, like serve: selection).
            stall = inj.hang_delay_s(
                DISPATCH_LANE, batch.requests[0].request_id
            )
            if stall > 0:
                time.sleep(stall)
        # The bind instant (ISSUE 11): ONE consistent (forest, version)
        # read per batch. A hot-swap landing after this keeps the old
        # reference alive until the batch resolves — in-flight batches
        # complete against the forest they bound; the next batch binds
        # the new one.
        entry = self.fleet.get(batch.model)
        model, version = self.fleet.binding(batch.model)
        requests = batch.requests
        rows = batch.rows
        if self._fusion is not None:
            # Fused dispatch (ISSUE 12): ride the bucket's GROUP width
            # and back-fill the masked region with whatever same-model
            # requests are already queued — rows that would dispatch as
            # exact zeros carry real work instead. take_fill preserves
            # FIFO order, so fairness and the per-request marks hold.
            width = self._fusion.width_for(batch.bucket)
            fill_reqs = self.coalescer.take_fill(
                batch.model, width - rows, picked
            )
            if fill_reqs:
                requests = requests + fill_reqs
                rows += sum(r.rows for r in fill_reqs)
            # Restamp the dispatch-level marks so every request in the
            # fused batch reports the geometry it actually rode.
            for req in requests:
                req.batch_seq = batch.seq
                req.batch_bucket = width
                req.batch_fill = rows / width
        else:
            width = batch.bucket
        with self._lock:
            compiled = self._executables[
                (entry.sig, "fused", width) if self._fusion is not None
                else (entry.sig, width)
            ]
        p = entry.n_features
        now = time.monotonic
        with obs.span("serving_batch", bucket=width,
                      rows=rows, requests=len(requests),
                      seq=batch.seq, close_reason=batch.close_reason,
                      fill=round(rows / width, 6), model=batch.model,
                      model_version=version,
                      fused=int(self._fusion is not None)):
            try:
                padded = np.zeros((width, p), np.float32)
                off = 0
                for req in requests:
                    padded[off:off + req.rows] = req.x
                    off += req.rows
                x_dev = jax.device_put(padded)
                device_start = now()
                if self._fusion is not None:
                    mask = np.zeros((width,), np.float32)
                    mask[:rows] = 1.0
                    out = compiled(model, x_dev, jax.device_put(mask), None)
                else:
                    out = compiled(model, x_dev, None)
                cate = np.asarray(out.cate)
                var = np.asarray(out.variance)
                device_end = now()
            except Exception as e:
                # A dispatch failure fails THIS batch's requests typed
                # and walks the MODEL's degraded recovery (re-verify +
                # reload of its last good checkpoint); other tenants
                # keep serving and the daemon itself survives
                # (never-crash is the serving contract). The default
                # model's supervisor IS the daemon-wide reloader, so
                # its faults degrade the whole daemon — the pre-fleet
                # contract.
                for req in requests:
                    req.picked_mono = picked
                    req.model_version = version
                    req.fail(e, now())
                    self._fleet_requests.inc(1, model=batch.model,
                                             status="error")
                    self.admission.release()
                entry.supervisor.report_fault(
                    f"dispatch:{type(e).__name__}"
                )
                return
            off = 0
            for req in requests:
                req.picked_mono = picked
                req.device_start_mono = device_start
                req.device_end_mono = device_end
                req.model_version = version
                req.resolve(
                    (cate[off:off + req.rows].copy(),
                     var[off:off + req.rows].copy()),
                    now(),
                )
                off += req.rows
                self._fleet_requests.inc(1, model=batch.model, status="ok")
                self.admission.release()
        # Statistical-health feed (ISSUE 16): the served CATE values and
        # the real request rows of this batch, already materialized
        # host-side numpy above — pure-python sketch updates, nothing
        # traced, so the zero-compile window cannot see this plane.
        self.stat.observe(batch.model, cate[:rows], padded[:rows])
        self._batches.inc(1, bucket=width)
        fill = rows / width
        self._fill.observe(fill, bucket=width)
        self._close_reasons.inc(1, reason=batch.close_reason)
        if self._fusion is not None:
            # The pad/masked split (ISSUE 12 satellite): a fused
            # dispatch has NO unmasked garbage rows — its empty region
            # is deterministic exact zeros, reported as masked.
            self._masked.observe(1.0 - fill, bucket=width)
            self._masked_rows.inc(width - rows)
        else:
            self._pad.observe(1.0 - fill, bucket=width)
            self._pad_rows.inc(width - rows)
        for req in requests:
            ph = req.phase_seconds()
            if ph is None:
                continue
            for phase, secs in ph.items():
                self._phase_hist.observe(secs, phase=phase)
                self._phase_total.inc(max(0.0, secs), phase=phase)
        # One SLO snapshot per dispatched batch: cheap (a dict copy per
        # family) and exactly as fresh as the data it judges. The
        # shedder's full evaluation (a history scan per SLO per
        # window) is throttled — per-batch it would grow with uptime
        # on the single device-owning thread.
        self.slo.tick()
        if self._shedder.threshold > 0.0:
            now = time.monotonic()
            with self._lock:
                due = now >= self._shed_next_update
                if due:
                    self._shed_next_update = now + SHED_REFRESH_S
            if due:
                self._shedder.update()

    # ── fleet rotation (ISSUE 11) ────────────────────────────────────

    def rotate(self, model_id: str, checkpoint: str,
               reason: str = "rotate") -> str:
        """Zero-downtime hot-swap of ``model_id`` onto ``checkpoint``.

        Runs on the CALLING thread (the retrain supervisor's, or an
        operator op's — never the request path): the candidate is
        SHA-256 re-verified and geometry-checked against the model's
        compiled executables, then swapped in atomically through the
        model's :class:`~.admission.ReloadSupervisor`. In-flight
        batches complete against the old forest, the next dispatch
        binds the new one, ``readyz`` never flips, and a same-shape
        rotation compiles NOTHING (the zero-compile window assertion
        covers it). Returns ``"rotated"`` / ``"refused"`` (corrupt or
        wrong-geometry candidate — last good kept) / ``"busy"`` /
        ``"unknown_model"`` / ``"retired_model"``."""
        entry = self.fleet.get(model_id)
        if entry is None:
            return "unknown_model"
        if entry.lifecycle.state == "retired":
            # Retirement is terminal: a retired tenant cannot be
            # rotated back into service (reinstatement is a restart
            # with a new fleet spec, not a hot-swap).
            return "retired_model"

        def loader():
            import jax

            inj = chaos.active()
            if inj is not None:
                delay = inj.rotate_verify_delay_s(f"rotate/{model_id}")
                if delay > 0:
                    # Slow-verify chaos: serving must be provably
                    # unaffected for this whole window.
                    time.sleep(delay)
            forest, train_x = self._load_model(checkpoint)
            if self._forest_signature(forest) != entry.sig:
                raise ValueError(
                    f"candidate {checkpoint!r} changed forest geometry "
                    f"for model {model_id!r}; a rotation cannot re-AOT"
                )
            # Pre-swap prewarm (ISSUE 12, the PR 11 rotation gap): the
            # candidate binds DEVICE-RESIDENT, fully materialized
            # buffers — the first post-swap dispatch pays no transfer —
            # and a fitted candidate's training-panel leaf index is
            # built SHARDED over the mesh here, BEFORE the swap
            # instant, so no post-rotation rescore pays the serial
            # build (BENCH_r05's 8.0 s prefix). All of it runs on the
            # rotation caller's thread; serving continues throughout.
            forest = jax.device_put(forest)
            for leaf in jax.tree_util.tree_leaves(forest):
                if hasattr(leaf, "block_until_ready"):
                    leaf.block_until_ready()
            li = None
            if train_x is not None:
                key = (entry.sig, int(np.shape(train_x)[0]))
                with self._lock:
                    warm = key in self._index_shapes
                    armed = self._compile_mark is not None
                if warm or not armed:
                    with obs.span("serving_leaf_index_prebuild",
                                  model=model_id,
                                  rows=int(np.shape(train_x)[0])):
                        li = self._build_leaf_index(
                            model_id, forest, train_x
                        )
                else:
                    # A NEW panel row count would trace the sharded
                    # build executable inside the armed no-compile
                    # window — skip the prebuild (recorded, never
                    # silent) rather than poison the serving proof;
                    # the swap itself stays warm.
                    obs.emit(
                        "serving_leaf_index_prebuild_skipped",
                        status="skipped", model=model_id,
                        rows=int(np.shape(train_x)[0]),
                    )
            return forest, li

        def installer(pair):
            forest, li = pair
            inj = chaos.active()
            if inj is not None and inj.take_rotate_fault(
                "mid_swap", site=f"rotate/{model_id}"
            ):
                from ate_replication_causalml_tpu.resilience.errors import (
                    ChaosRotateFault,
                )

                raise ChaosRotateFault(
                    f"chaos: injected mid-swap fault ({model_id})"
                )
            version = self.fleet.swap(model_id, forest, checkpoint,
                                      leaf_index=li)
            obs.emit("serving_model_rotated", status="ok",
                     model=model_id, version=version,
                     checkpoint=checkpoint)

        return entry.supervisor.rotate(
            loader, installer, reason=reason, model=model_id
        )

    def retire(self, model_id: str) -> bool:
        """Retire a model: its id keeps answering with a typed
        ``retired_model`` reject (never ``unknown_model`` — a retired
        tenant is a fact, not a typo). Returns whether the id
        existed."""
        entry = self.fleet.get(model_id)
        if entry is None:
            return False
        entry.lifecycle.retire()
        return True

    def retrain_supervisor(self, model_id: str, fit_fn, publish_dir: str,
                           **kwargs):
        """A :class:`~.retrain.RetrainSupervisor` wired to this
        daemon's verified-rotation entry for ``model_id``."""
        from ate_replication_causalml_tpu.serving.retrain import (
            RetrainSupervisor,
        )

        entry = self.fleet.get(model_id)
        if entry is None:
            raise KeyError(f"unknown model {model_id!r}")
        return RetrainSupervisor(
            model_id, fit_fn, publish_dir,
            rotate_fn=lambda path: self.rotate(
                model_id, path, reason="retrain"
            ),
            start_version=entry.version + 1,
            heartbeats=self.heartbeats,
            **kwargs,
        )

    # ── proof + shutdown ─────────────────────────────────────────────

    def compile_events_in_window(self) -> float:
        """Compile/trace events since startup marked the counter — the
        steady-state no-compile proof term. MUST be 0 while serving
        (0.0 before startup completes: no window yet)."""
        with self._lock:
            mark = self._compile_mark
        if mark is None:
            return 0.0
        return obs.compile_event_count() - mark

    def startup_seconds(self) -> dict[str, float]:
        with self._lock:
            return dict(self._startup_s)

    @staticmethod
    def _phase_device_count() -> int:
        """The live registry's decomposed-request count (the
        ``phase=device`` sample of ``serving_phase_seconds`` — every
        decomposed request records each phase exactly once). Process-
        global; the daemon marks it at startup so the reconciliation
        counts only THIS session."""
        m = obs.REGISTRY.family("serving_phase_seconds")
        if m is None:
            return 0
        return sum(
            int(s.get("count", 0))
            for key, s in m.peek_counts().items()
            if "phase=device" in key.split(",")
        )

    @staticmethod
    def _label_value(key: str, label: str) -> str | None:
        """One label's value out of the registry's canonical label-key
        string (``k=v,k2=v2``) — the single parser both decomposition
        readers below share."""
        return dict(
            pair.split("=", 1) for pair in key.split(",") if "=" in pair
        ).get(label)

    def phase_stats(self) -> dict:
        """p50/p99/count per lifecycle phase from the registry's bucket
        histograms — the decomposition the ``stats`` op, loadgen and
        ``bench.py --serving`` report. Empty before any batch served."""
        m = obs.REGISTRY.family("serving_phase_seconds")
        if m is None:
            return {}
        out: dict = {}
        for key, s in sorted(m.peek_counts().items()):
            phase = self._label_value(key, "phase")
            if phase is None:
                continue
            snap = m.snapshot_sample(s)
            out[phase] = {
                "count": snap["count"],
                "mean_s": snap["sum"] / snap["count"] if snap["count"] else 0.0,
                "p50_s": snap["p50"],
                "p99_s": snap["p99"],
                "max_s": snap["max"],
            }
        return out

    def close_reason_counts(self) -> dict[str, int]:
        """Batches by close reason (window expiry vs bucket fill vs
        next-wouldn't-fit vs drain) — the coalescer-policy blame."""
        samples = obs.REGISTRY.peek("serving_batch_close_total") or {}
        out: dict[str, int] = {}
        for key, v in sorted(samples.items()):
            reason = self._label_value(key, "reason")
            if reason is not None and v:
                out[reason] = int(v)
        return out

    @staticmethod
    def _fraction_mean(family: str) -> float:
        m = obs.REGISTRY.family(family)
        if m is None:
            return 0.0
        counts = m.peek_counts()
        n = sum(s["count"] for s in counts.values())
        return sum(s["sum"] for s in counts.values()) / n if n else 0.0

    def deadline_exceeded_counts(self) -> dict[str, int]:
        """Typed deadline rejects by the phase the budget died in
        (admission / queue / dispatch) — the split ``stats`` and the
        loadgen record report, reconciling with the serving report's
        reject-by-reason count."""
        samples = obs.REGISTRY.peek("serving_deadline_exceeded_total") or {}
        out: dict[str, int] = {}
        for key, v in sorted(samples.items()):
            phase = self._label_value(key, "phase")
            if phase is not None and v:
                out[phase] = int(v)
        return out

    def pad_fraction_mean(self) -> float:
        """Mean TRUE-waste pad fraction across per-bucket dispatches
        (fused dispatches report masked, not pad — ISSUE 12)."""
        return self._fraction_mean("serving_pad_fraction")

    def masked_fraction_mean(self) -> float:
        """Mean masked (exact-zero) fraction across fused dispatches."""
        return self._fraction_mean("serving_masked_fraction")

    def model_bindings(self) -> dict:
        """The probe-visible routing table (ISSUE 18): every non-retired
        model id this daemon serves, mapped to its bound checkpoint
        version and path. ``/readyz`` and the ``stats`` op both publish
        this, so a router (or any load balancer) builds its routing
        table from health probes alone — no static model→daemon config
        to drift out of date."""
        return {
            mid: {
                "version": info.get("version"),
                "checkpoint": info.get("checkpoint"),
            }
            for mid, info in self.fleet.describe().items()
            if info.get("state") != "retired"
        }

    def stats(self) -> dict:
        """The ``stats`` op payload: state, depth, startup phases, the
        no-compile window term, the per-phase latency decomposition and
        the SLO burn-rate summary."""
        with self._lock:
            admin = self._admin
        return {
            "state": self.lifecycle.state,
            "queue_depth": self.admission.depth,
            "pending": self.coalescer.pending_depth(),
            "buckets": list(self.config.buckets.sizes),
            "startup_seconds": self.startup_seconds(),
            "compile_events_in_window": self.compile_events_in_window(),
            "faults": self.lifecycle.fault_count,
            "reloads": self.lifecycle.reload_count,
            "phases": self.phase_stats(),
            "close_reasons": self.close_reason_counts(),
            "pad_fraction_mean": self.pad_fraction_mean(),
            "masked_fraction_mean": self.masked_fraction_mean(),
            "fused_buckets": (
                None if self._fusion is None
                else [list(g) for g in self._fusion.groups]
            ),
            "admin_port": admin.port if admin is not None else None,
            # Deadline & liveness plane (ISSUE 14).
            "deadline_exceeded": self.deadline_exceeded_counts(),
            "heartbeats": {
                lane: round(age, 6)
                for lane, age in self.heartbeat_ages().items()
            },
            "stalled_lanes": list(self.stalled_lanes()),
            "slo": self.slo.health(),
            # Fleet state (ISSUE 11): per-model version/lifecycle plus
            # the shedder's cached per-model burn rates. "models" is the
            # compact binding table the router tier consumes (ISSUE 18).
            "fleet": self.fleet.describe(),
            "models": self.model_bindings(),
            "shed_burn_threshold": self._shedder.threshold,
            "shed_burns": self._shedder.burns(),
            # Statistical health (ISSUE 16): per-model sketch counts and
            # last window-pair verdicts.
            "stat_health": self.stat.health(),
        }

    def dump_artifacts(self, outdir: str) -> list[str]:
        """Export the serving window's full artifact set into
        ``outdir``: metrics.json / events.jsonl / metrics.prom, the
        serving ``trace.json`` + ``serving_report.json`` pair,
        ``slo_report.json`` and ``stat_health.json``. Live-safe (the
        ``dump`` op calls this on a
        serving daemon) and called by :meth:`stop` when
        ``$ATE_TPU_METRICS_DIR`` is set. Returns the paths written."""
        from ate_replication_causalml_tpu.observability import (
            serving_report as _sreport,
        )
        from ate_replication_causalml_tpu.observability import trace as _trace

        if not obs.enabled():
            return []
        os.makedirs(outdir, exist_ok=True)
        if obs.trace_enabled():
            # The event log is a process-global ring: keep only this
            # daemon's window (same filter run_sweep applies). The
            # trace is built BEFORE the metrics snapshot so the
            # reconciliation's requests_in_metrics can never undercount
            # the trace's view (a request landing between the two bumps
            # metrics only).
            records = [
                r for r in obs.EVENTS.records()
                if r.get("start_mono_s", 0.0) >= self._born_mono - 1e-6
            ]
            with self._lock:
                phase_mark = self._phase_mark
            tr = _trace.build_trace(records, meta=_trace.run_meta(
                tool="serving",
                checkpoint=self.config.checkpoint,
                buckets=",".join(str(b) for b in self.config.buckets.sizes),
                serving_phase_mark=phase_mark,
            ))
            paths = obs.write_run_artifacts(outdir)
            # The reconciliation reads the metrics.json that was just
            # written — the same file the analyzer CLI will read — so
            # the daemon's serving_report.json and a bit-for-bit
            # analyzer reproduction can only agree.
            import json as _json

            with open(os.path.join(outdir, "metrics.json")) as f:
                metrics_snapshot = _json.load(f)
            paths += _sreport.write_serving_artifacts(
                outdir, tr, metrics=metrics_snapshot
            )
        else:
            paths = obs.write_run_artifacts(outdir)
        spath = os.path.join(outdir, _sreport.SLO_REPORT_BASENAME)
        obs.atomic_write_json(spath, self.slo.evaluate())
        paths.append(spath)
        # stat_health.json rides the same one-write-recipe discipline as
        # serving_report.json: the analyzer recomputes the identical
        # bytes from the embedded raw state (ISSUE 16).
        stathealth.write_stat_health(outdir, self.stat.state_dict())
        paths.append(os.path.join(outdir, stathealth.STAT_HEALTH_BASENAME))
        return paths

    def drain(self, timeout_s: float | None = None,
              clock=time.monotonic, sleep=time.sleep) -> str:
        """Graceful drain (ISSUE 14): move through the ``draining``
        lifecycle state — new admissions get typed ``draining`` rejects
        with retry-after, queued and in-flight requests COMPLETE (the
        coalescer flushes immediately instead of waiting out windows),
        artifacts dump, and the daemon stops — all within
        ``timeout_s`` (default ``ATE_TPU_SERVE_DRAIN_S``). Returns
        ``"drained"`` (zero in-flight requests dropped) or
        ``"timeout"`` (bound exceeded with work still in flight — a
        recorded ``serving_drain_timeout`` event; the CLI's SIGTERM
        handler force-exits nonzero on it). Exactly one caller owns the
        drain; concurrent and repeat callers BLOCK until the owning
        drain finishes and report its real outcome (a SIGTERM handler
        that was told "drained" while a wire-op drain was still in
        flight would ``os._exit(0)`` mid-drain and drop its work). The
        clock and sleep are injectable so the state machine is provable
        without wall-clock sleeping."""
        bound = (
            self.config.drain_timeout_s if timeout_s is None
            else float(timeout_s)
        )
        if not self.lifecycle.mark_draining():
            if self._drain_done.is_set():
                return self._drain_outcome or "timeout"
            if self.lifecycle.state == STOPPED:
                # Stopped without any drain (plain stop()) — terminal;
                # honest about whether work was still in flight.
                return ("drained" if self.admission.depth == 0
                        else "timeout")
            # The owning drain is still in flight: ride out ITS bound
            # (not ours — a SIGTERM arriving with the config default
            # must not cut short a wire drain that asked for longer),
            # padded for the owner's post-drain stop/export work.
            wait_cap = max(bound, self._drain_bound or 0.0,
                           self.config.drain_timeout_s) + 30.0
            if self._drain_done.wait(wait_cap):
                return self._drain_outcome or "timeout"
            return "timeout"
        self._drain_bound = bound
        budget = Budget.after(bound, clock=clock)
        obs.emit("serving_drain", status="started", bound_s=bound,
                 in_flight=self.admission.depth)
        # Flush the coalescer: every remaining next_batch call packs
        # immediately (close semantics), so queued waiters ride out on
        # the dispatcher without waiting for windows to expire.
        self.coalescer.close()
        while self.admission.depth > 0 and not budget.expired():
            sleep(min(0.005, max(1e-4, budget.remaining_s())))
        dropped = self.admission.depth
        outcome = "drained" if dropped == 0 else "timeout"
        self._drains.inc(1, outcome=outcome)
        if outcome == "drained":
            obs.emit("serving_drained", status="ok", bound_s=bound)
        else:
            obs.emit("serving_drain_timeout", status="error",
                     bound_s=bound, in_flight=dropped)
        self._drain_outcome = outcome
        try:
            self.stop(timeout=max(1.0, budget.remaining_s()))
        finally:
            # Release waiters even if stop() raises (the no-compile
            # enforcement can) — a non-owning SIGTERM handler spinning
            # forever on a dead owner is its own wedge.
            self._drain_done.set()
        return outcome

    def stop(self, timeout: float = 10.0) -> None:
        """Drain, stop the dispatcher and the observability plane,
        export telemetry (when ``$ATE_TPU_METRICS_DIR`` is set) and
        ENFORCE the no-compile guarantee: any compile event inside the
        serving window raises (``strict_no_compile=False`` downgrades
        to an error event for diagnostics runs). Idempotent — the
        drain path stops the daemon itself, and a later teardown
        stop() must be a no-op, not a second export."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            wd = self._watchdog
            self._watchdog = None
        if wd is not None:
            wd.stop()
        self._reloader.join(timeout)
        self.coalescer.close()
        self.lifecycle.mark_stopped()
        with self._lock:
            t = self._dispatcher
            sampler = self._sampler
            admin = self._admin
            self._sampler = None
            self._admin = None
        if t is not None:
            t.join(timeout)
        if sampler is not None:
            sampler.stop()
        if admin is not None:
            admin.stop()
        leaked = self.compile_events_in_window()
        obs.gauge(
            "serving_compile_events_in_window",
            "compile events recorded during the serving window (must be 0)",
        ).set(leaked)
        outdir = os.environ.get("ATE_TPU_METRICS_DIR")
        if outdir:
            try:
                self.dump_artifacts(outdir)
            except Exception as e:
                # Telemetry export must never mask the serving outcome.
                obs.emit("serving_export_failed", status="error",
                         error=f"{type(e).__name__}: {e}")
        if leaked:
            obs.emit("serving_compile_in_window", status="error",
                     events=leaked)
            if self.config.strict_no_compile:
                raise RuntimeError(
                    f"serving window recorded {leaked:g} jax compile/trace "
                    "events — the steady state must never compile"
                )


# ── wire serving (socket / stdio) ────────────────────────────────────


def _handle_op(server: CateServer, header: dict, arrays: dict):
    """One request frame → one reply ``(header, arrays, stop?)``."""
    op = header.get("op")
    rid = str(header.get("id", ""))
    if op == "predict":
        x = arrays.get("x")
        if x is None:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "predict needs an 'x' array"}, {}, False
        model = header.get("model")
        try:
            req = server.serve_request(
                rid, x, model=model,
                deadline_ms=header.get("deadline_ms"),
            )
        except RejectedRequest as rej:
            reply = {"ok": False, "id": rid, "error": rej.code,
                     "message": rej.message}
            if rej.retry_after_s is not None:
                reply["retry_after_s"] = rej.retry_after_s
            return reply, {}, False
        except Exception as e:
            # The wire contract is "always a reply": any request-scoped
            # failure — dispatch error, timeout, a validation case the
            # typed rejects missed — becomes an error frame, never a
            # dead connection (recorded; the daemon itself survives).
            obs.emit("serving_request_error", status="error",
                     request_id=rid, error=f"{type(e).__name__}: {e}")
            return {"ok": False, "id": rid, "error": "error",
                    "message": f"{type(e).__name__}: {e}"}, {}, False
        cate, var = req.result
        return (
            # The reply names the model VERSION that served it — the
            # client-visible bit-identity partition key across a
            # rotation.
            {"ok": True, "id": rid, "model": req.model,
             "model_version": req.model_version},
            {"cate": cate, "variance": var},
            False,
        )
    if op == "rotate":
        # Operator-triggered zero-downtime hot-swap. Serving continues
        # for the whole verify window; a refused candidate keeps the
        # last good model.
        model = str(header.get("model") or DEFAULT_MODEL)
        checkpoint = header.get("checkpoint")
        if not checkpoint:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "rotate needs a 'checkpoint' header field"
                    }, {}, False
        status = server.rotate(model, str(checkpoint), reason="op")
        return {"ok": status == "rotated", "op": "rotate",
                "model": model, "status": status}, {}, False
    if op == "retire":
        model = str(header.get("model") or "")
        known = server.retire(model)
        return {"ok": known, "op": "retire", "model": model,
                "status": "retired" if known else "unknown_model"
                }, {}, False
    if op == "ping":
        return {"ok": True, "op": "ping",
                "state": server.lifecycle.state}, {}, False
    if op == "stats":
        return {"ok": True, "op": "stats", "stats": server.stats()}, {}, False
    if op == "dump":
        # Live artifact export (ISSUE 7): trace.json + serving_report
        # + slo_report + metrics triple, without stopping the daemon.
        outdir = header.get("dir") or os.environ.get("ATE_TPU_METRICS_DIR")
        if not outdir:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "dump needs a 'dir' header field or "
                               "$ATE_TPU_METRICS_DIR"}, {}, False
        try:
            paths = server.dump_artifacts(outdir)
        except Exception as e:
            obs.emit("serving_dump_failed", status="error",
                     error=f"{type(e).__name__}: {e}")
            return {"ok": False, "id": rid, "error": "error",
                    "message": f"{type(e).__name__}: {e}"}, {}, False
        return {"ok": True, "op": "dump", "paths": paths}, {}, False
    if op == "drain":
        # Graceful shutdown over the wire (ISSUE 14): in-flight work
        # from EVERY connection completes, then the daemon stops and
        # the serve loop exits. The reply is sent after the drain so
        # the caller knows the outcome ("drained" = zero dropped).
        timeout = header.get("timeout_s")
        try:
            timeout = None if timeout is None else float(timeout)
        except (TypeError, ValueError):
            return {"ok": False, "error": "bad_request",
                    "message": f"timeout_s {timeout!r} is not a number"
                    }, {}, False
        outcome = server.drain(timeout)
        return {"ok": outcome == "drained", "op": "drain",
                "outcome": outcome}, {}, True
    if op == "shutdown":
        return {"ok": True, "op": "shutdown"}, {}, True
    return {"ok": False, "error": "bad_request",
            "message": f"unknown op {op!r}"}, {}, False


def serve_stream(server: CateServer, rstream, wstream) -> bool:
    """Serve one connection's framed request loop. Returns True when a
    ``shutdown`` op asked the whole daemon to exit."""
    while True:
        try:
            frame = protocol.read_frame(rstream)
        except protocol.ProtocolError as e:
            # A torn/corrupt frame kills THIS connection (there is no
            # way to resynchronize a length-prefixed stream), never the
            # daemon.
            obs.emit("serving_protocol_error", status="error", error=str(e))
            return False
        if frame is None:
            return False
        header, arrays = frame
        reply, out_arrays, stop = _handle_op(server, header, arrays)
        protocol.write_frame(wstream, reply, out_arrays)
        if stop:
            return True


def serve_stdio(server: CateServer) -> None:
    """Serve a single peer over stdin/stdout (the subprocess transport;
    logs belong on stderr)."""
    import sys

    serve_stream(server, sys.stdin.buffer, sys.stdout.buffer)
    server.stop()


def serve_socket(server: CateServer, host: str = "127.0.0.1",
                 port: int = 0,
                 on_bound: Callable[[int], None] | None = None) -> None:
    """Accept loop: one reader thread per connection, all feeding the
    shared coalescer (this is where micro-batching pays). Returns after
    a ``shutdown`` op. Binds ``port`` (0 = ephemeral; the bound port is
    printed to stderr and exported as a gauge for discovery —
    ``on_bound`` gets it directly, for in-process rigs that run this
    loop on a thread and cannot parse their own stderr)."""
    import sys

    stop_evt = threading.Event()
    with socket.create_server((host, port)) as srv:
        srv.settimeout(0.25)
        bound = srv.getsockname()[1]
        obs.gauge("serving_port", "bound TCP port").set(bound)
        print(f"# serving on {host}:{bound}", file=sys.stderr, flush=True)
        if on_bound is not None:
            on_bound(bound)

        def _conn(conn: socket.socket) -> None:
            with conn:
                rw = conn.makefile("rwb")
                try:
                    if serve_stream(server, rw, rw):
                        stop_evt.set()
                finally:
                    rw.close()

        threads: list[threading.Thread] = []
        conn_seq = 0
        # The accept loop also exits when the daemon stops underneath
        # it — a SIGTERM-driven drain() (scripts/serve.py) ends serving
        # without any connection sending a shutdown op.
        while not stop_evt.is_set() and server.lifecycle.state != "stopped":
            # Prune finished connections each pass — a long-lived daemon
            # accepts millions of short connections and must not retain
            # one dead Thread object per connection.
            threads = [t for t in threads if t.is_alive()]
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            conn_seq += 1
            # The thread name IS the trace track: every connection gets
            # its own timeline row in the exported serving trace.
            t = threading.Thread(target=_conn, args=(conn,), daemon=True,
                                 name=f"conn-{conn_seq}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(1.0)
    server.stop()

"""CATE serving daemon (ISSUE 6, the tentpole).

The predict path does 1M rows of CATE + variance in ~1.4 s steady, but
every fresh process pays a ~25-30 s trace/deserialize tail (NEXT.md §3:
"irreducible without ahead-of-time tracing or a persistent daemon").
This is the daemon: a long-lived process that pays the tail ONCE, as an
explicit startup phase, and then serves τ̂(x) (+ variance) queries whose
steady state provably never traces or compiles.

Startup phases (each a span + a ``serving_startup_seconds`` gauge):

1. **load** — ``utils/checkpoint.load_fitted`` with SHA-256
   verification; a torn or tampered forest checkpoint refuses to serve.
2. **aot** — one ``jax.jit(...).lower().compile()`` predict executable
   per declared batch bucket (``lower_predict_cate``; the same AOT
   machinery as ``scheduler/prefetch.py``), forest as a *runtime*
   argument so reloads reuse executables.
3. **warm** — one zero-batch dispatch per bucket, absorbing the
   first-dispatch transfer/conversion compiles.

After warm, the compile-event counter (``jax_compiles_total``, bridged
from ``jax.monitoring``) is marked; :meth:`CateServer.stop` asserts the
serving window left it unchanged — the no-compile guarantee is enforced
from the metrics registry, not hoped.

The serving core is the no-jax trio this module wires together:
admission (bounded depth, typed reject-on-overload), the coalescer
(micro-batch within a deadline window, pad to the nearest compiled
bucket), and the lifecycle/reload supervisor (degraded-mode serving:
on a fault — injected via the ``serve:`` chaos scope or real — requests
get typed retry-after rejects while the checkpoint is re-verified and
reloaded, then serving resumes; values after recovery are bit-identical
because the model is the same verified bytes).

Every protocol request gets a ``serving_request`` span; latencies ride
the ``serving_request_seconds`` bucket histogram, queue depth and batch
fill the registry, and everything exports through the same atomic
``metrics.json`` path as the sweep.

The observability plane (ISSUE 7) rides the same machinery:

* every request carries monotonic lifecycle marks (admission →
  coalescer close → dispatcher pickup → device entry/exit → reply), so
  its latency decomposes into ``coalesce_wait / queue_wait / dispatch /
  device / reply`` — per-phase bucket histograms + span attrs whose sum
  IS the end-to-end latency;
* ``stop()`` (and the ``dump`` op) export the serving window's
  ``trace.json`` (one track per connection, a dispatcher/device track,
  request→batch→reply flow arrows) plus ``serving_report.json`` (a pure
  function of the trace — ``scripts/analyze_trace.py`` recomputes it
  bit-for-bit) and ``slo_report.json`` (multi-window burn rates from
  ``observability/slo.py``);
* an optional read-only admin endpoint (``serving/admin.py``,
  ``ATE_TPU_SERVE_ADMIN_PORT``) serves ``/metrics`` / ``/healthz`` /
  ``/readyz`` / ``/varz`` live — degraded serving is a 503 on readyz.

None of it traces or compiles jax — the zero-compile window assertion
in :meth:`CateServer.stop` holds with the whole plane active.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability.slo import (
    DEFAULT_WINDOWS,
    SLOEngine,
    default_serving_slos,
)
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.serving import protocol
from ate_replication_causalml_tpu.serving.admission import (
    AdmissionController,
    ReloadSupervisor,
    ServingLifecycle,
)
from ate_replication_causalml_tpu.serving.coalescer import (
    Batch,
    BucketPlan,
    Coalescer,
    PendingRequest,
)

ENV_BUCKETS = "ATE_TPU_SERVE_BUCKETS"
ENV_WINDOW_MS = "ATE_TPU_SERVE_WINDOW_MS"
ENV_DEPTH = "ATE_TPU_SERVE_DEPTH"
ENV_RETRY_AFTER_MS = "ATE_TPU_SERVE_RETRY_AFTER_MS"
ENV_ADMIN_PORT = "ATE_TPU_SERVE_ADMIN_PORT"
ENV_SLO_MS = "ATE_TPU_SERVE_SLO_MS"

DEFAULT_BUCKETS = "1,8,64,256"
DEFAULT_WINDOW_MS = 2.0
DEFAULT_DEPTH = 64
DEFAULT_RETRY_AFTER_MS = 50.0
DEFAULT_SLO_LATENCY_MS = 250.0


class RejectedRequest(RuntimeError):
    """A typed reject: carries the wire ``error`` code and the
    retry-after hint. Raised out of :meth:`CateServer.serve_one` only
    for callers that asked (``raise_rejects=True``); the protocol layer
    turns it into a reject frame instead."""

    def __init__(self, code: str, message: str, retry_after_s: float | None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Daemon configuration; :meth:`from_env` reads the
    ``ATE_TPU_SERVE_*`` knobs documented in the README."""

    checkpoint: str
    buckets: BucketPlan = dataclasses.field(
        default_factory=lambda: BucketPlan.parse(DEFAULT_BUCKETS)
    )
    window_s: float = DEFAULT_WINDOW_MS / 1e3
    max_depth: int = DEFAULT_DEPTH
    retry_after_s: float = DEFAULT_RETRY_AFTER_MS / 1e3
    row_backend: str | None = None
    variance_compat: str = "unbiased"
    donate: bool | None = None
    tree_chunk: int = 32
    #: stop() raises if the serving window recorded any compile event;
    #: the enforcement knob exists for diagnostics, not for production.
    strict_no_compile: bool = True
    #: admin endpoint (ISSUE 7): None = off (the default); an int binds
    #: that TCP port on startup (0 = ephemeral, for tests).
    admin_port: int | None = None
    #: latency-SLO threshold: requests over this spend the error budget.
    slo_latency_s: float = DEFAULT_SLO_LATENCY_MS / 1e3
    #: multi-window burn-rate ladder (ascending; see observability/slo).
    slo_windows_s: tuple[float, ...] = DEFAULT_WINDOWS

    @classmethod
    def from_env(cls, checkpoint: str, **overrides) -> "ServeConfig":
        env = os.environ
        base = dict(
            buckets=BucketPlan.parse(env.get(ENV_BUCKETS, DEFAULT_BUCKETS)),
            window_s=float(env.get(ENV_WINDOW_MS, DEFAULT_WINDOW_MS)) / 1e3,
            max_depth=int(env.get(ENV_DEPTH, DEFAULT_DEPTH)),
            retry_after_s=float(
                env.get(ENV_RETRY_AFTER_MS, DEFAULT_RETRY_AFTER_MS)
            ) / 1e3,
            slo_latency_s=float(
                env.get(ENV_SLO_MS, DEFAULT_SLO_LATENCY_MS)
            ) / 1e3,
        )
        if env.get(ENV_ADMIN_PORT):
            base["admin_port"] = int(env[ENV_ADMIN_PORT])
        base.update(overrides)
        return cls(checkpoint=checkpoint, **base)


class CateServer:
    """The serving core: verified load → AOT → warm → steady dispatch.

    Thread model: any number of producer threads call
    :meth:`serve_one` / :meth:`submit`; ONE dispatcher thread owns the
    device (jax dispatch is serialized by design — the scheduler PR
    established that concurrent device entry buys nothing on one chip
    and can deadlock collectives). Shared state (the model reference,
    the executable table) is mutated only under ``self._lock``
    (graftlint JGL008 covers ``serving/``).
    """

    def __init__(self, config: ServeConfig):
        self.config = config
        self.lifecycle = ServingLifecycle()
        self.admission = AdmissionController(config.max_depth)
        self.coalescer = Coalescer(config.buckets, config.window_s)
        self._lock = threading.RLock()
        self._model = None
        self._executables: dict[int, object] = {}
        self._n_features: int | None = None
        # None until startup completes: a daemon stopped before its
        # warm phase has no serving window to enforce.
        self._compile_mark: float | None = None
        self._startup_s: dict[str, float] = {}
        self._dispatcher: threading.Thread | None = None
        # Everything the serving trace exports is filtered to records
        # at/after this mark — the event log is a process-global ring
        # shared with whatever ran before the daemon.
        self._born_mono = time.monotonic()
        self._reloader = ReloadSupervisor(
            self.lifecycle, self._load_checkpoint, self._install_model
        )
        self.slo = SLOEngine(default_serving_slos(
            latency_threshold_s=config.slo_latency_s,
            windows_s=config.slo_windows_s,
        ))
        self._admin = None
        self._sampler: obs.MetricSampler | None = None
        self._requests = obs.counter(
            "serving_requests_total", "CATE serving requests by terminal status"
        )
        self._rejects = obs.counter(
            "serving_rejected_total", "CATE serving rejections by reason"
        )
        self._batches = obs.counter(
            "serving_batches_total", "dispatched micro-batches by bucket"
        )
        self._latency = obs.bucket_histogram(
            "serving_request_seconds", "served request latency (enqueue to reply)"
        )
        self._fill = obs.bucket_histogram(
            "serving_batch_fill",
            "micro-batch fill ratio (real rows / bucket rows)",
            bounds=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        # Lifecycle decomposition (ISSUE 7): one bucket-histogram family
        # labeled by phase (quantiles) plus a counter mirror (the
        # schema-contract family — "no phase was ever recorded" must be
        # an explicit 0 in metrics.json) and the batch close reasons.
        self._phase_hist = obs.bucket_histogram(
            "serving_phase_seconds",
            "per-request lifecycle phase durations",
        )
        self._phase_total = obs.counter(
            "serving_phase_seconds_total",
            "summed per-request lifecycle phase seconds",
        )
        self._close_reasons = obs.counter(
            "serving_batch_close_total", "micro-batch close reasons"
        )
        self._pad = obs.bucket_histogram(
            "serving_pad_fraction",
            "padded fraction of dispatched bucket rows (1 - fill)",
            bounds=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )

    # ── startup ──────────────────────────────────────────────────────

    def _load_checkpoint(self):
        """SHA-256-verified model load; accepts a ``FittedCausalForest``
        or a bare ``CausalForest`` checkpoint. Raises
        ``CheckpointCorrupt`` (startup: refuse to serve; degraded
        reload: stay degraded) on any integrity failure."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            CausalForest,
            FittedCausalForest,
        )
        from ate_replication_causalml_tpu.utils.checkpoint import load_fitted

        obj = load_fitted(self.config.checkpoint, verify=True)
        forest = obj.forest if isinstance(obj, FittedCausalForest) else obj
        if not isinstance(forest, CausalForest):
            raise TypeError(
                f"checkpoint {self.config.checkpoint!r} holds "
                f"{type(obj).__name__}, not a causal forest"
            )
        return forest

    def _install_model(self, forest) -> None:
        """Swap the served model (startup and verified reloads). The
        executables are keyed to the forest's SHAPES — a reload with a
        different geometry would need a re-AOT, which degraded mode
        refuses (same-shape redeploys are the supported hot path)."""
        with self._lock:
            old = self._model
            if old is not None and (
                old.split_feat.shape != forest.split_feat.shape
                or old.bin_edges.shape != forest.bin_edges.shape
            ):
                raise ValueError(
                    "reloaded checkpoint changed forest geometry "
                    f"({old.split_feat.shape} -> {forest.split_feat.shape}); "
                    "restart the daemon to re-AOT"
                )
            self._model = forest
            self._n_features = int(forest.bin_edges.shape[0])

    def startup(self) -> dict[str, float]:
        """Run the three startup phases; returns their seconds (also
        exported as ``serving_startup_seconds{phase=}`` gauges)."""
        from ate_replication_causalml_tpu.models.causal_forest import (
            lower_predict_cate,
        )

        obs.install_jax_monitoring()
        import jax

        phases: dict[str, float] = {}
        with obs.span("serving_startup", checkpoint=self.config.checkpoint):
            t0 = time.perf_counter()
            with obs.span("serving_load"):
                self._install_model(self._load_checkpoint())
            phases["load"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            with self._lock:
                model = self._model
            for bucket in self.config.buckets.sizes:
                with obs.span("serving_aot_compile", bucket=bucket):
                    compiled = lower_predict_cate(
                        model,
                        bucket,
                        oob=False,
                        tree_chunk=self.config.tree_chunk,
                        row_backend=self.config.row_backend,
                        variance_compat=self.config.variance_compat,
                        donate=self.config.donate,
                    ).compile()
                with self._lock:
                    self._executables[bucket] = compiled
            phases["aot"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            with obs.span("serving_warm"):
                p = self._n_features
                for bucket in self.config.buckets.sizes:
                    zeros = jax.device_put(np.zeros((bucket, p), np.float32))
                    out = self._executables[bucket](model, zeros, None)
                    np.asarray(out.cate), np.asarray(out.variance)
            phases["warm"] = time.perf_counter() - t0

        g = obs.gauge(
            "serving_startup_seconds", "daemon startup phase durations"
        )
        for phase, secs in phases.items():
            g.set(secs, phase=phase)
        self._start_observability_plane()
        with self._lock:
            self._startup_s = dict(phases)
            self._compile_mark = obs.compile_event_count()
        self.lifecycle.mark_ready()
        self._start_dispatcher()
        return phases

    def _start_observability_plane(self) -> None:
        """The ISSUE 7 plane: background counter sampling for the
        serving trace, and the optional admin endpoint. Both are
        jax-free — starting them inside the no-compile window is the
        point (the window assertion proves they stay that way)."""
        if obs.enabled() and obs.trace_enabled():
            sampler = obs.MetricSampler(
                metrics=obs.MetricSampler.SERVING_METRICS
            )
            sampler.start()
            with self._lock:
                self._sampler = sampler
        if self.config.admin_port is not None:
            from ate_replication_causalml_tpu.serving.admin import AdminServer

            admin = AdminServer(self)
            try:
                bound = admin.start(self.config.admin_port)
            except BaseException:
                # A failed admin bind (port taken, privileged) aborts
                # startup — but must not leak the sampler thread into a
                # process that will never call stop().
                with self._lock:
                    sampler, self._sampler = self._sampler, None
                if sampler is not None:
                    sampler.stop()
                raise
            with self._lock:
                self._admin = admin
            obs.gauge("serving_admin_port", "bound admin HTTP port").set(bound)
            obs.emit("serving_admin_started", status="ok", port=bound)

    def _start_dispatcher(self) -> None:
        with self._lock:
            t = threading.Thread(
                target=self._dispatch_loop, name="serving-dispatch",
                daemon=True,
            )
            self._dispatcher = t
        t.start()

    # ── request path (producers) ─────────────────────────────────────

    def _reject(self, code: str, message: str,
                retry_after_s: float | None = None,
                request_id: str = "") -> RejectedRequest:
        self._rejects.inc(1, reason=code)
        self._requests.inc(1, status=f"rejected_{code}")
        # The reject timeline (ISSUE 7): one instant per refusal, so
        # the serving trace/report show WHEN admission pushed back, not
        # just how often. Covers every entry path — serve_one spans and
        # raw submit() callers alike.
        obs.emit("serving_reject", status="error", reason=code,
                 request_id=str(request_id))
        return RejectedRequest(code, message, retry_after_s)

    def submit(self, request_id: str, x: np.ndarray) -> PendingRequest:
        """Admission + chaos + coalesce. Returns the pending handle the
        caller waits on; raises :class:`RejectedRequest` for every typed
        refusal (the protocol layer converts those to reject frames).
        The admission slot is released by the dispatcher on resolve."""
        try:
            x = np.ascontiguousarray(x, dtype=np.float32)
        except (TypeError, ValueError) as e:
            # String/object/datetime queries must become a typed reject,
            # not a connection-killing exception.
            raise self._reject(
                "bad_request", f"x does not convert to float32 ({e})",
                request_id=request_id,
            ) from e
        if x.ndim != 2:
            raise self._reject("bad_request", f"x must be 2-D, got {x.shape}",
                               request_id=request_id)
        with self._lock:
            p = self._n_features
        if p is not None and x.shape[1] != p:
            raise self._reject(
                "bad_request", f"x has {x.shape[1]} features, model wants {p}",
                request_id=request_id,
            )
        rows = x.shape[0]
        if rows < 1 or rows > self.config.buckets.max_rows:
            raise self._reject(
                "bad_request",
                f"rows must be in [1, {self.config.buckets.max_rows}], "
                f"got {rows} (chunk larger queries client-side)",
                request_id=request_id,
            )
        inj = chaos.active()
        if inj is not None and inj.take_serve_fault(request_id):
            # The injected fault walks the REAL degraded path: recovery
            # re-verifies and reloads the checkpoint in the background
            # while this (and any concurrent) request is refused typed.
            self._reloader.report_fault(f"chaos:req/{request_id}")
            raise self._reject(
                "serve_fault",
                "injected serving fault; degraded-mode recovery running",
                self.config.retry_after_s, request_id=request_id,
            )
        if not self.lifecycle.can_serve():
            state = self.lifecycle.state
            raise self._reject(
                "degraded" if state == "degraded" else state,
                f"daemon is {state}",
                self.config.retry_after_s, request_id=request_id,
            )
        if not self.admission.try_admit():
            raise self._reject(
                "overloaded",
                f"admission queue at max depth {self.config.max_depth}",
                self.config.retry_after_s, request_id=request_id,
            )
        req = PendingRequest(
            str(request_id), x, rows, time.monotonic()
        )
        try:
            self.coalescer.submit(req)
        except BaseException:
            self.admission.release()
            raise
        return req

    def serve_one(
        self, request_id: str, x: np.ndarray, timeout: float | None = 30.0
    ) -> tuple[np.ndarray, np.ndarray]:
        """Blocking request path: submit, wait, return
        ``(cate, variance)`` for exactly the submitted rows. Every call
        gets a ``serving_request`` span; rejects raise
        :class:`RejectedRequest`, dispatch failures re-raise the
        dispatcher's error."""
        with obs.span("serving_request", request_id=str(request_id),
                      rows=int(np.shape(x)[0]) if np.ndim(x) == 2 else -1
                      ) as sp:
            try:
                req = self.submit(request_id, x)
            except RejectedRequest as rej:
                sp.set_status("rejected")
                sp.set_attr("reject", rej.code)
                raise
            if not req.wait(timeout):
                sp.set_status("error")
                self._requests.inc(1, status="timeout")
                raise TimeoutError(
                    f"request {request_id!r} not served in {timeout}s"
                )
            if req.error is not None:
                sp.set_status("error")
                self._requests.inc(1, status="error")
                self._latency.observe(
                    req.resolved_mono - req.enqueued_mono, status="error"
                )
                raise req.error
            self._requests.inc(1, status="ok")
            self._latency.observe(
                req.resolved_mono - req.enqueued_mono, status="ok"
            )
            # Lifecycle decomposition on the span (ISSUE 7): the phase
            # attrs whose sum is the end-to-end latency, plus the batch
            # linkage the trace exporter turns into request→batch→reply
            # flow arrows and serving_report.json aggregates.
            ph = req.phase_seconds()
            if ph is not None:
                for phase, secs in ph.items():
                    sp.set_attr(f"{phase}_s", round(secs, 9))
                sp.set_attr(
                    "e2e_s",
                    round(req.resolved_mono - req.enqueued_mono, 9),
                )
                sp.set_attr("batch_seq", req.batch_seq)
                sp.set_attr("bucket", req.batch_bucket)
                sp.set_attr("pad_fraction", round(1.0 - req.batch_fill, 6))
            return req.result

    # ── dispatch (the single device-owning thread) ───────────────────

    def _dispatch_loop(self) -> None:
        while True:
            batch = self.coalescer.next_batch(timeout=0.25)
            if batch is None:
                if self.lifecycle.state == "stopped":
                    return
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: Batch) -> None:
        import jax

        picked = time.monotonic()
        with self._lock:
            model = self._model
            compiled = self._executables[batch.bucket]
            p = self._n_features
        now = time.monotonic
        with obs.span("serving_batch", bucket=batch.bucket,
                      rows=batch.rows, requests=len(batch.requests),
                      seq=batch.seq, close_reason=batch.close_reason,
                      fill=round(batch.fill, 6)):
            try:
                padded = np.zeros((batch.bucket, p), np.float32)
                off = 0
                for req in batch.requests:
                    padded[off:off + req.rows] = req.x
                    off += req.rows
                x_dev = jax.device_put(padded)
                device_start = now()
                out = compiled(model, x_dev, None)
                cate = np.asarray(out.cate)
                var = np.asarray(out.variance)
                device_end = now()
            except Exception as e:
                # A dispatch failure fails THIS batch's requests typed
                # and walks degraded recovery; the daemon itself
                # survives (never-crash is the serving contract).
                for req in batch.requests:
                    req.picked_mono = picked
                    req.fail(e, now())
                    self.admission.release()
                self._reloader.report_fault(
                    f"dispatch:{type(e).__name__}"
                )
                return
            off = 0
            for req in batch.requests:
                req.picked_mono = picked
                req.device_start_mono = device_start
                req.device_end_mono = device_end
                req.resolve(
                    (cate[off:off + req.rows].copy(),
                     var[off:off + req.rows].copy()),
                    now(),
                )
                off += req.rows
                self.admission.release()
        self._batches.inc(1, bucket=batch.bucket)
        self._fill.observe(batch.fill, bucket=batch.bucket)
        self._close_reasons.inc(1, reason=batch.close_reason)
        self._pad.observe(1.0 - batch.fill, bucket=batch.bucket)
        for req in batch.requests:
            ph = req.phase_seconds()
            if ph is None:
                continue
            for phase, secs in ph.items():
                self._phase_hist.observe(secs, phase=phase)
                self._phase_total.inc(max(0.0, secs), phase=phase)
        # One SLO snapshot per dispatched batch: cheap (a dict copy per
        # family) and exactly as fresh as the data it judges.
        self.slo.tick()

    # ── proof + shutdown ─────────────────────────────────────────────

    def compile_events_in_window(self) -> float:
        """Compile/trace events since startup marked the counter — the
        steady-state no-compile proof term. MUST be 0 while serving
        (0.0 before startup completes: no window yet)."""
        with self._lock:
            mark = self._compile_mark
        if mark is None:
            return 0.0
        return obs.compile_event_count() - mark

    def startup_seconds(self) -> dict[str, float]:
        with self._lock:
            return dict(self._startup_s)

    @staticmethod
    def _label_value(key: str, label: str) -> str | None:
        """One label's value out of the registry's canonical label-key
        string (``k=v,k2=v2``) — the single parser both decomposition
        readers below share."""
        return dict(
            pair.split("=", 1) for pair in key.split(",") if "=" in pair
        ).get(label)

    def phase_stats(self) -> dict:
        """p50/p99/count per lifecycle phase from the registry's bucket
        histograms — the decomposition the ``stats`` op, loadgen and
        ``bench.py --serving`` report. Empty before any batch served."""
        m = obs.REGISTRY.family("serving_phase_seconds")
        if m is None:
            return {}
        out: dict = {}
        for key, s in sorted(m.peek_counts().items()):
            phase = self._label_value(key, "phase")
            if phase is None:
                continue
            snap = m.snapshot_sample(s)
            out[phase] = {
                "count": snap["count"],
                "mean_s": snap["sum"] / snap["count"] if snap["count"] else 0.0,
                "p50_s": snap["p50"],
                "p99_s": snap["p99"],
                "max_s": snap["max"],
            }
        return out

    def close_reason_counts(self) -> dict[str, int]:
        """Batches by close reason (window expiry vs bucket fill vs
        next-wouldn't-fit vs drain) — the coalescer-policy blame."""
        samples = obs.REGISTRY.peek("serving_batch_close_total") or {}
        out: dict[str, int] = {}
        for key, v in sorted(samples.items()):
            reason = self._label_value(key, "reason")
            if reason is not None and v:
                out[reason] = int(v)
        return out

    def pad_fraction_mean(self) -> float:
        """Mean padded fraction across all dispatched batches."""
        m = obs.REGISTRY.family("serving_pad_fraction")
        if m is None:
            return 0.0
        counts = m.peek_counts()
        n = sum(s["count"] for s in counts.values())
        return sum(s["sum"] for s in counts.values()) / n if n else 0.0

    def stats(self) -> dict:
        """The ``stats`` op payload: state, depth, startup phases, the
        no-compile window term, the per-phase latency decomposition and
        the SLO burn-rate summary."""
        with self._lock:
            admin = self._admin
        return {
            "state": self.lifecycle.state,
            "queue_depth": self.admission.depth,
            "pending": self.coalescer.pending_depth(),
            "buckets": list(self.config.buckets.sizes),
            "startup_seconds": self.startup_seconds(),
            "compile_events_in_window": self.compile_events_in_window(),
            "faults": self.lifecycle.fault_count,
            "reloads": self.lifecycle.reload_count,
            "phases": self.phase_stats(),
            "close_reasons": self.close_reason_counts(),
            "pad_fraction_mean": self.pad_fraction_mean(),
            "admin_port": admin.port if admin is not None else None,
            "slo": self.slo.health(),
        }

    def dump_artifacts(self, outdir: str) -> list[str]:
        """Export the serving window's full artifact set into
        ``outdir``: metrics.json / events.jsonl / metrics.prom, the
        serving ``trace.json`` + ``serving_report.json`` pair, and
        ``slo_report.json``. Live-safe (the ``dump`` op calls this on a
        serving daemon) and called by :meth:`stop` when
        ``$ATE_TPU_METRICS_DIR`` is set. Returns the paths written."""
        from ate_replication_causalml_tpu.observability import (
            serving_report as _sreport,
        )
        from ate_replication_causalml_tpu.observability import trace as _trace

        if not obs.enabled():
            return []
        os.makedirs(outdir, exist_ok=True)
        paths = obs.write_run_artifacts(outdir)
        if obs.trace_enabled():
            # The event log is a process-global ring: keep only this
            # daemon's window (same filter run_sweep applies).
            records = [
                r for r in obs.EVENTS.records()
                if r.get("start_mono_s", 0.0) >= self._born_mono - 1e-6
            ]
            tr = _trace.build_trace(records, meta=_trace.run_meta(
                tool="serving",
                checkpoint=self.config.checkpoint,
                buckets=",".join(str(b) for b in self.config.buckets.sizes),
            ))
            paths += _sreport.write_serving_artifacts(outdir, tr)
        spath = os.path.join(outdir, _sreport.SLO_REPORT_BASENAME)
        obs.atomic_write_json(spath, self.slo.evaluate())
        paths.append(spath)
        return paths

    def stop(self, timeout: float = 10.0) -> None:
        """Drain, stop the dispatcher and the observability plane,
        export telemetry (when ``$ATE_TPU_METRICS_DIR`` is set) and
        ENFORCE the no-compile guarantee: any compile event inside the
        serving window raises (``strict_no_compile=False`` downgrades
        to an error event for diagnostics runs)."""
        self._reloader.join(timeout)
        self.coalescer.close()
        self.lifecycle.mark_stopped()
        with self._lock:
            t = self._dispatcher
            sampler = self._sampler
            admin = self._admin
            self._sampler = None
            self._admin = None
        if t is not None:
            t.join(timeout)
        if sampler is not None:
            sampler.stop()
        if admin is not None:
            admin.stop()
        leaked = self.compile_events_in_window()
        obs.gauge(
            "serving_compile_events_in_window",
            "compile events recorded during the serving window (must be 0)",
        ).set(leaked)
        outdir = os.environ.get("ATE_TPU_METRICS_DIR")
        if outdir:
            try:
                self.dump_artifacts(outdir)
            except Exception as e:
                # Telemetry export must never mask the serving outcome.
                obs.emit("serving_export_failed", status="error",
                         error=f"{type(e).__name__}: {e}")
        if leaked:
            obs.emit("serving_compile_in_window", status="error",
                     events=leaked)
            if self.config.strict_no_compile:
                raise RuntimeError(
                    f"serving window recorded {leaked:g} jax compile/trace "
                    "events — the steady state must never compile"
                )


# ── wire serving (socket / stdio) ────────────────────────────────────


def _handle_op(server: CateServer, header: dict, arrays: dict):
    """One request frame → one reply ``(header, arrays, stop?)``."""
    op = header.get("op")
    rid = str(header.get("id", ""))
    if op == "predict":
        x = arrays.get("x")
        if x is None:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "predict needs an 'x' array"}, {}, False
        try:
            cate, var = server.serve_one(rid, x)
        except RejectedRequest as rej:
            reply = {"ok": False, "id": rid, "error": rej.code,
                     "message": rej.message}
            if rej.retry_after_s is not None:
                reply["retry_after_s"] = rej.retry_after_s
            return reply, {}, False
        except Exception as e:
            # The wire contract is "always a reply": any request-scoped
            # failure — dispatch error, timeout, a validation case the
            # typed rejects missed — becomes an error frame, never a
            # dead connection (recorded; the daemon itself survives).
            obs.emit("serving_request_error", status="error",
                     request_id=rid, error=f"{type(e).__name__}: {e}")
            return {"ok": False, "id": rid, "error": "error",
                    "message": f"{type(e).__name__}: {e}"}, {}, False
        return (
            {"ok": True, "id": rid},
            {"cate": cate, "variance": var},
            False,
        )
    if op == "ping":
        return {"ok": True, "op": "ping",
                "state": server.lifecycle.state}, {}, False
    if op == "stats":
        return {"ok": True, "op": "stats", "stats": server.stats()}, {}, False
    if op == "dump":
        # Live artifact export (ISSUE 7): trace.json + serving_report
        # + slo_report + metrics triple, without stopping the daemon.
        outdir = header.get("dir") or os.environ.get("ATE_TPU_METRICS_DIR")
        if not outdir:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "dump needs a 'dir' header field or "
                               "$ATE_TPU_METRICS_DIR"}, {}, False
        try:
            paths = server.dump_artifacts(outdir)
        except Exception as e:
            obs.emit("serving_dump_failed", status="error",
                     error=f"{type(e).__name__}: {e}")
            return {"ok": False, "id": rid, "error": "error",
                    "message": f"{type(e).__name__}: {e}"}, {}, False
        return {"ok": True, "op": "dump", "paths": paths}, {}, False
    if op == "shutdown":
        return {"ok": True, "op": "shutdown"}, {}, True
    return {"ok": False, "error": "bad_request",
            "message": f"unknown op {op!r}"}, {}, False


def serve_stream(server: CateServer, rstream, wstream) -> bool:
    """Serve one connection's framed request loop. Returns True when a
    ``shutdown`` op asked the whole daemon to exit."""
    while True:
        try:
            frame = protocol.read_frame(rstream)
        except protocol.ProtocolError as e:
            # A torn/corrupt frame kills THIS connection (there is no
            # way to resynchronize a length-prefixed stream), never the
            # daemon.
            obs.emit("serving_protocol_error", status="error", error=str(e))
            return False
        if frame is None:
            return False
        header, arrays = frame
        reply, out_arrays, stop = _handle_op(server, header, arrays)
        protocol.write_frame(wstream, reply, out_arrays)
        if stop:
            return True


def serve_stdio(server: CateServer) -> None:
    """Serve a single peer over stdin/stdout (the subprocess transport;
    logs belong on stderr)."""
    import sys

    serve_stream(server, sys.stdin.buffer, sys.stdout.buffer)
    server.stop()


def serve_socket(server: CateServer, host: str = "127.0.0.1",
                 port: int = 0) -> None:
    """Accept loop: one reader thread per connection, all feeding the
    shared coalescer (this is where micro-batching pays). Returns after
    a ``shutdown`` op. Binds ``port`` (0 = ephemeral; the bound port is
    printed to stderr and exported as a gauge for discovery)."""
    import sys

    stop_evt = threading.Event()
    with socket.create_server((host, port)) as srv:
        srv.settimeout(0.25)
        bound = srv.getsockname()[1]
        obs.gauge("serving_port", "bound TCP port").set(bound)
        print(f"# serving on {host}:{bound}", file=sys.stderr, flush=True)

        def _conn(conn: socket.socket) -> None:
            with conn:
                rw = conn.makefile("rwb")
                try:
                    if serve_stream(server, rw, rw):
                        stop_evt.set()
                finally:
                    rw.close()

        threads: list[threading.Thread] = []
        conn_seq = 0
        while not stop_evt.is_set():
            # Prune finished connections each pass — a long-lived daemon
            # accepts millions of short connections and must not retain
            # one dead Thread object per connection.
            threads = [t for t in threads if t.is_alive()]
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            conn_seq += 1
            # The thread name IS the trace track: every connection gets
            # its own timeline row in the exported serving trace.
            t = threading.Thread(target=_conn, args=(conn,), daemon=True,
                                 name=f"conn-{conn_seq}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(1.0)
    server.stop()

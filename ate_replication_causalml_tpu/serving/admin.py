"""Live admin endpoint for the CATE serving daemon (ISSUE 7 — no jax).

A tiny read-only HTTP surface on a separate thread, so an operator (or
a Kubernetes probe) can look inside a running daemon without speaking
the binary serving protocol:

* ``/metrics`` — the registry in Prometheus text exposition format
  (``observability/promtext.py``), scrape-ready;
* ``/healthz`` — liveness: 200 with a JSON body (lifecycle state,
  no-compile window term, per-lane heartbeat ages, SLO burn rates)
  unless the daemon is stopped OR its dispatcher heartbeat has gone
  stale past the watchdog bound (ISSUE 14): a process whose one
  device-owning thread is wedged is NOT alive, however healthy the
  HTTP thread answering this probe feels — the pre-watchdog 200 was
  exactly the black-hole failure mode. A DEGRADED daemon with a
  beating dispatcher is alive — it is recovering — so healthz stays
  200 while the body says so;
* ``/readyz`` — readiness: 200 only while the lifecycle is SERVING.
  Degraded/starting/stopped ⇒ 503, which is how a chaos-degraded
  window becomes visible to a load balancer (the acceptance test pins
  the flip);
* ``/varz`` — the registry's cheap ``peek()`` snapshot as JSON (no
  collector hooks, so a probe never triggers a filesystem scan).

Bounded and read-only by construction: GET only (anything else gets
the stdlib's 501), fixed routes, no query parameters, responses built
from in-memory state. Off by default — the daemon starts it only when
``ATE_TPU_SERVE_ADMIN_PORT`` (or ``ServeConfig.admin_port``) is set.
The handler core is a pure function (:func:`handle_admin_path`) so the
tier-1 tests drive it over a socketpair without binding a port.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ate_replication_causalml_tpu.observability import registry as _registry

#: routes served; anything else is a 404 with this list in the body.
ROUTES = ("/metrics", "/healthz", "/readyz", "/varz")


def varz_payload(registry: _registry.MetricsRegistry | None = None) -> dict:
    """Every family's ``peek_labeled()`` view:
    ``{family: [{"labels": {...}, "value": v}, ...]}`` — self-describing
    JSON, parsed by the registry's ONE canonical label-key parser
    (``registry.parse_label_key``) instead of a hand-rolled split here.
    Cheap by contract — peek is a dict copy under the registry lock,
    never a collector scan."""
    reg = registry if registry is not None else _registry.REGISTRY
    out: dict = {}
    for m in reg.metrics():
        samples = reg.peek_labeled(m.name)
        if samples:
            out[m.name] = [
                {"labels": labels, "value": value}
                for labels, value in samples
            ]
    return out


def handle_admin_path(server, path: str) -> tuple[int, str, bytes]:
    """Resolve one GET ``path`` against the daemon — the transport-free
    core the HTTP handler (and the socketpair tests) call. ``server``
    is duck-typed: ``lifecycle.state``, ``compile_events_in_window()``
    and ``slo.health()`` are the only touchpoints, so a stub flips the
    probes without a real daemon."""
    if path == "/metrics":
        from ate_replication_causalml_tpu.observability.promtext import (
            render_prom_text,
        )

        return 200, "text/plain; version=0.0.4", render_prom_text().encode()
    if path == "/healthz":
        state = server.lifecycle.state
        # Liveness detail (ISSUE 14): per-lane heartbeat ages + the
        # watchdog's stall verdict. Duck-typed with defaults so pre-
        # watchdog stubs (and the tier-1 admin stubs) keep working.
        ages = getattr(server, "heartbeat_ages", dict)()
        stalled = tuple(getattr(server, "stalled_lanes", tuple)())
        # Statistical health (ISSUE 16) — duck-typed like the watchdog
        # fields, so the pre-stathealth stubs keep working.
        stat = getattr(server, "stat", None)
        payload = {
            "state": state,
            "compile_events_in_window": server.compile_events_in_window(),
            "heartbeats": {k: round(v, 6) for k, v in ages.items()},
            "stalled_lanes": list(stalled),
            "slo": server.slo.health(),
            "stat_health": stat.health() if stat is not None else {},
        }
        # A wedged dispatcher is a liveness failure even though the
        # process (and this probe thread) are up: the daemon cannot
        # serve and will not recover by itself — restart-worthy, which
        # is exactly what a 503 on healthz tells the orchestrator.
        alive = state != "stopped" and "dispatch" not in stalled
        code = 200 if alive else 503
        return code, "application/json", _json_bytes(payload)
    if path == "/readyz":
        state = server.lifecycle.state
        ready = state == "serving"
        # Model-binding table (ISSUE 18): the router tier builds its
        # routing view from this body alone. Duck-typed with a default
        # so the pre-fleet stubs keep working.
        models = getattr(server, "model_bindings", dict)()
        return (
            200 if ready else 503,
            "application/json",
            _json_bytes({"ready": ready, "state": state, "models": models}),
        )
    if path == "/varz":
        return 200, "application/json", _json_bytes(varz_payload())
    return (
        404,
        "application/json",
        _json_bytes({"error": "not found", "routes": list(ROUTES)}),
    )


def _json_bytes(payload: dict) -> bytes:
    return (json.dumps(payload, indent=1, sort_keys=True) + "\n").encode()


class AdminRequestHandler(BaseHTTPRequestHandler):
    """GET-only shim over :func:`handle_admin_path`. The owning
    ``ThreadingHTTPServer`` carries the daemon as ``cate_server`` (the
    socketpair tests pass any object with that attribute)."""

    server_version = "ate-serve-admin/1"
    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 — stdlib handler contract
        try:
            hb = getattr(self.server.cate_server, "heartbeats", None)
            if hb is not None:
                # The admin lane's own liveness stamp (ISSUE 14): a
                # probe that answers IS a heartbeat.
                hb.beat("admin")
            handler = getattr(
                self.server, "path_handler", handle_admin_path
            )
            code, ctype, body = handler(
                self.server.cate_server, self.path.split("?", 1)[0]
            )
        except Exception as e:  # noqa: BLE001 — a probe must answer
            # with a 500, never kill its connection thread replyless.
            code, ctype = 500, "text/plain"
            body = f"{type(e).__name__}: {e}\n".encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        # Probes arrive every few seconds forever; stderr stays quiet.
        pass


class AdminServer:
    """Owns the admin HTTP listener's lifetime beside a daemon.

    ``handler`` swaps the transport-free path resolver — the daemon
    keeps the default :func:`handle_admin_path`; the fleet router
    passes its own (``serving/router.py handle_router_admin_path``) so
    both admin planes share ONE HTTP shell (GET-only, 500-never-kill,
    silent logs) instead of two copies of it."""

    def __init__(self, cate_server, host: str = "127.0.0.1",
                 handler=handle_admin_path, thread_name: str = "serving-admin"):
        self._cate_server = cate_server
        self._host = host
        self._handler = handler
        self._thread_name = thread_name
        self._lock = threading.Lock()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self, port: int = 0) -> int:
        """Bind (0 = ephemeral) and serve on a daemon thread; returns
        the bound port. Idempotent — a second start returns the
        existing port."""
        with self._lock:
            if self._httpd is not None:
                return self._httpd.server_address[1]
            httpd = ThreadingHTTPServer(
                (self._host, int(port)), AdminRequestHandler
            )
            httpd.daemon_threads = True
            httpd.cate_server = self._cate_server
            httpd.path_handler = self._handler
            self._httpd = httpd
            t = threading.Thread(
                target=httpd.serve_forever, name=self._thread_name,
                daemon=True,
            )
            self._thread = t
        t.start()
        return httpd.server_address[1]

    @property
    def port(self) -> int | None:
        with self._lock:
            return (
                None if self._httpd is None
                else self._httpd.server_address[1]
            )

    def stop(self, timeout: float | None = 5.0) -> None:
        with self._lock:
            httpd, t = self._httpd, self._thread
            self._httpd = None
            self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if t is not None:
            t.join(timeout)

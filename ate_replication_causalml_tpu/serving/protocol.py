"""Length-prefixed wire protocol for the CATE serving daemon (ISSUE 6).

One frame carries one JSON header plus zero or more raw array buffers::

    [total_len u32][header_len u32][header JSON][array payload bytes]

All length prefixes are big-endian u32; ``total_len`` counts everything
after itself. Arrays travel as contiguous raw buffers appended after
the header in the order of the header's ``arrays`` entry
(``{name: {"dtype": ..., "shape": [...]}}``) — no pickle, so frames are
portable and a foreign peer can speak the protocol from any language
with a JSON library and ``struct``.

Torn frames are first-class: a reader that hits EOF *inside* a frame
gets :class:`ProtocolError` naming how much arrived — the artifact a
killed peer leaves — while EOF *between* frames is a clean close
(:func:`read_frame` returns None). Length fields are validated before
any allocation, so a corrupt prefix cannot make the reader balloon.

No jax anywhere in this module (or the coalescer/admission core): the
client side must be importable on hosts that will never initialize a
backend.
"""

from __future__ import annotations

import json
import math
import struct

import numpy as np

#: Refuse frames beyond this before allocating (a corrupt/hostile
#: length prefix must not look like a 4 GB allocation request). 256 MB
#: comfortably covers the largest declared batch bucket at serving
#: dtypes.
MAX_FRAME_BYTES = 256 << 20

_U32 = struct.Struct("!I")


class ProtocolError(ValueError):
    """Malformed or torn frame. A ValueError — framing bugs and torn
    streams are terminal for the connection, never retried blindly."""


def encode_frame(
    header: dict, arrays: dict[str, np.ndarray] | None = None
) -> bytes:
    """Serialize ``header`` (+ named arrays) into one wire frame."""
    meta: dict[str, dict] = {}
    payload: list[bytes] = []
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        meta[name] = {"dtype": str(a.dtype), "shape": list(a.shape)}
        payload.append(a.tobytes())
    hdr = dict(header)
    if meta:
        hdr["arrays"] = meta
    hb = json.dumps(hdr, separators=(",", ":")).encode()
    body = _U32.pack(len(hb)) + hb + b"".join(payload)
    if len(body) > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"frame of {len(body)} bytes exceeds MAX_FRAME_BYTES"
        )
    return _U32.pack(len(body)) + body


def decode_frame(body: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    """Parse a frame body (everything after the ``total_len`` prefix)
    back into ``(header, arrays)``. Every declared array must be fully
    present and the frame fully consumed — trailing or missing bytes
    are a :class:`ProtocolError`, never a silent partial decode."""
    if len(body) < _U32.size:
        raise ProtocolError("frame shorter than its header-length field")
    (hlen,) = _U32.unpack_from(body)
    off = _U32.size + hlen
    if off > len(body):
        raise ProtocolError(
            f"header length {hlen} exceeds frame body of {len(body)} bytes"
        )
    try:
        header = json.loads(body[_U32.size:off].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"frame header is not valid JSON ({e})") from e
    if not isinstance(header, dict):
        raise ProtocolError("frame header is not a JSON object")
    arrays: dict[str, np.ndarray] = {}
    for name, m in (header.pop("arrays", None) or {}).items():
        try:
            dt = np.dtype(m["dtype"])
            shape = tuple(int(s) for s in m["shape"])
        except (TypeError, KeyError, ValueError) as e:
            raise ProtocolError(
                f"array {name!r} has malformed metadata {m!r}"
            ) from e
        if dt.kind not in "biufc":
            # Object/str/datetime dtypes have no raw-buffer wire form
            # (np.frombuffer on dtype "O" raises a PLAIN ValueError that
            # would escape the protocol layer and kill the connection
            # thread replyless).
            raise ProtocolError(
                f"array {name!r} has non-numeric dtype {dt!r}"
            )
        if any(s < 0 for s in shape):
            raise ProtocolError(f"array {name!r} has negative dims {shape}")
        nbytes = dt.itemsize * math.prod(shape)
        if off + nbytes > len(body):
            raise ProtocolError(
                f"array {name!r} truncated: needs {nbytes} bytes, "
                f"{len(body) - off} left in frame"
            )
        try:
            arrays[name] = (
                np.frombuffer(body[off:off + nbytes], dtype=dt)
                .reshape(shape)
                .copy()  # own the memory; the frame buffer is transient
            )
        except ValueError as e:
            raise ProtocolError(
                f"array {name!r} does not decode as {dt!r}{shape} ({e})"
            ) from e
        off += nbytes
    if off != len(body):
        raise ProtocolError(
            f"{len(body) - off} trailing bytes after declared arrays"
        )
    return header, arrays


def _read_exact(stream, n: int, *, allow_eof: bool = False) -> bytes | None:
    """Read exactly ``n`` bytes. EOF before the first byte returns None
    when ``allow_eof`` (a clean close between frames); EOF mid-read is
    always a torn frame."""
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            if allow_eof and not buf:
                return None
            raise ProtocolError(
                f"torn frame: EOF after {len(buf)}/{n} bytes"
            )
        buf += chunk
    return buf


def read_frame(stream) -> tuple[dict, dict[str, np.ndarray]] | None:
    """Read one frame from a binary stream (``.read(n)``), or None on a
    clean EOF at a frame boundary."""
    head = _read_exact(stream, _U32.size, allow_eof=True)
    if head is None:
        return None
    (total,) = _U32.unpack(head)
    if total > MAX_FRAME_BYTES:
        raise ProtocolError(
            f"declared frame of {total} bytes exceeds MAX_FRAME_BYTES"
        )
    if total < _U32.size:
        raise ProtocolError(f"declared frame of {total} bytes is too short")
    return decode_frame(_read_exact(stream, total))


def write_frame(
    stream, header: dict, arrays: dict[str, np.ndarray] | None = None
) -> None:
    stream.write(encode_frame(header, arrays))
    stream.flush()

"""Deterministic load-replay harness for the CATE daemon (ISSUE 7).

Open-loop traffic generation with a fully seeded schedule: the arrival
process (exponential inter-arrival gaps — a Poisson process at the
offered rate), the bucket mix (weighted row-count draws) and the query
payloads are all pure functions of the seed, so the same seed replays
the *identical* request stream — ids, timing, bytes — against any
daemon. That buys two things:

* **regression comparison** — two daemon builds measured under the
  same seed saw the same offered load, so their latency records are
  comparable;
* **chaos coordination** — the ``serve:`` chaos scope selects faults by
  a pure hash of the request id, and the schedule's ids are
  deterministic (``{prefix}{index}``), so a chaos replay faults the
  same requests every run and a retrying generator converges to
  bit-identical answers.

Open-loop means requests are *submitted at their scheduled time*, not
when the previous reply lands — the arrival process never adapts to
server latency, which is what makes overload visible as queue growth
and admission rejects instead of silently throttled offered load.

The schedule/record core is jax-free and numpy-only (tier-1 unit
tests); :func:`run_inprocess` drives a live in-process
:class:`~.daemon.CateServer` (what ``bench.py --serving`` uses) and
:func:`run_wire` drives a TCP/stdio daemon through
:class:`~.client.CateClient` pools (what ``scripts/loadgen.py`` uses).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Sequence

import numpy as np

from ate_replication_causalml_tpu.resilience.deadline import Budget

#: default offered rate — fast enough to exercise coalescing at micro
#: scale without turning the bench into a sleep festival.
DEFAULT_RATE_HZ = 2000.0
DEFAULT_MIX = "1:4,8:2,32:1"


@dataclasses.dataclass(frozen=True)
class ScheduledRequest:
    """One planned request: when it is offered, under what id, with how
    many rows, against which fleet model ("" = the daemon's default)."""

    index: int
    request_id: str
    t_s: float
    rows: int
    model: str = ""


def parse_mix(spec: str) -> tuple[tuple[int, float], ...]:
    """Parse a bucket-mix spec: ``"1:4,8:2,32:1"`` (rows:weight) or
    ``"1,8,32"`` (equal weights). Weights need not normalize."""
    out: list[tuple[int, float]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        rows_s, _, weight_s = part.partition(":")
        try:
            rows = int(rows_s)
            weight = float(weight_s) if weight_s else 1.0
        except ValueError as e:
            raise ValueError(f"bad mix entry {part!r} in {spec!r}") from e
        if rows < 1 or weight <= 0:
            raise ValueError(f"bad mix entry {part!r} in {spec!r}")
        out.append((rows, weight))
    if not out:
        raise ValueError(f"empty mix spec {spec!r}")
    return tuple(out)


def build_schedule(
    seed: int,
    requests: int,
    rate_hz: float = DEFAULT_RATE_HZ,
    mix: str | Sequence[tuple[int, float]] = DEFAULT_MIX,
    id_prefix: str = "r",
    models: Sequence[str] | None = None,
) -> list[ScheduledRequest]:
    """The deterministic open-loop schedule: same seed ⇒ identical
    ``(id, t_s, rows, model)`` tuples (pinned by a tier-1 test). Draw
    order is fixed — all gaps first, then all row counts, then (only
    when ``models`` is given) the model assignment — so adding a new
    randomized field later cannot silently reshuffle existing ones,
    and a schedule built without ``models`` is bit-identical to the
    pre-fleet one."""
    if requests < 1:
        raise ValueError(f"requests must be >= 1, got {requests}")
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    entries = parse_mix(mix) if isinstance(mix, str) else tuple(mix)
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, size=requests)
    arrivals = np.cumsum(gaps)
    weights = np.asarray([w for _, w in entries], dtype=np.float64)
    rows = rng.choice(
        np.asarray([r for r, _ in entries], dtype=np.int64),
        size=requests, p=weights / weights.sum(),
    )
    if models:
        model_ids = list(models)
        picks = rng.integers(0, len(model_ids), size=requests)
        assigned = [model_ids[int(k)] for k in picks]
    else:
        assigned = [""] * requests
    return [
        ScheduledRequest(
            index=i,
            request_id=f"{id_prefix}{i}",
            t_s=float(arrivals[i]),
            rows=int(rows[i]),
            model=assigned[i],
        )
        for i in range(requests)
    ]


def build_queries(
    seed: int, schedule: Sequence[ScheduledRequest], features: int
) -> list[np.ndarray]:
    """Deterministic float32 query payloads matching the schedule's row
    counts. A separate derived seed keeps payload bytes independent of
    schedule-shape draws (changing the mix does not change row
    values row-for-row)."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x9E3779B9)))
    return [
        rng.normal(size=(s.rows, features)).astype(np.float32)
        for s in schedule
    ]


#: mid-stream distribution shifts :func:`apply_shift` knows how to
#: stage (ISSUE 16 — the statistical-health plane's drift proof).
SHIFT_KINDS = ("covariate", "checkpoint")


def apply_shift(
    schedule: Sequence[ScheduledRequest],
    queries: Sequence[np.ndarray],
    *,
    shift_at: int,
    shift_kind: str = "covariate",
    shift_model: str | None = None,
    shift_delta: float = 2.5,
) -> tuple[list[ScheduledRequest], list[np.ndarray]]:
    """Stage a deterministic mid-stream distribution shift: a pure
    post-transform of an already-built ``(schedule, queries)`` pair
    that leaves every request BEFORE ``shift_at`` byte-identical to the
    unshifted build of the same seed — which is exactly what lets a
    shifted and an unshifted replay share a prefix, so the drift
    detector's flip is attributable to the shift and nothing else
    (ISSUE 16's acceptance pair).

    ``covariate``
        adds ``shift_delta`` to feature column 0 of every query from
        ``shift_at`` on (copies; the inputs are never mutated) — moves
        the covariate-mean AND, through the propensity column, the
        propensity channel.
    ``checkpoint``
        rebinds every request from ``shift_at`` on to ``shift_model``
        (a different served model id) — the served-CATE channel of the
        TARGET model sees a different query population, the
        traffic-shape analogue of a checkpoint swap.
    """
    if shift_kind not in SHIFT_KINDS:
        raise ValueError(
            f"shift_kind must be one of {SHIFT_KINDS}, got {shift_kind!r}"
        )
    if not 0 <= shift_at <= len(schedule):
        raise ValueError(
            f"shift_at must be in [0, {len(schedule)}], got {shift_at}"
        )
    if shift_kind == "checkpoint" and not shift_model:
        raise ValueError("shift_kind='checkpoint' needs shift_model")
    out_sched = list(schedule)
    out_queries = list(queries)
    for i in range(shift_at, len(schedule)):
        if shift_kind == "covariate":
            q = out_queries[i].copy()
            q[:, 0] += np.float32(shift_delta)
            out_queries[i] = q
        else:
            out_sched[i] = dataclasses.replace(
                out_sched[i], model=shift_model
            )
    return out_sched, out_queries


def _percentiles(latencies_s: list[float]) -> dict:
    from ate_replication_causalml_tpu.observability.serving_report import (
        index_quantile,
    )

    s = sorted(latencies_s)
    return {
        "p50_s": index_quantile(s, 0.50),
        "p90_s": index_quantile(s, 0.90),
        "p99_s": index_quantile(s, 0.99),
        "max_s": s[-1],
        "mean_s": sum(s) / len(s),
    }


def _record(
    schedule: Sequence[ScheduledRequest],
    latencies_s: list[float],
    duration_s: float,
    retries: dict[str, int],
    rate_hz: float,
) -> dict:
    out = {
        "requests": len(schedule),
        "served": len(latencies_s),
        "rows_offered": int(sum(s.rows for s in schedule)),
        "offered_rate_hz": rate_hz,
        "duration_s": round(duration_s, 6),
        "achieved_rate_hz": (
            round(len(latencies_s) / duration_s, 3) if duration_s > 0 else 0.0
        ),
        "reject_retries": {k: retries[k] for k in sorted(retries)},
    }
    if any(s.model for s in schedule):
        by_model: dict[str, int] = {}
        for s in schedule:
            key = s.model or "default"
            by_model[key] = by_model.get(key, 0) + 1
        out["offered_by_model"] = {k: by_model[k] for k in sorted(by_model)}
    if latencies_s:
        out.update({
            k: round(v, 9) for k, v in _percentiles(latencies_s).items()
        })
    return out


def run_inprocess(
    server,
    schedule: Sequence[ScheduledRequest],
    queries: Sequence[np.ndarray],
    timeout_s: float = 60.0,
    max_attempts: int = 500,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    deadline_ms: float | None = None,
) -> dict:
    """Replay ``schedule`` open-loop against an in-process
    :class:`~.daemon.CateServer` via :meth:`submit` — submissions are
    paced by the schedule, never by replies. Typed retryable rejects
    (overload backpressure, chaos faults, degraded windows) are retried
    under the SAME id after the server's hint, exactly like a polite
    production client; ``bad_request`` raises (a schedule that offends
    the daemon's contract is a harness bug, not load). ``deadline_ms``
    (ISSUE 14) stamps every submission with that remaining budget;
    requests the server expires are counted into the record's
    ``deadline_expired`` (typed, pre-dispatch — never raised as
    harness failures: that rejection IS the overload contract under
    test)."""
    from ate_replication_causalml_tpu.serving.daemon import RejectedRequest

    t0 = clock()
    pending = []
    retries: dict[str, int] = {}
    expired = 0
    for sched, q in zip(schedule, queries):
        delay = t0 + sched.t_s - clock()
        if delay > 0:
            sleep(delay)
        # ONE end-to-end budget per request (the wire client's
        # discipline): every retry stamps the REMAINING budget, never a
        # fresh per-attempt deadline, so in-process and wire records
        # agree under identical load.
        req_budget = (
            None if deadline_ms is None
            else Budget.from_ms(deadline_ms, clock=clock)
        )
        for _ in range(max_attempts):
            if req_budget is not None and req_budget.expired():
                expired += 1
                break
            try:
                pending.append(
                    server.submit(sched.request_id, q,
                                  model=sched.model or None,
                                  deadline_ms=(
                                      None if req_budget is None
                                      else req_budget.remaining_ms()))
                )
                break
            except RejectedRequest as rej:
                if rej.code in ("bad_request", "unknown_model",
                                "retired_model"):
                    # Terminal: a schedule that offends the daemon's
                    # contract (or targets a gone model) is a harness
                    # bug, not load — retrying 500 times would only
                    # bury the real cause.
                    raise
                if rej.code == "deadline_exceeded":
                    # The caller's budget is gone; re-stamping a fresh
                    # one would misstate the deadline. Count and move
                    # on — the open-loop schedule never blocks on it.
                    expired += 1
                    break
                retries[rej.code] = retries.get(rej.code, 0) + 1
                sleep(rej.retry_after_s or 0.002)
        else:
            raise RuntimeError(
                f"no progress on {sched.request_id} after "
                f"{max_attempts} attempts"
            )
    latencies: list[float] = []
    for req in pending:
        if not req.wait(timeout_s):
            raise TimeoutError(f"request {req.request_id} never served")
        if req.error is not None:
            if (
                isinstance(req.error, RejectedRequest)
                and req.error.code == "deadline_exceeded"
            ):
                expired += 1
                continue
            raise req.error
        latencies.append(req.resolved_mono - req.enqueued_mono)
    duration = clock() - t0
    offered = len(schedule) / schedule[-1].t_s if schedule[-1].t_s > 0 else 0.0
    record = _record(schedule, latencies, duration, retries,
                     round(offered, 3))
    if deadline_ms is not None:
        record["deadline_ms"] = deadline_ms
        record["deadline_expired"] = expired
    return record


def run_wire(
    client_factory: Callable[[], object],
    schedule: Sequence[ScheduledRequest],
    queries: Sequence[np.ndarray],
    concurrency: int = 8,
    max_retries: int = 64,
    close_clients: bool = True,
    deadline_ms: float | None = None,
) -> dict:
    """Replay ``schedule`` against a live daemon over the wire.
    ``concurrency`` connections (one :class:`CateClient` each — the
    client is not thread-safe) pull due requests from the shared
    schedule; each blocks on its own round-trip, so pacing holds as
    long as in-flight requests stay under ``concurrency`` (reported
    offered-vs-achieved rate shows when it did not). Pass
    ``close_clients=False`` when the factory hands out a borrowed
    client the caller still needs (the stdio transport's single
    pipe)."""
    from ate_replication_causalml_tpu.serving.client import (
        ServingUnavailable,
    )

    lock = threading.Lock()
    next_idx = [0]
    latencies: list[float] = []
    errors: list[BaseException] = []
    retries: dict[str, int] = {}
    expired = [0]
    t0 = time.monotonic()

    def worker() -> None:
        client = client_factory()
        try:
            while True:
                with lock:
                    i = next_idx[0]
                    if i >= len(schedule):
                        return
                    next_idx[0] = i + 1
                sched = schedule[i]
                delay = t0 + sched.t_s - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                sent = time.monotonic()
                try:
                    client.predict(
                        queries[i], request_id=sched.request_id,
                        max_retries=max_retries,
                        model=sched.model or None,
                        deadline_ms=deadline_ms,
                    )
                except ServingUnavailable as e:
                    if e.code == "deadline_exceeded":
                        # Typed pre-dispatch expiry under a finite
                        # deadline — the contract under test, not a
                        # harness failure.
                        with lock:
                            expired[0] += 1
                        continue
                    with lock:
                        errors.append(e)
                    return
                except BaseException as e:
                    with lock:
                        errors.append(e)
                    return
                lat = time.monotonic() - sent
                with lock:
                    latencies.append(lat)
        finally:
            # Fold this connection's absorbed retryable rejects into
            # the run record — reject_retries == {} must MEAN no
            # backpressure, not "the wire path doesn't count".
            counts = getattr(client, "retry_counts", {})
            with lock:
                for code, n in counts.items():
                    retries[code] = retries.get(code, 0) + n
            if close_clients:
                try:
                    client.close()
                except OSError:
                    pass

    threads = [
        threading.Thread(target=worker, name=f"loadgen-{i}", daemon=True)
        for i in range(max(1, min(concurrency, len(schedule))))
    ]
    for t in threads:
        t.start()
    for t in threads:
        # Bounded joins (graftlint JGL012): a wedged worker must show
        # up as a visible wait loop, never an unbounded block a
        # watchdog cannot see past.
        while t.is_alive():
            t.join(1.0)
    if errors:
        raise errors[0]
    duration = time.monotonic() - t0
    offered = len(schedule) / schedule[-1].t_s if schedule[-1].t_s > 0 else 0.0
    record = _record(schedule, latencies, duration, retries,
                     round(offered, 3))
    if deadline_ms is not None:
        record["deadline_ms"] = deadline_ms
        record["deadline_expired"] = expired[0]
    return record

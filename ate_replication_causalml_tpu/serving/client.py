"""Client for the CATE serving daemon (ISSUE 6 — no jax).

Speaks the length-prefixed protocol over either transport:

* TCP — :meth:`CateClient.connect` (the production shape: many clients,
  one daemon, micro-batching across connections);
* subprocess stdio — :meth:`CateClient.spawn_stdio` (hermetic tests and
  one-shot tooling: the client owns the daemon's lifetime).

Typed rejects (``overloaded`` / ``serve_fault`` / ``degraded``) are
retried after the server's ``retry_after_s`` hint under the SAME
request id — ids are the client's idempotency key: the chaos harness
selects faults by id, so a retrying client converges deterministically
and a chaos run's final answers are bit-identical to a fault-free run.
"""

from __future__ import annotations

import itertools
import subprocess
import socket
import time

import numpy as np

from ate_replication_causalml_tpu.serving import protocol


class ServingError(RuntimeError):
    """Terminal (non-retryable) server reply; carries the wire code."""

    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class ServingUnavailable(ServingError):
    """Retry budget exhausted on retryable rejects."""

    def __init__(self, code: str, message: str, attempts: int):
        super().__init__(code, f"{message} (after {attempts} attempts)")
        self.attempts = attempts


#: Reject codes worth retrying after the server's hint.
RETRYABLE = ("overloaded", "serve_fault", "degraded", "starting")


class CateClient:
    """One connection to a serving daemon."""

    def __init__(self, rstream, wstream, *, proc=None, sock=None):
        self._r = rstream
        self._w = wstream
        self._proc = proc
        self._sock = sock
        self._seq = itertools.count(1)
        #: retryable rejects absorbed by predict(), by wire code — the
        #: backpressure this connection actually saw (loadgen folds it
        #: into its record; an operator reading reject_retries == {}
        #: must be able to trust it).
        self.retry_counts: dict[str, int] = {}

    @classmethod
    def connect(cls, host: str, port: int, timeout: float = 10.0
                ) -> "CateClient":
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(timeout)
        rw = sock.makefile("rwb")
        return cls(rw, rw, sock=sock)

    @classmethod
    def spawn_stdio(cls, argv: list[str], **popen_kw) -> "CateClient":
        """Launch ``argv`` (a ``scripts/serve.py --stdio`` command line)
        and speak the protocol over its pipes; stderr passes through."""
        proc = subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE, **popen_kw
        )
        return cls(proc.stdout, proc.stdin, proc=proc)

    def close(self) -> None:
        for stream in (self._w, self._r):
            try:
                stream.close()
            except OSError:
                pass
        if self._sock is not None:
            self._sock.close()
        if self._proc is not None:
            self._proc.wait(timeout=10)

    # ── ops ──────────────────────────────────────────────────────────

    def _roundtrip(self, header: dict, arrays=None):
        protocol.write_frame(self._w, header, arrays)
        frame = protocol.read_frame(self._r)
        if frame is None:
            raise ServingError("closed", "server closed the connection")
        return frame

    def predict(
        self,
        x: np.ndarray,
        request_id: str | None = None,
        max_retries: int = 16,
    ) -> tuple[np.ndarray, np.ndarray]:
        """``(cate, variance)`` for the rows of ``x``. Retryable rejects
        honor the server's retry-after under the same id; anything else
        raises :class:`ServingError` typed with the wire code."""
        rid = str(request_id) if request_id is not None else f"c{next(self._seq)}"
        x = np.ascontiguousarray(x, dtype=np.float32)
        for attempt in range(1, max_retries + 2):
            header, arrays = self._roundtrip(
                {"op": "predict", "id": rid}, {"x": x}
            )
            if header.get("ok"):
                return arrays["cate"], arrays["variance"]
            code = header.get("error", "error")
            if code not in RETRYABLE or attempt > max_retries:
                if code in RETRYABLE:
                    raise ServingUnavailable(
                        code, header.get("message", ""), attempt
                    )
                raise ServingError(code, header.get("message", ""))
            self.retry_counts[code] = self.retry_counts.get(code, 0) + 1
            time.sleep(float(header.get("retry_after_s", 0.05)))
        raise AssertionError("unreachable")

    def ping(self) -> dict:
        header, _ = self._roundtrip({"op": "ping"})
        return header

    def stats(self) -> dict:
        header, _ = self._roundtrip({"op": "stats"})
        if not header.get("ok"):
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return header["stats"]

    def dump(self, outdir: str | None = None) -> list[str]:
        """Ask the daemon to export its observability artifacts
        (trace.json / serving_report.json / slo_report.json + the
        metrics triple) without stopping. ``outdir`` defaults to the
        daemon's ``$ATE_TPU_METRICS_DIR``. Returns the paths written."""
        header_out: dict = {"op": "dump"}
        if outdir is not None:
            header_out["dir"] = outdir
        header, _ = self._roundtrip(header_out)
        if not header.get("ok"):
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return list(header.get("paths", ()))

    def shutdown(self) -> None:
        """Ask the daemon to exit (acknowledged before it stops)."""
        self._roundtrip({"op": "shutdown"})

    def __enter__(self) -> "CateClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

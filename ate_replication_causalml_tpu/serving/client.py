"""Client for the CATE serving daemon (ISSUE 6 — no jax).

Speaks the length-prefixed protocol over either transport:

* TCP — :meth:`CateClient.connect` (the production shape: many clients,
  one daemon, micro-batching across connections);
* subprocess stdio — :meth:`CateClient.spawn_stdio` (hermetic tests and
  one-shot tooling: the client owns the daemon's lifetime).

Typed rejects (``overloaded`` / ``serve_fault`` / ``degraded`` /
``model_degraded`` / ``shed``) are retried under the SAME request id —
ids are the client's idempotency key: the chaos harness selects faults
by id, so a retrying client converges deterministically and a chaos
run's final answers are bit-identical to a fault-free run.

Backoff honors the server's typed ``retry_after_s`` hint as the BASE of
the PR 3 discipline rather than a fixed sleep: exponential in the
attempt, deterministic crc32 jitter keyed on ``(request_id, code,
attempt)`` (retries de-herd across clients with zero nondeterminism —
tests assert the exact schedule), capped at
:data:`BACKOFF_CAP_MULT` × hint and at the absolute
:attr:`CateClient.max_backoff_s`. Every absorbed reject and every
backoff second is metered on the client (``retry_counts`` /
``backoff_s_total``) so ``reject_retries == {}`` in a loadgen record
can be trusted.
"""

from __future__ import annotations

import itertools
import subprocess
import socket
import time

import numpy as np

from ate_replication_causalml_tpu.resilience.backoff import (
    BACKOFF_CAP_MULT,
    jittered_backoff_delay,
)
from ate_replication_causalml_tpu.resilience.deadline import Budget
from ate_replication_causalml_tpu.serving import protocol

__all__ = ["BACKOFF_CAP_MULT", "CONNECTION_LOST", "CateClient",
           "ServingError", "ServingUnavailable", "retry_backoff_delay"]


def retry_backoff_delay(request_id: str, code: str, attempt: int,
                        hint_s: float, cap_s: float = 2.0) -> float:
    """Deterministic client backoff before retry ``attempt`` of a typed
    reject: ``hint_s`` grows exponentially per attempt with a crc32
    jitter in [0, 25%), capped at ``BACKOFF_CAP_MULT × hint_s`` and at
    ``cap_s`` absolute. A pure function of its arguments — the same
    retrying request sleeps the same schedule every run. One formula,
    shared with the shard runner and the retrain supervisor
    (``resilience/backoff.py``)."""
    return jittered_backoff_delay(
        f"{request_id}|{code}|{attempt}", attempt, hint_s, cap_s=cap_s
    )


class ServingError(RuntimeError):
    """Terminal (non-retryable) server reply; carries the wire code."""

    def __init__(self, code: str, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class ServingUnavailable(ServingError):
    """Retry budget exhausted on retryable rejects."""

    def __init__(self, code: str, message: str, attempts: int):
        super().__init__(code, f"{message} (after {attempts} attempts)")
        self.attempts = attempts


#: Reject codes worth retrying after the server's hint. The fleet
#: codes (ISSUE 11): ``model_degraded`` is one tenant's recovery
#: window, ``shed`` is SLO-burn backpressure — both clear; unknown or
#: retired model ids are terminal and raise. ``deadline_exceeded``
#: (ISSUE 14) is retryable ONLY while the caller still has budget —
#: the retry stamps the smaller remaining deadline and the backoff is
#: capped by it; ``draining`` is terminal on THIS connection (the
#: daemon behind it is going away; in a balanced fleet the caller's
#: next connection lands elsewhere).
RETRYABLE = ("overloaded", "serve_fault", "degraded", "starting",
             "model_degraded", "shed", "deadline_exceeded",
             "backend_unavailable")

#: wire codes that mean the TRANSPORT died, not that the server
#: rejected anything (ISSUE 18): a TCP client reconnects and resubmits
#: under the SAME request id (ids are the idempotency key — a daemon
#: failover behind a router is invisible to a well-behaved client);
#: over stdio there is nothing to reconnect to, so the loss is
#: terminal and typed.
CONNECTION_LOST = "connection_lost"


class CateClient:
    """One connection to a serving daemon."""

    def __init__(self, rstream, wstream, *, proc=None, sock=None):
        self._r = rstream
        self._w = wstream
        self._proc = proc
        self._sock = sock
        self._seq = itertools.count(1)
        #: retryable rejects absorbed by predict(), by wire code — the
        #: backpressure this connection actually saw (loadgen folds it
        #: into its record; an operator reading reject_retries == {}
        #: must be able to trust it).
        self.retry_counts: dict[str, int] = {}
        #: seconds slept in typed-reject backoff (metered, like the
        #: shard runner's backoff counter).
        self.backoff_s_total: float = 0.0
        #: absolute backoff ceiling per sleep.
        self.max_backoff_s: float = 2.0
        #: TCP origin (host, port, timeout) when built by
        #: :meth:`connect` — the reconnect target after a mid-stream
        #: connection loss (ISSUE 18). None for stdio/socketpair
        #: transports, which cannot reconnect.
        self._addr: tuple[str, int, float] | None = None

    @classmethod
    def connect(cls, host: str, port: int, timeout: float = 10.0
                ) -> "CateClient":
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(timeout)
        rw = sock.makefile("rwb")
        client = cls(rw, rw, sock=sock)
        client._addr = (host, port, timeout)
        return client

    @classmethod
    def spawn_stdio(cls, argv: list[str], **popen_kw) -> "CateClient":
        """Launch ``argv`` (a ``scripts/serve.py --stdio`` command line)
        and speak the protocol over its pipes; stderr passes through."""
        proc = subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE, **popen_kw
        )
        return cls(proc.stdout, proc.stdin, proc=proc)

    def close(self) -> None:
        for stream in (self._w, self._r):
            try:
                stream.close()
            except OSError:
                pass
        if self._sock is not None:
            self._sock.close()
        if self._proc is not None:
            self._proc.wait(timeout=10)

    # ── ops ──────────────────────────────────────────────────────────

    def _roundtrip(self, header: dict, arrays=None):
        try:
            protocol.write_frame(self._w, header, arrays)
            frame = protocol.read_frame(self._r)
        except (protocol.ProtocolError, OSError) as e:
            # The transport died mid-frame (a kill -9'd daemon's wire
            # signature) — typed, so predict() can reconnect-and-
            # resubmit and every other op surfaces a classified error.
            raise ServingError(
                CONNECTION_LOST, f"{type(e).__name__}: {e}"
            ) from e
        if frame is None:
            raise ServingError(
                CONNECTION_LOST, "server closed the connection"
            )
        return frame

    def _reconnect(self) -> None:
        """Dial a fresh TCP connection to the original :meth:`connect`
        address (ISSUE 18). The new streams swap in only on success —
        on dial failure the dead ones stay, and the next roundtrip
        surfaces ``connection_lost`` again (consuming another retry)
        instead of tripping over an already-closed file object."""
        host, port, timeout = self._addr  # type: ignore[misc]
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(timeout)
        rw = sock.makefile("rwb")
        old = (self._r, self._w, self._sock)
        self._r = self._w = rw
        self._sock = sock
        for stale in old:
            if stale is not None:
                try:
                    stale.close()
                except (OSError, ValueError):
                    pass

    def predict_full(
        self,
        x: np.ndarray,
        request_id: str | None = None,
        max_retries: int = 16,
        model: str | None = None,
        deadline_ms: float | None = None,
    ) -> tuple[np.ndarray, np.ndarray, dict]:
        """``(cate, variance, reply_header)`` for the rows of ``x`` —
        the header carries the ``model`` / ``model_version`` that
        actually served the request (the bit-identity partition key
        across a hot-swap). ``model`` routes to a fleet entry (None =
        the daemon's default model). ``deadline_ms`` (ISSUE 14) arms
        the end-to-end deadline: the client stamps its REMAINING
        budget into every attempt's header (the server checks it at
        admission, batch close and dispatch pickup), backoff sleeps
        are capped by what is left, and an exhausted budget raises
        ``ServingUnavailable("deadline_exceeded", ...)``. Retryable
        rejects back off on the server's retry-after hint with
        deterministic crc32 jitter (:func:`retry_backoff_delay`) under
        the same id; anything else raises :class:`ServingError` typed
        with the wire code."""
        rid = str(request_id) if request_id is not None else f"c{next(self._seq)}"
        x = np.ascontiguousarray(x, dtype=np.float32)
        budget = Budget.from_ms(deadline_ms) if deadline_ms is not None else None
        request: dict = {"op": "predict", "id": rid}
        if model is not None:
            request["model"] = model
        for attempt in range(1, max_retries + 2):
            if budget is not None:
                remaining = budget.remaining_ms()
                if remaining <= 0.0:
                    raise ServingUnavailable(
                        "deadline_exceeded",
                        f"client deadline of {deadline_ms}ms exhausted",
                        attempt - 1,
                    )
                request["deadline_ms"] = round(remaining, 3)
            try:
                header, arrays = self._roundtrip(request, {"x": x})
            except ServingError as e:
                if e.code != CONNECTION_LOST or self._addr is None:
                    # Non-transport errors propagate; a stdio/socketpair
                    # transport has nothing to re-dial, so its loss is
                    # terminal (but still typed).
                    raise
                if attempt > max_retries:
                    raise ServingUnavailable(
                        CONNECTION_LOST,
                        "connection lost and retry budget exhausted",
                        attempt,
                    ) from e
                # Reconnect-and-resubmit under the SAME request id: ids
                # are the idempotency key (the answer is deterministic
                # per model version), so a daemon failover behind a
                # router is invisible here — this is what makes the
                # kill -9 episode's zero-silent-drops invariant
                # achievable (ISSUE 18).
                self.retry_counts[CONNECTION_LOST] = (
                    self.retry_counts.get(CONNECTION_LOST, 0) + 1
                )
                cap_s = self.max_backoff_s
                if budget is not None:
                    cap_s = min(cap_s, max(0.0, budget.remaining_s()))
                delay = retry_backoff_delay(
                    rid, CONNECTION_LOST, attempt, 0.05, cap_s=cap_s
                )
                self.backoff_s_total += delay
                time.sleep(delay)
                try:
                    self._reconnect()
                except OSError:
                    # Dial failed — the daemon may still be restarting.
                    # The dead streams stayed in place, so the next
                    # attempt's roundtrip re-raises connection_lost and
                    # consumes another retry.
                    pass
                continue
            if header.get("ok"):
                return arrays["cate"], arrays["variance"], header
            code = header.get("error", "error")
            if code not in RETRYABLE or attempt > max_retries:
                if code in RETRYABLE:
                    raise ServingUnavailable(
                        code, header.get("message", ""), attempt
                    )
                raise ServingError(code, header.get("message", ""))
            self.retry_counts[code] = self.retry_counts.get(code, 0) + 1
            cap_s = self.max_backoff_s
            if budget is not None:
                # Never sleep past the caller's deadline: the remaining
                # budget is the backoff cap (PR 3's "an unaffordable
                # backoff cuts the work" rule, client-side).
                cap_s = min(cap_s, max(0.0, budget.remaining_s()))
            delay = retry_backoff_delay(
                rid, code, attempt,
                float(header.get("retry_after_s", 0.05)),
                cap_s=cap_s,
            )
            self.backoff_s_total += delay
            time.sleep(delay)
        raise AssertionError("unreachable")

    def predict(
        self,
        x: np.ndarray,
        request_id: str | None = None,
        max_retries: int = 16,
        model: str | None = None,
        deadline_ms: float | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """:meth:`predict_full` without the reply header."""
        cate, var, _ = self.predict_full(
            x, request_id=request_id, max_retries=max_retries, model=model,
            deadline_ms=deadline_ms,
        )
        return cate, var

    def ping(self) -> dict:
        header, _ = self._roundtrip({"op": "ping"})
        return header

    def stats(self) -> dict:
        header, _ = self._roundtrip({"op": "stats"})
        if not header.get("ok"):
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return header["stats"]

    def dump(self, outdir: str | None = None) -> list[str]:
        """Ask the daemon to export its observability artifacts
        (trace.json / serving_report.json / slo_report.json + the
        metrics triple) without stopping. ``outdir`` defaults to the
        daemon's ``$ATE_TPU_METRICS_DIR``. Returns the paths written."""
        header_out: dict = {"op": "dump"}
        if outdir is not None:
            header_out["dir"] = outdir
        header, _ = self._roundtrip(header_out)
        if not header.get("ok"):
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return list(header.get("paths", ()))

    def rotate(self, checkpoint: str, model: str | None = None) -> str:
        """Ask the daemon for a zero-downtime hot-swap of ``model``
        (None = default) onto ``checkpoint``. Returns the rotation
        status (``rotated`` / ``refused`` / ``busy`` /
        ``unknown_model``) — a refusal keeps the last good model
        serving, by contract."""
        request: dict = {"op": "rotate", "checkpoint": checkpoint}
        if model is not None:
            request["model"] = model
        header, _ = self._roundtrip(request)
        if "status" not in header:
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return str(header["status"])

    def retire(self, model: str) -> bool:
        """Retire a fleet model; returns whether the id existed."""
        header, _ = self._roundtrip({"op": "retire", "model": model})
        return bool(header.get("ok"))

    def drain(self, timeout_s: float | None = None) -> str:
        """Ask the daemon for a graceful drain (ISSUE 14): in-flight
        work completes, artifacts dump, the daemon exits. Blocks until
        the drain finishes; returns the outcome (``"drained"`` = zero
        in-flight requests dropped, ``"timeout"`` = the bound cut
        it). The reply only arrives AFTER the drain, so the socket's
        regular 10 s read timeout is widened to cover the drain bound
        (the server default is 30 s) for this one round-trip."""
        request: dict = {"op": "drain"}
        if timeout_s is not None:
            request["timeout_s"] = float(timeout_s)
        wait_s = (30.0 if timeout_s is None else float(timeout_s)) + 30.0
        prev = None
        if self._sock is not None:
            prev = self._sock.gettimeout()
            if prev is not None and prev < wait_s:
                self._sock.settimeout(wait_s)
        try:
            header, _ = self._roundtrip(request)
        finally:
            if self._sock is not None and prev is not None:
                try:
                    self._sock.settimeout(prev)
                except OSError:
                    pass  # the daemon closed the connection behind us
        if "outcome" not in header:
            raise ServingError(header.get("error", "error"),
                               header.get("message", ""))
        return str(header["outcome"])

    def shutdown(self) -> None:
        """Ask the daemon to exit (acknowledged before it stops)."""
        self._roundtrip({"op": "shutdown"})

    def __enter__(self) -> "CateClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Model-fleet state for the CATE serving daemon (ISSUE 11 — no jax).

The daemon stopped serving ONE frozen checkpoint: production traffic
means many models (per-tenant / per-experiment forests), periodic refit
on fresh data, and rotation without dropping requests. This module is
the jax-free state layer the daemon composes:

* :class:`ModelFleet` — the lock-guarded registry of served models.
  Each :class:`ModelEntry` carries the forest reference (opaque — this
  module never imports jax), a monotonically increasing **version**,
  the geometry signature its AOT executables were compiled against,
  and its own lifecycle. A hot-swap (:meth:`ModelFleet.swap`) replaces
  the forest reference and bumps the version under ONE lock
  acquisition, so a dispatcher that reads a binding sees either the
  old (forest, version) pair or the new one — never a half-swapped
  mix, and in-flight batches keep the reference they already hold.
* :class:`ModelLifecycle` — per-model ``serving ⇄ degraded → retired``
  state, the small sibling of the daemon-wide
  :class:`~.admission.ServingLifecycle`. One tenant's degradation
  gates ONLY that tenant's requests; the daemon's global ``readyz``
  never flips for a per-model fault. The interface matches what
  :class:`~.admission.ReloadSupervisor` needs (``mark_fault`` /
  ``mark_recovered`` / ``state``), so each entry owns its own
  single-flight reload/rotation supervisor.
* :class:`BurnShedder` — SLO-burn-driven admission. Shedding decisions
  move from one global queue depth to per-model multi-window burn
  rates: a model sheds (typed ``shed`` reject with retry-after) while
  its two fastest SLO windows BOTH burn above the threshold — the
  multi-window confirmation shape from the SRE workbook, so a single
  bad batch cannot flap admission. Shed rejects are recorded under
  their own status and EXCLUDED from the driving SLO's totals
  (``ignore_match``), so shedding cannot feed back into the burn rate
  that caused it and latch permanently.
* :func:`parse_fleet_spec` — the ``ATE_TPU_SERVE_FLEET`` grammar
  (``"tenantA=/path/a.npz,tenantB=/path/b.npz"``).

Same-shape models share AOT executables: the daemon keys its compiled
predict table by (geometry signature, bucket), and
``lower_predict_cate`` takes the forest as a *runtime* argument — so a
ten-tenant fleet of same-shape GRF instances costs exactly one
executable set, and rotating any of them compiles nothing.
"""

from __future__ import annotations

import threading

from ate_replication_causalml_tpu.observability import events as _events

#: Per-model lifecycle states.
MODEL_SERVING = "serving"
MODEL_DEGRADED = "degraded"
MODEL_RETIRED = "retired"


def parse_fleet_spec(spec: str) -> tuple[tuple[str, str], ...]:
    """Parse ``ATE_TPU_SERVE_FLEET``: comma-separated ``id=path`` pairs.
    Ids must be unique and non-empty; a malformed spec raises at config
    time, never silently serves a partial fleet."""
    out: list[tuple[str, str]] = []
    seen: set[str] = set()
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        model_id, eq, path = item.partition("=")
        model_id = model_id.strip()
        path = path.strip()
        if not eq or not model_id or not path:
            raise ValueError(
                f"bad fleet entry {item!r} (want id=path) in {spec!r}"
            )
        if model_id in seen:
            raise ValueError(f"duplicate fleet model id {model_id!r} in {spec!r}")
        seen.add(model_id)
        out.append((model_id, path))
    return tuple(out)


class ModelLifecycle:
    """Per-model ``serving ⇄ degraded → retired`` state machine.

    Starts SERVING (a model only enters the fleet after its checkpoint
    verified and installed). Implements the lifecycle protocol
    :class:`~.admission.ReloadSupervisor` drives — ``mark_fault``
    returns True to exactly one caller (the owner of recovery),
    ``mark_recovered`` flips back — plus a terminal ``retire``. Every
    transition is a ``serving_model_state`` event labeled by model."""

    def __init__(self, model_id: str):
        self.model_id = model_id
        self._lock = threading.Lock()
        self._state = MODEL_SERVING
        self._fault_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def can_serve(self) -> bool:
        return self.state == MODEL_SERVING

    def mark_fault(self, reason: str) -> bool:
        """Report a model-scoped fault. True to the one caller that
        moved SERVING → DEGRADED (it owns recovery); concurrent
        reporters, and reports on degraded/retired models, get False."""
        with self._lock:
            self._fault_count += 1
            if self._state != MODEL_SERVING:
                return False
            self._state = MODEL_DEGRADED
        _events.emit("serving_model_state", status="error",
                     model=self.model_id, frm=MODEL_SERVING,
                     to=MODEL_DEGRADED, reason=reason)
        return True

    def mark_recovered(self) -> None:
        with self._lock:
            if self._state == MODEL_RETIRED:
                # Retirement is terminal and wins races: a background
                # reload that completes AFTER the operator retired the
                # model must not resurrect it (and must not die on an
                # uncaught error in the reload thread either).
                return
            if self._state != MODEL_DEGRADED:
                raise RuntimeError(
                    f"model {self.model_id!r} cannot recover from "
                    f"{self._state!r}"
                )
            self._state = MODEL_SERVING
        _events.emit("serving_model_state", status="ok",
                     model=self.model_id, frm=MODEL_DEGRADED,
                     to=MODEL_SERVING)

    def retire(self) -> None:
        """Terminal: the model id keeps answering — with a typed
        ``retired_model`` reject — instead of vanishing into
        ``unknown_model`` (a retired tenant is a fact, not a typo)."""
        with self._lock:
            if self._state == MODEL_RETIRED:
                return
            frm, self._state = self._state, MODEL_RETIRED
        _events.emit("serving_model_state", status="ok",
                     model=self.model_id, frm=frm, to=MODEL_RETIRED)

    @property
    def fault_count(self) -> int:
        with self._lock:
            return self._fault_count


class ModelEntry:
    """One served model: the forest reference and its metadata. The
    forest/version/checkpoint/leaf_index fields are mutated only
    through :class:`ModelFleet` under the fleet lock; ``lifecycle`` and
    ``supervisor`` have their own internal locking.

    ``leaf_index`` (ISSUE 12): the pre-built (T, n) training-matrix
    routing cache for a FITTED checkpoint — built sharded over the mesh
    BEFORE the swap instant (``compute_leaf_index_sharded``), so a
    rotation never pays the serial build on its first in-sample
    rescore. None for bare-forest checkpoints; ALWAYS overwritten by a
    swap (a stale index against a new forest would be silently
    wrong)."""

    __slots__ = ("model_id", "forest", "version", "sig", "n_features",
                 "checkpoint", "lifecycle", "supervisor", "leaf_index")

    def __init__(self, model_id: str, forest, sig, n_features: int,
                 checkpoint: str, leaf_index=None):
        self.model_id = model_id
        self.forest = forest
        self.version = 1
        self.sig = sig
        self.n_features = int(n_features)
        self.checkpoint = checkpoint
        self.leaf_index = leaf_index
        self.lifecycle = ModelLifecycle(model_id)
        self.supervisor = None  # wired by the daemon after install


class ModelFleet:
    """Lock-guarded model registry; the daemon's routing table."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, ModelEntry] = {}

    def install(self, model_id: str, forest, sig, n_features: int,
                checkpoint: str, leaf_index=None) -> ModelEntry:
        """Register a verified model at version 1 (startup only)."""
        entry = ModelEntry(model_id, forest, sig, n_features, checkpoint,
                           leaf_index)
        with self._lock:
            if model_id in self._entries:
                raise ValueError(f"model {model_id!r} already installed")
            self._entries[model_id] = entry
        return entry

    def get(self, model_id: str) -> ModelEntry | None:
        with self._lock:
            return self._entries.get(model_id)

    def ids(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(self._entries)

    def binding(self, model_id: str):
        """Consistent ``(forest, version)`` read — the pair a dispatch
        binds. One lock acquisition, so a concurrent swap yields either
        the old pair or the new one, never a mix."""
        with self._lock:
            entry = self._entries[model_id]
            return entry.forest, entry.version

    def reinstall(self, model_id: str, forest) -> None:
        """Degraded-recovery install: replace the forest reference with
        the re-verified LAST GOOD bytes. The version does NOT advance —
        a recovery is not a rotation, and bit-identity across it is the
        point."""
        with self._lock:
            self._entries[model_id].forest = forest

    def swap(self, model_id: str, forest, checkpoint: str,
             leaf_index=None) -> int:
        """The hot-swap instant: replace the forest reference, bump the
        version, record the new last-good checkpoint and the candidate's
        PRE-BUILT leaf index (None clears a stale one — ISSUE 12: an old
        index against the new forest would be silently wrong). Returns
        the new version. In-flight batches keep the reference they
        already bound; new dispatches see the new pair."""
        with self._lock:
            entry = self._entries[model_id]
            entry.forest = forest
            entry.version += 1
            entry.checkpoint = checkpoint
            entry.leaf_index = leaf_index
            return entry.version

    def describe(self) -> dict:
        """The ``stats`` op's fleet section. Entry fields are read
        UNDER the fleet lock — a snapshot racing a swap() must never
        show the new version paired with the old checkpoint path."""
        with self._lock:
            out = {
                e.model_id: {
                    "version": e.version,
                    "checkpoint": e.checkpoint,
                    "n_features": e.n_features,
                    "leaf_index_rows": (
                        None if e.leaf_index is None
                        else int(e.leaf_index.shape[1])
                    ),
                }
                for e in self._entries.values()
            }
            entries = list(self._entries.values())
        for e in entries:  # lifecycle has its own lock
            out[e.model_id]["state"] = e.lifecycle.state
            out[e.model_id]["faults"] = e.lifecycle.fault_count
        return out


class BurnShedder:
    """Per-model admission shedding driven by SLO burn rates.

    Reads per-model availability SLOs (named ``fleet:<model>``, built
    by :func:`~..observability.slo.fleet_slos`) out of one
    :class:`~..observability.slo.SLOEngine` report. A model sheds when
    its two fastest windows BOTH burn above ``threshold`` — fast-window
    detection with slow-window confirmation, so one bad batch in an
    otherwise healthy minute cannot flap admission. ``threshold <= 0``
    disables shedding entirely.

    The request path reads ONLY the cached dict — never a full engine
    evaluation (one stale-cache burst would otherwise thunder-herd N
    concurrent connection readers into N simultaneous engine scans on
    the admission hot path). :meth:`update` is the single refresher:
    the daemon calls it from the dispatcher after each batch (so the
    cache is at most one batch stale — exactly as fresh as the SLO
    data feeding it), tests call it directly."""

    SLO_PREFIX = "fleet:"

    def __init__(self, engine, threshold: float):
        self._engine = engine
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        self._burns: dict[str, float] = {}

    def _confirmed_burn(self, slo_report: dict) -> float:
        """The shedding figure for one SLO: the *minimum* of the two
        fastest windows' burn rates (both must exceed the threshold for
        the min to)."""
        windows = slo_report.get("windows", [])[:2]
        if not windows:
            return 0.0
        return min(w.get("burn_rate", 0.0) for w in windows)

    def update(self) -> dict[str, float]:
        """Evaluate the engine now and cache per-model confirmed burn
        rates; returns the fresh map. The ONLY evaluation site —
        called from the dispatcher per batch, never the request
        path."""
        if self.threshold <= 0.0:
            return {}
        report = self._engine.evaluate()
        burns = {
            s["name"][len(self.SLO_PREFIX):]: self._confirmed_burn(s)
            for s in report.get("slos", [])
            if str(s.get("name", "")).startswith(self.SLO_PREFIX)
        }
        with self._lock:
            self._burns = burns
        return dict(burns)

    def burns(self) -> dict[str, float]:
        with self._lock:
            return dict(self._burns)

    def should_shed(self, model_id: str) -> bool:
        """Pure cache read — O(dict lookup) on the admission path."""
        if self.threshold <= 0.0:
            return False
        with self._lock:
            return self._burns.get(model_id, 0.0) > self.threshold

"""Horizontal serving tier: a health-routed router over N daemons
(ISSUE 18, tentpole).

One :class:`CateServer` process serving millions of users is a fiction
no matter how fast the predict path gets (ROADMAP item 2). This module
is the scale-out half: a **jax-free, stdlib-only** router process that
fronts N daemons over the existing length-prefixed wire protocol
(``serving/protocol.py``) and makes the fleet look like one daemon to
every existing client:

* **Consistent-hash routing** — requests route on a deterministic
  sha256 ring keyed by *model id* (:class:`ConsistentHashRing`), so a
  model's traffic concentrates on one daemon and that daemon's
  geometry-keyed AOT executables stay warm; membership change moves
  only the keys the changed node owned (minimal movement, unit-proven
  in ``tests/test_router.py``). The ring is pure and immutable —
  eviction never rebuilds it, it just walks to the next live owner, so
  a daemon's keys come straight back when it readmits.
* **Probe-driven rotation membership** — eviction and readmission
  decisions come purely from the daemons' existing admin probes:
  ``/readyz`` (readiness + the model/version bindings the daemon
  serves) and the liveness ``/healthz`` (a wedged dispatcher is dead
  however healthy its HTTP thread feels — ISSUE 14). No config push: a
  degraded daemon drops out of rotation at the next probe tick and
  rejoins the same way.
* **Circuit breaking + bounded failover** — per-backend
  :class:`CircuitBreaker` (closed → open after N consecutive
  connection failures, half-open trial after a cooldown); a forward
  that dies mid-stream retries against the next distinct ring owner at
  most ``failover_hops`` times (metered ``router_failover_total``),
  and an exhausted candidate list is a typed ``backend_unavailable``
  reject with a retry-after hint — never a dead client connection.
* **Fleet-wide rolling rotation** — :class:`FleetSupervisor.rotate_all`
  drains one daemon at a time through the PR 14 discipline applied at
  the router (cordon = administrative out-of-rotation; in-flight
  forwards complete), rotates it from the one published checkpoint
  path, waits for the probe to confirm the advanced version, and
  readmits before touching the next — asserting at every step that at
  least one backend stays in rotation (zero downtime is a checked
  number, not a vibe).
* **Merged fleet dump** — :meth:`RouterServer.dump_fleet` exports every
  live daemon's artifact set into ``outdir/daemon-<name>/`` plus a
  ``fleet_manifest.json`` carrying the router's own counters, so
  ``scripts/check_metrics_schema.py`` can reconcile per-daemon reports
  against the router's totals.

Everything here must stay importable (and runnable —
``scripts/router.py``) on hosts that will never initialize a backend:
stdlib + the protocol module only, no numpy arrays ever materialized
(frames forward as decoded dicts/arrays from the protocol layer, which
the router treats as opaque).

Observed counter families (pre-created by ``install_jax_monitoring``
so "the router never ran" is a recorded 0):

* ``router_requests_total{backend,outcome}`` — one bump per forward
  attempt outcome (``ok`` / ``reject`` / ``error`` /
  ``connection_error``) plus ``backend="-",outcome="unavailable"``
  for requests no candidate could take;
* ``router_failover_total`` — forwards retried against the next ring
  owner after a connection-level failure;
* ``router_backend_state{backend,state}`` — rotation-membership
  transitions (``admitted`` / ``evicted`` / ``cordoned`` /
  ``uncordoned``), so a flapping daemon is visible as a counter slope;
* ``router_request_path_total{path}`` — one bump per *request* (not
  attempt): ``direct`` / ``failover`` / ``exhausted``;
* ``router_request_seconds{outcome}`` — bucket histogram of the
  router-observed end-to-end forward latency, feeding the
  ``router:latency`` SLO.

**Request telemetry (PR 20).** Every forward is a ``router_request``
span carrying backend, failover hop count, outcome and a four-phase
latency split — ``connect_s`` (candidate scan + connection acquire),
``send_s`` (request frame on the wire), ``wait_s`` (backend think
time until the first reply byte) and ``reply_s`` (reply read +
bookkeeping). The phases are contiguous ``perf_counter`` intervals
accumulated across failover hops, so their sum telescopes to the
span's ``e2e_s`` within float rounding (the PR 7 ±1 µs discipline —
a checked number, pinned by a tier-1 test). Probe ticks, breaker
flips and rotation-membership transitions are instants on dedicated
``router-probe`` / ``router-breaker`` / ``router-backend`` tracks.
Router SLOs (``observability/slo.py router_slos``) burn from the
request counters; :func:`handle_router_admin_path` serves them on a
GET-only admin plane (``/metrics`` ``/healthz`` ``/readyz``
``/fleetz``) through the SAME HTTP shell the daemon admin uses
(``serving/admin.py AdminServer(handler=...)``). ``dump_fleet`` also
exports the router's own trace + SLO report into ``outdir/router/``
and stitches the merged fleet artifacts
(``observability/fleet_report.py``).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import http.client
import json
import os
import socket
import threading
import time
from typing import Callable, Sequence

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability import slo as _slo
from ate_replication_causalml_tpu.serving import protocol

__all__ = [
    "BackendSpec",
    "CircuitBreaker",
    "ConsistentHashRing",
    "FleetSupervisor",
    "ROUTER_ADMIN_ROUTES",
    "RouterConfig",
    "RouterServer",
    "handle_router_admin_path",
    "parse_backend_specs",
]

#: vnodes per backend — enough that a 3..8-node ring balances within
#: the bound the tier-1 test pins, few enough that ring build is free.
DEFAULT_VNODES = 64

#: the reject code a request gets when no in-rotation backend could
#: take it — typed and retryable, the fleet's analogue of
#: ``overloaded``.
BACKEND_UNAVAILABLE = "backend_unavailable"

#: forward-attempt outcomes router_requests_total is labeled with.
OUTCOMES = ("ok", "reject", "error", "connection_error", "unavailable")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number") from None
    if value <= 0:
        raise ValueError(f"{name}={value}: expected > 0")
    return value


def _env_int(name: str, default: int, minimum: int = 1) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer") from None
    if value < minimum:
        raise ValueError(f"{name}={value}: expected >= {minimum}")
    return value


@dataclasses.dataclass(frozen=True)
class BackendSpec:
    """One daemon the router fronts: wire address + admin-probe port."""

    name: str
    host: str
    port: int
    admin_port: int


def parse_backend_specs(spec: str) -> tuple[BackendSpec, ...]:
    """Parse ``name=host:port@adminport,...`` (config-time raise on any
    malformed entry — the repo-wide env/flag discipline)."""
    out: list[BackendSpec] = []
    seen: set[str] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, addr = part.partition("=")
        name = name.strip()
        hostport, at, admin_s = addr.partition("@")
        host, colon, port_s = hostport.rpartition(":")
        if not (eq and at and colon and name and host):
            raise ValueError(
                f"bad backend entry {part!r} in {spec!r} "
                "(want name=host:port@adminport)"
            )
        if name in seen:
            raise ValueError(f"duplicate backend name {name!r} in {spec!r}")
        try:
            port, admin_port = int(port_s), int(admin_s)
        except ValueError:
            raise ValueError(
                f"bad backend ports in {part!r} (want integers)"
            ) from None
        if not (0 < port < 65536 and 0 < admin_port < 65536):
            raise ValueError(f"backend ports out of range in {part!r}")
        seen.add(name)
        out.append(BackendSpec(name, host.strip(), port, admin_port))
    if not out:
        raise ValueError(f"empty backend spec {spec!r}")
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router knobs; :meth:`from_env` reads the ``ATE_TPU_ROUTER_*``
    family with config-time validation."""

    backends: tuple[BackendSpec, ...]
    vnodes: int = DEFAULT_VNODES
    probe_interval_s: float = 0.25
    probe_timeout_s: float = 2.0
    connect_timeout_s: float = 5.0
    io_timeout_s: float = 30.0
    failure_threshold: int = 3
    cooldown_s: float = 1.0
    failover_hops: int = 2
    retry_after_s: float = 0.05

    @classmethod
    def from_env(cls, backends: "str | tuple[BackendSpec, ...]",
                 **overrides) -> "RouterConfig":
        if isinstance(backends, str):
            backends = parse_backend_specs(backends)
        kw: dict = {
            "vnodes": _env_int("ATE_TPU_ROUTER_VNODES", DEFAULT_VNODES),
            "probe_interval_s": _env_float("ATE_TPU_ROUTER_PROBE_S", 0.25),
            "failure_threshold": _env_int("ATE_TPU_ROUTER_FAILURES", 3),
            "cooldown_s": _env_float("ATE_TPU_ROUTER_COOLDOWN_S", 1.0),
            "failover_hops": _env_int("ATE_TPU_ROUTER_FAILOVER", 2,
                                      minimum=0),
            "retry_after_s": _env_float("ATE_TPU_ROUTER_RETRY_AFTER_S",
                                        0.05),
        }
        kw.update(overrides)
        return cls(backends=tuple(backends), **kw)


# ── the consistent-hash ring (pure) ──────────────────────────────────


def _ring_pos(token: str) -> int:
    """A vnode/key position: the first 8 bytes of sha256 as an int —
    stable across processes, platforms and Python hash randomization."""
    return int.from_bytes(
        hashlib.sha256(token.encode()).digest()[:8], "big"
    )


class ConsistentHashRing:
    """Deterministic consistent-hash ring over backend names.

    Pure and immutable: positions are sha256 of ``{salt}{name}#{i}``
    for ``vnodes`` virtual nodes per backend, a key routes to the
    first vnode clockwise of ``sha256(key)``. Two properties the
    tier-1 tests pin:

    * **determinism** — the same members produce the identical
      assignment in every process (no seed, no insertion order);
    * **minimal movement** — :meth:`with_backend` /
      :meth:`without_backend` move only keys the changed backend owns
      (true by construction: every other vnode keeps its position).
    """

    def __init__(self, backends: Sequence[str],
                 vnodes: int = DEFAULT_VNODES, salt: str = ""):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        names = tuple(sorted(set(backends)))
        if len(names) != len(tuple(backends)):
            raise ValueError(f"duplicate backend names in {backends!r}")
        if not names:
            raise ValueError("a ring needs at least one backend")
        self.backends = names
        self.vnodes = int(vnodes)
        self.salt = salt
        points: list[tuple[int, str]] = []
        for name in names:
            for i in range(self.vnodes):
                points.append((_ring_pos(f"{salt}{name}#{i}"), name))
        points.sort()
        self._points = points
        self._positions = [p for p, _ in points]

    def owner(self, key: str) -> str:
        """The backend owning ``key`` — first vnode clockwise."""
        return self.owners(key, 1)[0]

    def owners(self, key: str, n: int | None = None) -> tuple[str, ...]:
        """The first ``n`` DISTINCT backends clockwise of ``key`` (all
        of them by default) — the failover candidate order: owner
        first, then each next-nearest distinct backend."""
        want = len(self.backends) if n is None else min(n, len(self.backends))
        start = bisect.bisect_right(self._positions, _ring_pos(key))
        out: list[str] = []
        seen: set[str] = set()
        for i in range(len(self._points)):
            _, name = self._points[(start + i) % len(self._points)]
            if name not in seen:
                seen.add(name)
                out.append(name)
                if len(out) == want:
                    break
        return tuple(out)

    def assignment(self, keys: Sequence[str]) -> dict[str, str]:
        return {k: self.owner(k) for k in keys}

    def with_backend(self, name: str) -> "ConsistentHashRing":
        return ConsistentHashRing(
            (*self.backends, name), self.vnodes, self.salt
        )

    def without_backend(self, name: str) -> "ConsistentHashRing":
        rest = tuple(b for b in self.backends if b != name)
        return ConsistentHashRing(rest, self.vnodes, self.salt)


# ── per-backend circuit breaker ──────────────────────────────────────


class CircuitBreaker:
    """Closed → open after ``threshold`` consecutive connection-level
    failures; after ``cooldown_s`` one half-open trial is allowed —
    its success closes the breaker, its failure re-opens (and re-arms
    the cooldown). The clock is injectable so the state machine is
    provable without wall sleeping."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self._threshold = threshold
        self._cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._trial_out = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            return "half_open" if self._trial_out else "open"

    def allow(self) -> bool:
        """May a forward attempt go to this backend right now? An open
        breaker releases exactly one trial per cooldown window."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._trial_out:
                return False
            if self._clock() - self._opened_at >= self._cooldown_s:
                self._trial_out = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._trial_out = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._trial_out or self._failures >= self._threshold:
                self._opened_at = self._clock()
                self._trial_out = False


# ── health probing (admin-plane HTTP) ────────────────────────────────


def probe_backend(spec: BackendSpec, timeout_s: float = 2.0
                  ) -> tuple[bool, bool, dict]:
    """One probe round against a daemon's admin plane: ``(ready,
    alive, models)``. ``ready`` is ``/readyz`` 200, ``alive`` is
    ``/healthz`` 200 (the ISSUE 14 liveness — a wedged dispatcher
    503s here however warm the HTTP thread is), ``models`` is the
    readyz body's ``{model_id: {"version": ..., "checkpoint": ...}}``
    binding table (ISSUE 18 satellite: the router builds its routing
    table from probes alone, never from static config). Any transport
    failure is simply ``(False, False, {})`` — an unreachable daemon
    is out of rotation, not an error."""
    try:
        conn = http.client.HTTPConnection(
            spec.host, spec.admin_port, timeout=timeout_s
        )
        try:
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            body = resp.read()
            ready = resp.status == 200
            models: dict = {}
            try:
                models = dict(json.loads(body).get("models") or {})
            except (json.JSONDecodeError, UnicodeDecodeError, AttributeError):
                models = {}
            conn.request("GET", "/healthz")
            live = conn.getresponse()
            live.read()
            alive = live.status == 200
        finally:
            conn.close()
        return ready, alive, models
    except OSError:
        return False, False, {}


class HealthProber:
    """One daemon thread polling every backend's admin plane at a
    fixed interval and feeding :meth:`RouterServer.update_health`.
    Stop is bounded (JGL012): the loop wakes on an event, the join is
    a visible timed wait."""

    def __init__(self, router: "RouterServer", interval_s: float,
                 timeout_s: float = 2.0):
        self._router = router
        self._interval_s = float(interval_s)
        self._timeout_s = float(timeout_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def probe_once(self) -> None:
        ready_n = 0
        for spec in self._router.config.backends:
            ready, alive, models = probe_backend(spec, self._timeout_s)
            ready_n += 1 if (ready and alive) else 0
            self._router.update_health(spec.name, ready, alive, models)
        # One instant per probe round on its dedicated track (bounded
        # volume — per-backend outcomes are already counter slopes),
        # and an SLO clock tick so burn windows advance while idle.
        obs.emit("router_probe", status="ok", track="router-probe",
                 backends=len(self._router.config.backends),
                 ready=ready_n)
        self._router.slo.tick()

    def start(self) -> None:
        if self._thread is not None:
            return
        t = threading.Thread(target=self._run, name="router-prober",
                             daemon=True)
        self._thread = t
        t.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self._interval_s)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout)


# ── the router ───────────────────────────────────────────────────────


class _BackendConn:
    """One pooled wire connection to a backend."""

    def __init__(self, spec: BackendSpec, connect_timeout_s: float,
                 io_timeout_s: float):
        self.sock = socket.create_connection(
            (spec.host, spec.port), timeout=connect_timeout_s
        )
        self.sock.settimeout(io_timeout_s)
        self.rw = self.sock.makefile("rwb")

    def send(self, header: dict, arrays: dict) -> None:
        """Put one request frame on the wire (the ``send`` phase)."""
        protocol.write_frame(self.rw, header, arrays)

    def wait_reply(self) -> None:
        """Block until the first reply byte is buffered (the ``wait``
        phase — backend think time). ``peek`` never consumes, so the
        subsequent :meth:`read_reply` sees the full frame; EOF here is
        silent and surfaces as the read's ProtocolError."""
        self.rw.peek(1)

    def read_reply(self):
        """Read the buffered reply frame (the ``reply`` phase); a clean
        server close mid-request surfaces as
        :class:`protocol.ProtocolError` (the caller treats every
        transport failure identically)."""
        frame = protocol.read_frame(self.rw)
        if frame is None:
            raise protocol.ProtocolError(
                "backend closed the connection before replying"
            )
        return frame

    def roundtrip(self, header: dict, arrays: dict):
        """Forward one frame and read the reply — the un-phased
        convenience the direct (non-routed) channel uses."""
        self.send(header, arrays)
        self.wait_reply()
        return self.read_reply()

    def close(self) -> None:
        for closer in (self.rw.close, self.sock.close):
            try:
                closer()
            except OSError:
                pass


class _Backend:
    """Router-side record for one daemon: probe state, cordon flag,
    breaker, connection pool and in-flight count. Mutable fields are
    guarded by the owning router's lock; the breaker locks itself."""

    def __init__(self, spec: BackendSpec, breaker: CircuitBreaker):
        self.spec = spec
        self.breaker = breaker
        self.ready = False
        self.alive = False
        self.models: dict = {}
        self.cordoned = False
        self.in_flight = 0
        self.pool: list[_BackendConn] = []

    def in_rotation(self) -> bool:
        return self.ready and self.alive and not self.cordoned


class RouterServer:
    """The daemon-fronting router: accepts client connections on the
    same wire protocol the daemons speak and forwards ``predict`` by
    consistent-hash model routing; everything else it answers itself
    (``ping`` / ``stats`` / ``dump`` / ``rotate_all`` / ``shutdown``).
    jax-free by contract — this process must run on a host with no
    accelerator stack."""

    def __init__(self, config: RouterConfig,
                 clock: Callable[[], float] = time.monotonic):
        if not config.backends:
            raise ValueError("router needs at least one backend")
        self.config = config
        self._clock = clock
        self._lock = threading.Lock()
        self._backends = {
            spec.name: _Backend(spec, CircuitBreaker(
                config.failure_threshold, config.cooldown_s, clock
            ))
            for spec in config.backends
        }
        self.ring = ConsistentHashRing(
            tuple(self._backends), config.vnodes
        )
        self.prober = HealthProber(
            self, config.probe_interval_s, config.probe_timeout_s
        )
        self._stopped = False
        # Trace filter for dump_fleet: only records born after this
        # router exist in ITS dump (the event ring is process-global
        # and in-process tests run several routers per process).
        self._born_mono = time.monotonic()
        self._requests = obs.counter(
            "router_requests_total",
            "router forward attempts by backend and outcome",
        )
        self._failovers = obs.counter(
            "router_failover_total",
            "forwards retried against the next ring owner",
        )
        self._transitions = obs.counter(
            "router_backend_state",
            "backend rotation-membership transitions",
        )
        self._paths = obs.counter(
            "router_request_path_total",
            "router forwards by direct/failover/exhausted path",
        )
        self._latency = obs.bucket_histogram(
            "router_request_seconds",
            "router-observed forward latency (e2e)",
        )
        # Born-relative counter baselines, same motive as _born_mono:
        # the registry is process-global, so the totals this router
        # PUBLISHES (stats, manifest) must subtract whatever earlier
        # routers in the process already metered — the campaign runs a
        # reference episode before the chaos one, and its manifest
        # must not inherit the reference's traffic.
        self._req_baseline = dict(
            obs.REGISTRY.peek("router_requests_total") or {}
        )
        self._fo_baseline = dict(
            obs.REGISTRY.peek("router_failover_total") or {}
        )
        #: last-published breaker state per backend; flips become
        #: instants on the dedicated ``router-breaker`` track.
        self._breaker_seen = {name: "closed" for name in self._backends}
        self.slo = _slo.SLOEngine(_slo.router_slos(), clock=clock)

    # ── membership ───────────────────────────────────────────────────

    def start(self, probe: bool = True) -> None:
        """Run one synchronous probe round (so the routing table is
        populated before the first request), then start the prober."""
        self.prober.probe_once()
        if probe:
            self.prober.start()

    def update_health(self, name: str, ready: bool, alive: bool,
                      models: dict) -> None:
        with self._lock:
            b = self._backends[name]
            was = b.in_rotation()
            b.ready, b.alive = bool(ready), bool(alive)
            b.models = dict(models)
            now = b.in_rotation()
        if was != now:
            state = "admitted" if now else "evicted"
            self._transitions.inc(1, backend=name, state=state)
            obs.emit("router_backend_state", status="ok", backend=name,
                     state=state, track="router-backend")

    def set_cordon(self, name: str, cordoned: bool) -> None:
        """Administrative out-of-rotation (the rolling-rotation drain):
        new forwards skip the backend, in-flight ones complete."""
        with self._lock:
            b = self._backends[name]
            if b.cordoned == bool(cordoned):
                return
            b.cordoned = bool(cordoned)
        state = "cordoned" if cordoned else "uncordoned"
        self._transitions.inc(1, backend=name, state=state)
        obs.emit("router_backend_state", status="ok", backend=name,
                 state=state, track="router-backend")

    def in_rotation(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(
                n for n, b in self._backends.items() if b.in_rotation()
            ))

    def in_flight(self, name: str) -> int:
        with self._lock:
            return self._backends[name].in_flight

    def probe_ready(self, name: str) -> bool:
        """Last-probe readiness + liveness, ignoring cordon — what the
        rotation supervisor confirms against while the backend is
        still deliberately cordoned out of rotation."""
        with self._lock:
            b = self._backends[name]
            return b.ready and b.alive

    def bound_version(self, name: str, model: str):
        """The checkpoint version backend ``name`` reports for
        ``model`` (from its last probe), or None."""
        with self._lock:
            entry = self._backends[name].models.get(model)
        if isinstance(entry, dict):
            return entry.get("version")
        return entry

    def candidates(self, model: str) -> list[str]:
        """Forward candidates for ``model``: the ring's distinct owner
        order filtered to in-rotation backends whose breaker admits an
        attempt, truncated to 1 + ``failover_hops``."""
        out: list[str] = []
        for name in self.ring.owners(model):
            with self._lock:
                ok = self._backends[name].in_rotation()
            if ok and self._backends[name].breaker.allow():
                # allow() is where open → half_open happens (the trial
                # release); publish the flip instant from here.
                self._note_breaker(name)
                out.append(name)
                if len(out) > self.config.failover_hops:
                    break
        return out

    # ── forwarding ───────────────────────────────────────────────────

    def _note_breaker(self, name: str) -> None:
        """Publish a breaker state flip as an instant on the dedicated
        ``router-breaker`` track. Deduplicated against the last
        published state so steady-state traffic emits nothing."""
        state = self._backends[name].breaker.state
        with self._lock:
            if self._breaker_seen.get(name) == state:
                return
            self._breaker_seen[name] = state
        obs.emit("router_breaker", status="ok", backend=name,
                 state=state, track="router-breaker")

    def _attempt_failed(self, name: str,
                        conn: _BackendConn | None) -> None:
        """One connection-level attempt failure: breaker bookkeeping
        (+ flip instant), the attempt counter, and the dead
        connection's release."""
        self._backends[name].breaker.record_failure()
        self._note_breaker(name)
        self._requests.inc(1, backend=name, outcome="connection_error")
        if conn is not None:
            self._release(name, conn, reusable=False)

    def _acquire(self, name: str) -> _BackendConn:
        with self._lock:
            b = self._backends[name]
            conn = b.pool.pop() if b.pool else None
            b.in_flight += 1
        if conn is None:
            try:
                conn = _BackendConn(
                    b.spec, self.config.connect_timeout_s,
                    self.config.io_timeout_s,
                )
            except OSError:
                with self._lock:
                    b.in_flight -= 1
                raise
        return conn

    def _release(self, name: str, conn: _BackendConn | None,
                 reusable: bool) -> None:
        with self._lock:
            b = self._backends[name]
            b.in_flight -= 1
            if conn is not None and reusable:
                b.pool.append(conn)
                conn = None
        if conn is not None:
            conn.close()

    def forward_predict(self, header: dict, arrays: dict
                        ) -> tuple[dict, dict]:
        """Route one predict frame: try each candidate in ring order,
        failing over on connection-level errors (the backend's typed
        rejects are NOT failed over — they forward to the client,
        whose retry may legitimately land on the same owner). Returns
        the reply ``(header, arrays)``."""
        model = str(header.get("model") or "default")
        rid = str(header.get("id", ""))
        hops = 0
        # Contiguous perf_counter intervals: every moment between t0
        # and the final mark lands in exactly one phase bucket, so the
        # four phases telescope to e2e by construction (the PR 7 ±1 µs
        # discipline) — across failover hops included.
        t0 = time.perf_counter()
        last = t0
        phases = {"connect_s": 0.0, "send_s": 0.0, "wait_s": 0.0,
                  "reply_s": 0.0}

        def mark(phase: str) -> float:
            nonlocal last
            now = time.perf_counter()
            phases[phase] += now - last
            last = now
            return now

        with obs.span("router_request", request_id=rid,
                      model=model) as sp:
            reply: dict | None = None
            out_arrays: dict = {}
            backend, outcome = "-", "unavailable"
            for name in self.candidates(model):
                if hops:
                    self._failovers.inc(1)
                    obs.emit("router_failover", status="ok",
                             request_id=rid, backend=name, hop=hops)
                hops += 1
                try:
                    conn = self._acquire(name)
                except OSError:
                    mark("connect_s")
                    self._attempt_failed(name, None)
                    continue
                mark("connect_s")
                try:
                    conn.send(header, arrays)
                except (protocol.ProtocolError, OSError):
                    mark("send_s")
                    self._attempt_failed(name, conn)
                    continue
                mark("send_s")
                try:
                    conn.wait_reply()
                except (protocol.ProtocolError, OSError):
                    mark("wait_s")
                    self._attempt_failed(name, conn)
                    continue
                mark("wait_s")
                try:
                    reply, out_arrays = conn.read_reply()
                except (protocol.ProtocolError, OSError):
                    # The backend died mid-stream (kill -9's wire
                    # signature). The request id is the idempotency
                    # key — resubmitting the SAME frame to the next
                    # owner is the client's own retry discipline,
                    # applied one tier down.
                    mark("reply_s")
                    self._attempt_failed(name, conn)
                    continue
                self._backends[name].breaker.record_success()
                self._note_breaker(name)
                self._release(name, conn, reusable=True)
                backend = name
                outcome = ("ok" if reply.get("ok")
                           else "reject" if reply.get("error")
                           else "error")
                self._requests.inc(1, backend=name, outcome=outcome)
                break
            if reply is None:
                # Candidate scan (or the whole empty loop) is connect
                # work; the reject build below lands in reply_s.
                mark("connect_s")
                self._requests.inc(1, backend="-", outcome="unavailable")
                reply = {
                    "ok": False, "id": rid, "error": BACKEND_UNAVAILABLE,
                    "message": ("no backend in rotation for model "
                                f"{model!r}"),
                    "retry_after_s": self.config.retry_after_s,
                }
            end = mark("reply_s")
            e2e = end - t0
            path = ("exhausted" if backend == "-"
                    else "failover" if hops > 1 else "direct")
            sp.set_attr("backend", backend)
            sp.set_attr("hops", hops - 1 if hops else 0)
            sp.set_attr("outcome", outcome)
            sp.set_attr("path", path)
            for key, value in phases.items():
                sp.set_attr(key, round(value, 9))
            sp.set_attr("e2e_s", round(e2e, 9))
            sp.set_status("ok" if outcome in ("ok", "reject") else "error")
            self._latency.observe(e2e, outcome=outcome)
            self._paths.inc(1, path=path)
            self.slo.tick()
        return reply, out_arrays

    def call_backend(self, name: str, header: dict,
                     arrays: dict | None = None) -> tuple[dict, dict]:
        """One direct (non-routed) op against a named backend — the
        fleet supervisor's rotate/stats/dump channel. Connection
        errors propagate: the caller decides what a dead backend
        means — but they still count as breaker evidence, same as on
        the routed path."""
        try:
            conn = self._acquire(name)
        except OSError:
            self._backends[name].breaker.record_failure()
            self._note_breaker(name)
            raise
        try:
            reply, out_arrays = conn.roundtrip(header, arrays or {})
        except (protocol.ProtocolError, OSError):
            self._backends[name].breaker.record_failure()
            self._note_breaker(name)
            self._release(name, conn, reusable=False)
            raise
        self._release(name, conn, reusable=True)
        return reply, out_arrays

    # ── stats & merged dump ──────────────────────────────────────────

    def stats(self) -> dict:
        with self._lock:
            backends = {
                name: {
                    "ready": b.ready,
                    "alive": b.alive,
                    "cordoned": b.cordoned,
                    "in_rotation": b.in_rotation(),
                    "breaker": b.breaker.state,
                    "in_flight": b.in_flight,
                    "models": dict(b.models),
                }
                for name, b in sorted(self._backends.items())
            }
        return {
            "role": "router",
            "backends": backends,
            "ring": {"vnodes": self.ring.vnodes,
                     "backends": list(self.ring.backends)},
            "requests": self._born_counts("router_requests_total",
                                          self._req_baseline),
            "failover_total": self.failover_total(),
            "slo": self.slo.health(),
        }

    def _born_counts(self, name: str,
                     baseline: dict) -> dict[str, int]:
        """Per-label-key counter totals SINCE this router was built —
        the process-global value minus the construction-time baseline
        (zero-delta keys dropped)."""
        out: dict[str, int] = {}
        for key, v in sorted(
                (obs.REGISTRY.peek(name) or {}).items()):
            n = int(v) - int(baseline.get(key, 0))
            if n > 0:
                out[key] = n
        return out

    def failover_total(self) -> int:
        return sum(self._born_counts("router_failover_total",
                                     self._fo_baseline).values())

    def request_counts(self) -> dict[str, dict[str, int]]:
        """``{backend: {outcome: n}}`` from the router's own counter —
        the totals the fleet manifest publishes for reconciliation.
        The registry is process-global, so the view is BORN-RELATIVE
        (this router's own traffic only) and filtered to this router's
        backends (plus the ``-`` null backend): another router in the
        same process — the campaign's fault-free reference episode,
        an earlier test rig — must not leak into the manifest."""
        mine = set(self._backends) | {"-"}
        out: dict[str, dict[str, int]] = {}
        for key, v in self._born_counts(
                "router_requests_total", self._req_baseline).items():
            labels = obs.parse_label_key(key)
            backend = labels.get("backend", "?")
            outcome = labels.get("outcome", "?")
            if backend not in mine:
                continue
            out.setdefault(backend, {})[outcome] = int(v)
        return out

    def _own_records(self) -> list[dict]:
        """The router's slice of the process-global event ring: its
        own record families, born after THIS router — in-process
        fleets (tests, campaign) share the ring with daemons and
        earlier routers, and a daemon span must never appear twice in
        the merged fleet timeline. ``chaos_`` rides along because the
        campaign injects faults from the router's process — the
        SIGKILL instant belongs on the fleet timeline."""
        born = self._born_mono - 1e-6
        return [
            r for r in obs.EVENTS.records()
            if r.get("start_mono_s", -1.0) >= born
            and str(r.get("name", "")).startswith(
                ("router_", "fleet_", "chaos_")
            )
        ]

    def dump_fleet(self, outdir: str) -> dict:
        """Merged fleet dump: every in-rotation daemon exports its
        artifact set into ``outdir/daemon-<name>/`` (the daemon's own
        ``dump`` op — trace, serving report, SLO report, metrics
        triple), the router writes ``fleet_manifest.json`` beside
        them with its request totals per backend so the validator can
        reconcile the two views, plus its OWN trace + SLO report into
        ``outdir/router/``, and finally stitches the merged fleet
        artifacts (``fleet_trace.json`` / ``fleet_report.json`` /
        ``fleet_stat_health.json``) — a pure function of the dump dir
        (``observability/fleet_report.py``), so ``scripts/
        fleet_report.py`` reproduces them bit-for-bit offline.
        Returns the manifest dict."""
        os.makedirs(outdir, exist_ok=True)
        # The dump marker guarantees the router trace is non-empty
        # (its wall anchor must exist for the fleet re-base) even for
        # a router that admitted no backend.
        obs.emit("router_dump", status="ok", track="router-backend",
                 dir=os.path.basename(outdir))
        backends: dict[str, dict] = {}
        for name in sorted(self._backends):
            with self._lock:
                up = self._backends[name].in_rotation()
            entry: dict = {"in_rotation": up, "dumped": False}
            if up:
                sub = os.path.join(outdir, f"daemon-{name}")
                try:
                    reply, _ = self.call_backend(
                        name, {"op": "dump", "dir": sub}
                    )
                    entry["dumped"] = bool(reply.get("ok"))
                    entry["dir"] = f"daemon-{name}"
                except (protocol.ProtocolError, OSError) as e:
                    entry["error"] = f"{type(e).__name__}: {e}"
            backends[name] = entry
        manifest = {
            "schema_version": 1,
            "kind": "fleet_manifest",
            "backends": backends,
            "router": {
                "requests": self.request_counts(),
                "failover_total": self.failover_total(),
            },
            "router_dir": "router",
        }
        obs.atomic_write_json(
            os.path.join(outdir, "fleet_manifest.json"), manifest
        )
        # The router's own artifact set (trace + SLO report), then the
        # merged fleet triple — recomputed from the on-disk dump only,
        # never from live state, so the offline script's recomputation
        # is byte-identical by construction.
        rdir = os.path.join(outdir, "router")
        os.makedirs(rdir, exist_ok=True)
        trace = obs.build_trace(
            self._own_records(), meta={"tool": "router"}
        )
        obs.write_trace_json(os.path.join(rdir, "trace.json"),
                             trace=trace)
        obs.atomic_write_json(
            os.path.join(rdir, "slo_report.json"), self.slo.evaluate()
        )
        from ate_replication_causalml_tpu.observability import (
            fleet_report as _fleet_report,
        )

        _fleet_report.write_fleet_artifacts(outdir)
        return manifest

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self.prober.stop()
        with self._lock:
            conns = [c for b in self._backends.values() for c in b.pool]
            for b in self._backends.values():
                b.pool.clear()
        for c in conns:
            c.close()

    @property
    def stopped(self) -> bool:
        with self._lock:
            return self._stopped


# ── router admin plane (GET-only, shares the daemon's HTTP shell) ────

#: routes the router admin plane serves; anything else is a 404 with
#: this list in the body.
ROUTER_ADMIN_ROUTES = ("/metrics", "/healthz", "/readyz", "/fleetz")


def handle_router_admin_path(router: RouterServer,
                             path: str) -> tuple[int, str, bytes]:
    """Resolve one GET ``path`` against the router — the transport-free
    core ``serving/admin.py AdminServer(handler=...)`` mounts, so the
    router and the daemon share ONE HTTP shell (GET-only,
    500-never-kill, silent logs) with different path resolvers:

    * ``/metrics`` — the registry in Prometheus text format;
    * ``/healthz`` — liveness: 200 with per-backend breaker states and
      the router SLO burn until :meth:`RouterServer.stop`;
    * ``/readyz`` — readiness: 200 iff at least one backend is in
      rotation (a router fronting an empty fleet can take no traffic —
      the load balancer should know);
    * ``/fleetz`` — the full :meth:`RouterServer.stats` view (ring,
      per-backend rotation/breaker/in-flight, request totals).
    """
    from ate_replication_causalml_tpu.serving.admin import _json_bytes

    if path == "/metrics":
        from ate_replication_causalml_tpu.observability.promtext import (
            render_prom_text,
        )

        return 200, "text/plain; version=0.0.4", render_prom_text().encode()
    if path == "/healthz":
        with router._lock:
            items = sorted(router._backends.items())
        # Breaker states read OUTSIDE the router lock: the breaker
        # locks itself and the committed concurrency model has no
        # router-lock → breaker-lock edge to add.
        payload = {
            "role": "router",
            "state": "stopped" if router.stopped else "routing",
            "breakers": {n: b.breaker.state for n, b in items},
            "in_rotation": list(router.in_rotation()),
            "slo": router.slo.health(),
        }
        code = 200 if not router.stopped else 503
        return code, "application/json", _json_bytes(payload)
    if path == "/readyz":
        rotation = router.in_rotation()
        ready = bool(rotation) and not router.stopped
        return (
            200 if ready else 503,
            "application/json",
            _json_bytes({"ready": ready, "role": "router",
                         "in_rotation": list(rotation)}),
        )
    if path == "/fleetz":
        return 200, "application/json", _json_bytes(router.stats())
    return (
        404,
        "application/json",
        _json_bytes({"error": "not found",
                     "routes": list(ROUTER_ADMIN_ROUTES)}),
    )


# ── wire serving (client-facing loop) ────────────────────────────────


def handle_router_op(router: RouterServer, supervisor: "FleetSupervisor",
                     header: dict, arrays: dict):
    """One client frame → ``(reply_header, reply_arrays, stop?)`` —
    the router's analogue of the daemon's ``_handle_op``."""
    op = header.get("op")
    rid = str(header.get("id", ""))
    if op == "predict":
        reply, out = router.forward_predict(header, arrays)
        return reply, out, False
    if op == "ping":
        return {"ok": True, "op": "ping", "role": "router",
                "in_rotation": list(router.in_rotation())}, {}, False
    if op == "stats":
        return {"ok": True, "op": "stats", "stats": router.stats()}, {}, False
    if op == "dump":
        outdir = header.get("dir") or os.environ.get("ATE_TPU_METRICS_DIR")
        if not outdir:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "dump needs a 'dir' header field or "
                               "$ATE_TPU_METRICS_DIR"}, {}, False
        try:
            manifest = router.dump_fleet(outdir)
        except OSError as e:
            return {"ok": False, "id": rid, "error": "error",
                    "message": f"{type(e).__name__}: {e}"}, {}, False
        return {"ok": True, "op": "dump",
                "manifest": manifest}, {}, False
    if op == "rotate_all":
        checkpoint = header.get("checkpoint")
        if not checkpoint:
            return {"ok": False, "id": rid, "error": "bad_request",
                    "message": "rotate_all needs a 'checkpoint' header "
                               "field"}, {}, False
        result = supervisor.rotate_all(
            str(checkpoint), model=str(header.get("model") or "default"),
            timeout_s=float(header.get("timeout_s") or 120.0),
        )
        ok = all(s == "rotated" for s in result["statuses"].values()) \
            and result["zero_downtime"]
        return {"ok": ok, "op": "rotate_all", **result}, {}, False
    if op == "shutdown":
        return {"ok": True, "op": "shutdown"}, {}, True
    return {"ok": False, "error": "bad_request",
            "message": f"unknown op {op!r}"}, {}, False


def serve_stream(router: RouterServer, supervisor: "FleetSupervisor",
                 rstream, wstream) -> bool:
    """One client connection's framed loop; True when a ``shutdown``
    op asked the router to exit."""
    while True:
        try:
            frame = protocol.read_frame(rstream)
        except protocol.ProtocolError as e:
            obs.emit("router_protocol_error", status="error", error=str(e))
            return False
        if frame is None:
            return False
        header, arrays = frame
        reply, out_arrays, stop = handle_router_op(
            router, supervisor, header, arrays
        )
        protocol.write_frame(wstream, reply, out_arrays)
        if stop:
            return True


def serve_socket(router: RouterServer, host: str = "127.0.0.1",
                 port: int = 0,
                 on_bound: Callable[[int], None] | None = None) -> None:
    """Client-facing accept loop, the daemon's shape: one reader
    thread per connection, 0.25 s accept timeout so a stop() underneath
    ends the loop, bounded joins on exit."""
    import sys

    supervisor = FleetSupervisor(router)
    stop_evt = threading.Event()
    with socket.create_server((host, port)) as srv:
        srv.settimeout(0.25)
        bound = srv.getsockname()[1]
        obs.gauge("router_port", "bound router TCP port").set(bound)
        print(f"# routing on {host}:{bound}", file=sys.stderr, flush=True)
        if on_bound is not None:
            on_bound(bound)

        def _conn(conn: socket.socket) -> None:
            with conn:
                rw = conn.makefile("rwb")
                try:
                    if serve_stream(router, supervisor, rw, rw):
                        stop_evt.set()
                finally:
                    rw.close()

        threads: list[threading.Thread] = []
        conn_seq = 0
        while not stop_evt.is_set() and not router.stopped:
            threads = [t for t in threads if t.is_alive()]
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            conn_seq += 1
            t = threading.Thread(target=_conn, args=(conn,), daemon=True,
                                 name=f"router-conn-{conn_seq}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(1.0)
    router.stop()


# ── fleet-wide rolling rotation ──────────────────────────────────────


class FleetSupervisor:
    """Fleet-wide operations driven through the router's view of the
    world. :meth:`rotate_all` is the rolling rotation the README
    runbook documents: one daemon at a time, drained through the
    cordon (the PR 14 graceful-drain discipline applied at the router
    — no new forwards, in-flight completes), rotated from the SAME
    published checkpoint path, probe-confirmed at the advanced
    version, readmitted before the next daemon is touched."""

    def __init__(self, router: RouterServer,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.router = router
        self._clock = clock
        self._sleep = sleep

    def _wait(self, pred: Callable[[], bool], deadline: float) -> bool:
        while not pred():
            if self._clock() >= deadline:
                return False
            self._sleep(0.01)
        return True

    def rotate_all(self, checkpoint: str, model: str = "default",
                   timeout_s: float = 120.0) -> dict:
        """Roll ``model`` onto ``checkpoint`` across every in-rotation
        daemon. Returns per-backend statuses, the probe-confirmed
        version bindings, each daemon's post-swap compile count (must
        be 0 — the PR 11/12 verify-window prebuild contract), and
        ``zero_downtime``: True iff at least one backend stayed in
        rotation through every step (checked at every transition, not
        assumed)."""
        statuses: dict[str, str] = {}
        versions: dict[str, object] = {}
        compiles: dict[str, object] = {}
        min_in_rotation = len(self.router.in_rotation())
        zero_downtime = min_in_rotation >= 1

        def note_rotation_floor() -> None:
            nonlocal min_in_rotation, zero_downtime
            n = len(self.router.in_rotation())
            min_in_rotation = min(min_in_rotation, n)
            if n < 1:
                zero_downtime = False

        for name in sorted(self.router.ring.backends):
            deadline = self._clock() + timeout_s
            if name not in self.router.in_rotation():
                statuses[name] = "not_in_rotation"
                continue
            if len(self.router.in_rotation()) <= 1:
                # Cordoning the last live backend IS downtime; refuse
                # this daemon's turn rather than take the fleet out.
                statuses[name] = "refused_no_capacity"
                zero_downtime = False
                continue
            before = self.router.bound_version(name, model)
            self.router.set_cordon(name, True)
            note_rotation_floor()
            try:
                drained = self._wait(
                    lambda: self.router.in_flight(name) == 0, deadline
                )
                if not drained:
                    statuses[name] = "drain_timeout"
                    continue
                try:
                    reply, _ = self.router.call_backend(name, {
                        "op": "rotate", "model": model,
                        "checkpoint": checkpoint,
                    })
                except (protocol.ProtocolError, OSError) as e:
                    statuses[name] = f"unreachable:{type(e).__name__}"
                    continue
                status = str(reply.get("status", reply.get("error", "error")))
                statuses[name] = status
                if status != "rotated":
                    continue
                # Probe-confirm: the daemon must report ready with the
                # version ADVANCED past what it served before the swap
                # (the router never trusts its own rotate reply alone).
                confirmed = self._wait(
                    lambda: (
                        _probe_ready(self.router, name)
                        and self.router.bound_version(name, model)
                        not in (None, before)
                    ),
                    deadline,
                )
                if not confirmed:
                    statuses[name] = "verify_timeout"
                    continue
                versions[name] = self.router.bound_version(name, model)
                try:
                    sreply, _ = self.router.call_backend(
                        name, {"op": "stats"}
                    )
                    compiles[name] = (sreply.get("stats") or {}).get(
                        "compile_events_in_window"
                    )
                except (protocol.ProtocolError, OSError):
                    compiles[name] = None
            finally:
                self.router.set_cordon(name, False)
                self.router.prober.probe_once()
                note_rotation_floor()
            obs.emit("fleet_rotation", status="ok", backend=name,
                     model=model, outcome=statuses[name])
        result = {
            "model": model,
            "checkpoint": checkpoint,
            "statuses": statuses,
            "versions": versions,
            "post_swap_compiles": compiles,
            "zero_downtime": zero_downtime,
            "min_in_rotation": min_in_rotation,
        }
        obs.emit(
            "fleet_rotation_all", model=model,
            status="ok" if all(
                s == "rotated" for s in statuses.values()
            ) and zero_downtime else "error",
            rotated=sum(1 for s in statuses.values() if s == "rotated"),
        )
        return result


def _probe_ready(router: RouterServer, name: str) -> bool:
    """Force one probe round and report whether ``name`` probes ready
    — never stale cache, and deliberately NOT the in-rotation set:
    the backend under confirmation is still cordoned."""
    router.prober.probe_once()
    return router.probe_ready(name)

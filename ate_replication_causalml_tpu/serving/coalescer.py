"""Request coalescer: micro-batch within a deadline window, pad to the
nearest compiled bucket (ISSUE 6, the serving core — no jax).

The daemon AOT-compiles one predict executable per declared batch size
(the :class:`BucketPlan`). Requests arrive one at a time; dispatching
each alone would waste the large buckets, while waiting indefinitely to
fill one would trade worst-case latency for throughput. The
:class:`Coalescer` takes the standard middle road: accumulate FIFO, and
close a batch the moment it cannot grow (the next waiter would overflow
the largest bucket) or the moment the OLDEST waiter's deadline window
expires — so no request waits more than ``window_s`` for co-travellers,
and a burst packs densely without any timer firing.

The batch then rides the smallest bucket that fits (pad rows are zeros,
masked out by construction: every per-row aggregation in the predict
executable is row-independent, so garbage rows produce garbage outputs
that are simply never sliced back — the bit-identity tests pin this).

All timing is injectable (``clock=``) so the deadline math is testable
without sleeping, and monotonic — wall-clock jumps must not flush or
starve batches (graftlint JGL009).

Observability (ISSUE 7): every closed batch carries its close *reason*
(``bucket_full`` / ``next_wont_fit`` / ``window_expired`` / ``drain``),
the clock reading at close, and a monotonically increasing sequence
number — the marks the per-request lifecycle decomposition and the
serving trace's request→batch flow arrows are built from. The request
itself accumulates the remaining marks (picked up by the dispatcher,
device entry/exit, resolved) as it travels; :meth:`PendingRequest.
phase_seconds` telescopes them into the canonical phase breakdown whose
sum IS the end-to-end latency.
"""

from __future__ import annotations

import bisect
import dataclasses
import itertools
import threading
import time
from typing import Callable, NamedTuple

#: The per-request lifecycle phases, in timeline order. Durations are
#: differences of consecutive monotonic marks, so they telescope:
#: their sum equals ``resolved_mono - enqueued_mono`` exactly (up to
#: float rounding — the acceptance tests allow ±1 µs).
PHASES = ("coalesce_wait", "queue_wait", "dispatch", "device", "reply")

#: The batch close reasons the coalescer can report (precedence order:
#: a batch that is both full and expired closed because it was full).
CLOSE_REASONS = ("bucket_full", "next_wont_fit", "window_expired", "drain")


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The declared batch shapes the daemon compiled, ascending."""

    sizes: tuple[int, ...]

    def __post_init__(self):
        if not self.sizes:
            raise ValueError("bucket plan needs at least one batch size")
        sizes = tuple(int(s) for s in self.sizes)
        if any(s < 1 for s in sizes) or any(
            b <= a for a, b in zip(sizes, sizes[1:])
        ):
            raise ValueError(
                f"bucket sizes must be positive and strictly ascending, "
                f"got {self.sizes!r}"
            )
        object.__setattr__(self, "sizes", sizes)

    @classmethod
    def parse(cls, spec: str) -> "BucketPlan":
        """Parse the ``ATE_TPU_SERVE_BUCKETS`` form (``"1,8,64,256"``).
        Order-insensitive and duplicate-tolerant on input; the plan
        itself is canonical (sorted, deduped)."""
        try:
            sizes = sorted({int(s) for s in spec.split(",") if s.strip()})
        except ValueError as e:
            raise ValueError(f"bad bucket spec {spec!r}: {e}") from e
        return cls(tuple(sizes))

    @property
    def max_rows(self) -> int:
        return self.sizes[-1]

    def bucket_for(self, rows: int) -> int | None:
        """Smallest declared size that fits ``rows`` (None when even the
        largest bucket is too small — the caller rejects, typed)."""
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        idx = bisect.bisect_left(self.sizes, rows)
        return None if idx == len(self.sizes) else self.sizes[idx]


@dataclasses.dataclass(frozen=True)
class FusionPlan:
    """Serve-time bucket fusion (ISSUE 12, tentpole c): adjacent
    buckets of a :class:`BucketPlan` fuse into GROUPS, and the daemon
    AOT-compiles ONE masked executable per group (at the group's max
    width, ``compiled(forest, x, mask, None)``) instead of one per
    bucket — the executable count per model DROPS, which is a
    first-class cost (NEXT.md hardware lessons: 1-5 s per executable
    through the remote toolchain, paid per distinct geometry at every
    daemon startup).

    A batch that would have ridden bucket ``b`` rides its group's
    width instead, with a traced 0/1 row-mask marking real rows: the
    executable's trailing region is deterministic exact zeros (masked),
    never garbage (pad), and the dispatcher back-fills it with the next
    pending requests of the same model (``Coalescer.take_fill``) — pad
    FLOPs become useful FLOPs whenever traffic is queued.

    ``groups`` partitions ``plan.sizes`` ascending; pairing walks from
    the LARGEST bucket down (``pair_adjacent``) so the big buckets —
    where an executable is expensive and pad rows are plentiful —
    always share, and an odd count leaves the SMALLEST bucket alone."""

    plan: BucketPlan
    groups: tuple[tuple[int, ...], ...]

    def __post_init__(self):
        flat = [s for g in self.groups for s in g]
        if tuple(flat) != self.plan.sizes:
            raise ValueError(
                f"groups {self.groups!r} must partition the plan's "
                f"sizes {self.plan.sizes!r} in ascending order"
            )

    @classmethod
    def pair_adjacent(cls, plan: BucketPlan) -> "FusionPlan":
        sizes = list(plan.sizes)
        groups: list[tuple[int, ...]] = []
        while sizes:
            take = sizes[-2:] if len(sizes) >= 2 else sizes[-1:]
            groups.insert(0, tuple(take))
            del sizes[-len(take):]
        return cls(plan, tuple(groups))

    @property
    def widths(self) -> tuple[int, ...]:
        """One executable width per group (the group max), ascending."""
        return tuple(g[-1] for g in self.groups)

    def width_for(self, bucket: int) -> int:
        """The fused executable width a ``bucket`` batch dispatches
        on."""
        for g in self.groups:
            if bucket in g:
                return g[-1]
        raise ValueError(f"bucket {bucket} is not in the plan")


class PendingRequest:
    """One admitted request travelling through the coalescer. The
    producer blocks on :meth:`wait`; the dispatcher fills exactly one of
    ``result`` / ``error`` and fires the event. Timing marks are
    monotonic; the lifecycle marks (batch close, dispatcher pickup,
    device entry/exit) are stamped as the request travels and feed the
    per-phase latency decomposition (ISSUE 7). All marks are written
    before the done-event publication and only read after it — the
    event is the memory barrier, so the marks need no lock."""

    __slots__ = (
        "request_id", "x", "rows", "enqueued_mono", "resolved_mono",
        "batch_closed_mono", "picked_mono", "device_start_mono",
        "device_end_mono", "batch_seq", "batch_bucket", "batch_fill",
        "model", "model_version", "budget", "result", "error", "_done",
    )

    def __init__(self, request_id: str, x, rows: int, enqueued_mono: float,
                 model: str = "", budget=None):
        self.request_id = request_id
        self.x = x
        self.rows = rows
        self.enqueued_mono = enqueued_mono
        #: the caller's remaining wall-clock budget (a resilience
        #: ``Budget``, ISSUE 14), or None for deadline-less requests.
        #: Checked at every hand-off: an expired request is a typed
        #: ``deadline_exceeded`` reject, never a device dispatch.
        self.budget = budget
        #: fleet routing (ISSUE 11): the model id the request bound at
        #: admission, and the model VERSION the dispatcher actually
        #: served it with — the bit-identity partition key across a
        #: hot-swap (old forest before the swap instant, new after).
        self.model = model
        self.model_version: int | None = None
        self.resolved_mono: float | None = None
        self.batch_closed_mono: float | None = None
        self.picked_mono: float | None = None
        self.device_start_mono: float | None = None
        self.device_end_mono: float | None = None
        self.batch_seq: int | None = None
        self.batch_bucket: int | None = None
        self.batch_fill: float | None = None
        self.result = None
        self.error: BaseException | None = None
        self._done = threading.Event()

    def resolve(self, result, now: float) -> None:
        self.result = result
        self.resolved_mono = now
        self._done.set()

    def fail(self, error: BaseException, now: float) -> None:
        self.error = error
        self.resolved_mono = now
        self._done.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def phase_seconds(self) -> dict[str, float] | None:
        """The lifecycle decomposition for a SERVED request, or None
        while unresolved / failed before full mark coverage. Phases are
        consecutive mark differences (:data:`PHASES` order), so::

            sum(phase_seconds().values()) == resolved_mono - enqueued_mono

        exactly up to float rounding — the property the acceptance
        criteria pin at ±1 µs."""
        marks = (
            self.enqueued_mono, self.batch_closed_mono, self.picked_mono,
            self.device_start_mono, self.device_end_mono,
            self.resolved_mono,
        )
        if any(m is None for m in marks):
            return None
        return {
            phase: marks[i + 1] - marks[i]
            for i, phase in enumerate(PHASES)
        }


class Batch(NamedTuple):
    """A closed batch: the requests, their real row total, the compiled
    bucket it rides, the fill ratio the metrics report, plus the close
    bookkeeping (reason, clock reading, sequence number) the lifecycle
    decomposition and the serving trace are built from. ``model`` is
    the fleet routing key — a batch is model-pure by construction (one
    padded matrix dispatches against ONE forest)."""

    requests: tuple[PendingRequest, ...]
    rows: int
    bucket: int
    fill: float
    close_reason: str = "bucket_full"
    closed_mono: float = 0.0
    seq: int = 0
    model: str = ""


class Coalescer:
    """FIFO micro-batcher with a per-oldest-waiter deadline window.

    Thread model: producers call :meth:`submit`; ONE dispatcher thread
    loops on :meth:`next_batch`. All shared state lives under the
    condition's lock (graftlint JGL008 — ``serving/`` is in the
    unlocked-shared-state rule's scope by design)."""

    def __init__(
        self,
        plan: BucketPlan,
        window_s: float,
        clock: Callable[[], float] = time.monotonic,
        on_expired: Callable[[tuple[PendingRequest, ...], float], None]
        | None = None,
    ):
        if window_s < 0:
            raise ValueError(f"window_s must be >= 0, got {window_s}")
        self.plan = plan
        self.window_s = float(window_s)
        self._clock = clock
        #: deadline hand-off (ISSUE 14): waiters whose Budget expired
        #: are REMOVED before any batch math — an expired waiter must
        #: neither dispatch nor hold a fusing batch open via the
        #: oldest-waiter window — and handed to this callback (the
        #: daemon rejects them typed, phase="queue"). The callback runs
        #: with the condition held and must not re-enter the coalescer.
        self._on_expired = on_expired
        self._cond = threading.Condition()
        self._pending: list[PendingRequest] = []
        self._closed = False
        self._seq = itertools.count(1)

    def submit(self, req: PendingRequest) -> None:
        """Enqueue an admitted request (rows already validated against
        ``plan.max_rows`` by the admission layer; oversize here is a
        programming error and raises)."""
        if req.rows > self.plan.max_rows:
            raise ValueError(
                f"request of {req.rows} rows exceeds the largest bucket "
                f"({self.plan.max_rows}); the daemon must reject it typed"
            )
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            self._pending.append(req)
            self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting work and wake the dispatcher; queued requests
        still drain (each remaining :meth:`next_batch` call flushes
        immediately instead of waiting out the window)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def pending_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    # ── batch math ───────────────────────────────────────────────────

    def _harvest_expired(self, now: float) -> tuple[PendingRequest, ...]:
        """Remove (and report) every waiter whose deadline Budget has
        expired. Called with the condition held, at the top of every
        :meth:`next_batch` pass — BEFORE the batch math and before the
        oldest-waiter window computation, so an expired head-of-line
        waiter can neither ride a batch nor force one closed."""
        with self._cond:  # re-entrant — safe under next_batch's hold
            expired = tuple(
                r for r in self._pending
                if r.budget is not None and r.budget.expired()
            )
            if expired:
                gone = set(map(id, expired))
                self._pending = [
                    r for r in self._pending if id(r) not in gone
                ]
        if expired and self._on_expired is not None:
            self._on_expired(expired, now)
        return expired

    def _pack_due(self, now: float) -> Batch | None:
        """Close a batch if one is due. Batches are MODEL-PURE (fleet
        routing, ISSUE 11): the candidate is the FIFO prefix *of one
        model's waiters* that fits the largest bucket, with models
        visited in order of their oldest waiter — so a slow tenant's
        window wait never delays another tenant's full bucket. A
        candidate closes when (a) it IS the largest bucket, (b) that
        model's next waiter would not fit (flushing beats head-of-line
        blocking), (c) the model's oldest waiter's window expired, or
        (d) the coalescer is draining. Re-acquires the condition (an
        RLock underneath), so it is safe both from :meth:`next_batch`
        and standalone in tests. The close reason is recorded in
        precedence order (a batch that is both full and expired closed
        because it was full). With a single model this reduces exactly
        to the pre-fleet FIFO behavior."""
        with self._cond:
            visited: list[str] = []
            for head in self._pending:
                if head.model in visited:
                    continue
                visited.append(head.model)
                group = [r for r in self._pending if r.model == head.model]
                take: list[PendingRequest] = []
                total = 0
                for req in group:
                    if total + req.rows > self.plan.max_rows:
                        break
                    take.append(req)
                    total += req.rows
                expired = now - take[0].enqueued_mono >= self.window_s
                if total == self.plan.max_rows:
                    reason = "bucket_full"
                elif len(take) < len(group):
                    reason = "next_wont_fit"
                elif expired:
                    reason = "window_expired"
                elif self._closed:
                    reason = "drain"
                else:
                    continue  # this model's waiters are not due yet
                taken = set(map(id, take))
                self._pending = [
                    r for r in self._pending if id(r) not in taken
                ]
                bucket = self.plan.bucket_for(total)
                batch = Batch(tuple(take), total, bucket, total / bucket,
                              close_reason=reason, closed_mono=now,
                              seq=next(self._seq), model=head.model)
                for req in take:
                    req.batch_closed_mono = now
                    req.batch_seq = batch.seq
                    req.batch_bucket = bucket
                    req.batch_fill = batch.fill
                return batch
            return None

    def take_fill(self, model: str, capacity: int,
                  now: float) -> tuple[PendingRequest, ...]:
        """Back-fill for a FUSED dispatch (ISSUE 12): remove and return
        the FIFO prefix of ``model``'s pending requests whose rows fit
        ``capacity`` — the rows that would otherwise dispatch as masked
        zeros. Stops at the first waiter that does not fit (FIFO
        fairness: never reorder past a waiter), returns () when nothing
        is queued. The caller stamps batch marks (seq/bucket/fill) once
        the fused batch's final composition is known; only the close
        clock is stamped here."""
        if capacity < 1:
            return ()
        with self._cond:
            take: list[PendingRequest] = []
            total = 0
            for req in self._pending:
                if req.model != model:
                    continue
                if req.budget is not None and req.budget.expired():
                    # Never back-fill an expired waiter onto the device;
                    # it stays queued for the next harvest's typed
                    # reject (skipping it does not reorder live work —
                    # it was never going to dispatch).
                    continue
                if total + req.rows > capacity:
                    break
                take.append(req)
                total += req.rows
            if not take:
                return ()
            taken = set(map(id, take))
            self._pending = [
                r for r in self._pending if id(r) not in taken
            ]
            for req in take:
                req.batch_closed_mono = now
            return tuple(take)

    def next_batch(self, timeout: float | None = None) -> Batch | None:
        """Dispatcher entry: block until a batch closes, the coalescer
        is closed AND drained (returns None forever after), or
        ``timeout`` elapses (returns None; the dispatcher re-loops so a
        stop flag can be observed)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                now = self._clock()
                self._harvest_expired(now)
                batch = self._pack_due(now)
                if batch is not None:
                    return batch
                if self._closed and not self._pending:
                    return None
                # Sleep until the oldest waiter's window would expire,
                # the caller's timeout, or a submit/close notification.
                wait = None
                if self._pending:
                    wait = self._pending[0].enqueued_mono + self.window_s - now
                    # Wake for the earliest deadline expiry too, so an
                    # expiring waiter's typed reject is not delayed by
                    # a longer coalescing window.
                    for r in self._pending:
                        if r.budget is not None:
                            wait = min(wait, r.budget.expires_mono - now)
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                if wait is not None and wait <= 0:
                    # The packing condition will see the expiry on the
                    # next loop iteration with a fresh clock read.
                    wait = 1e-4
                self._cond.wait(wait)

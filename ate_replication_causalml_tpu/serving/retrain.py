"""Retrain supervisor: fit → publish → verify → hot-swap (ISSUE 11).

The train-to-serve loop's driver. Production refit is exactly the
cross-fit nuisance + orthogonal-moment machinery re-run on fresh
panels; this module owns everything AROUND that fit so the daemon
never serves a corrupt, stale, or half-loaded model:

1. **fit** — ``fit_fn()`` produces a fresh fitted forest. The callable
   is injected: production wires the pipeline's forest fit on the
   sharded artifact plane (device-resident ``NamedSharding`` nuisances,
   PR 8); tests wire a synthetic micro-forest. Either way it runs
   under the resilience layer's **classified-retry/deadline
   discipline**: transient failures (``JaxRuntimeError``, ``OSError``,
   injected :class:`~..resilience.errors.ChaosRotateFault`) retry with
   capped exponential backoff and deterministic crc32 jitter — the
   exact ``parallel/retry.py`` schedule, reimplemented here without
   the jax import so the supervisor stays wire-light; programming
   errors raise immediately (a bug refit three times is the same bug).
   A wall-clock ``deadline_s`` bounds the whole run.
2. **publish** — ``save_fitted`` writes the candidate to a fresh
   *versioned* path (``{model}-v{NNNN}.npz``), atomically (tmp +
   rename) with the SHA-256 content digest embedded. Every attempt
   gets a NEW version number: a refused candidate stays on disk for
   quarantine, never overwritten.
3. **rotate** — the path is handed to the daemon's rotation entry
   (:meth:`~.daemon.CateServer.rotate` →
   :meth:`~.admission.ReloadSupervisor.rotate`), which re-verifies the
   digest, checks geometry against the compiled executables, and
   hot-swaps with zero downtime. A failed re-verify is a typed
   ``refused`` — the last good checkpoint keeps serving. ``busy``
   (another reload/rotation in flight) is retried like a transient.

Chaos (``rotate:`` scope): ``retrain`` faults the fit (retried),
``corrupt`` truncates the published archive after its digest was
embedded (the rotation re-verify must refuse it), ``mid_swap`` and
``verify_ms`` land inside the rotation itself (daemon side).

Telemetry: ``serving_retrain_total{model,status}`` terminal outcomes,
``serving_retrain_retries_total{model}`` transient retries, a
``retrain_run`` span per run with ``retrain_retry`` /
``retrain_deadline`` events — the same families
``check_metrics_schema.py`` requires on every instrumented run.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
from typing import Callable

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.backoff import (
    BACKOFF_CAP_MULT,
    jittered_backoff_delay,
)
from ate_replication_causalml_tpu.resilience.deadline import Budget
from ate_replication_causalml_tpu.resilience.errors import (
    ChaosRotateFault,
    classify,
)
from ate_replication_causalml_tpu.resilience.watchdog import (
    HeartbeatRegistry,
)

__all__ = ["BACKOFF_CAP_MULT", "RetrainConfig", "RetrainOutcome",
           "RetrainSupervisor", "retrain_backoff_delay"]


def retrain_backoff_delay(model_id: str, attempt: int, base_s: float) -> float:
    """Backoff before retrying a transient retrain failure: exponential
    in the attempt, crc32-jittered, capped — the PR 3 discipline (one
    formula, ``resilience/backoff.py``), a pure function of
    ``(model_id, attempt)`` so tests can assert the exact sleep
    schedule."""
    return jittered_backoff_delay(
        f"retrain|{model_id}|{attempt}", attempt, base_s
    )


@dataclasses.dataclass(frozen=True)
class RetrainConfig:
    """Retry/deadline discipline for one supervisor."""

    max_attempts: int = 3
    backoff_s: float = 0.05
    deadline_s: float | None = None


@dataclasses.dataclass
class RetrainOutcome:
    """One ``run_once`` result. ``status`` is the terminal word:
    ``rotated`` (new model serving), ``refused`` (candidate failed the
    rotation's re-verify — last good kept), ``retired_model`` /
    ``unknown_model`` (the target id is gone — terminal), ``failed``
    (retries exhausted), ``deadline`` (wall clock cut the run),
    ``busy`` (rotation claim contended past the retry budget)."""

    model_id: str
    status: str
    attempts: int = 0
    checkpoint: str | None = None
    error: str | None = None


class RetrainSupervisor:
    """Drives the fit → publish → rotate pipeline for ONE model.

    Everything side-effectful is injected so the state machine is
    provable without jax: ``fit_fn`` returns the fresh forest,
    ``publish_fn(path, forest)`` persists it (default: the atomic,
    digest-embedding ``utils.checkpoint.save_fitted``, resolved
    lazily), ``rotate_fn(path)`` performs the verified hot-swap and
    returns the rotation status string (the daemon's
    :meth:`~.daemon.CateServer.rotate` bound to this model)."""

    def __init__(
        self,
        model_id: str,
        fit_fn: Callable[[], object],
        publish_dir: str,
        rotate_fn: Callable[[str], str],
        config: RetrainConfig = RetrainConfig(),
        publish_fn: Callable[[str, object], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        start_version: int = 2,
        heartbeats: HeartbeatRegistry | None = None,
    ):
        self.model_id = model_id
        self._fit_fn = fit_fn
        self._publish_dir = publish_dir
        self._rotate_fn = rotate_fn
        self.config = config
        self._publish_fn = publish_fn
        self._clock = clock
        self._sleep = sleep
        #: the watchdog lane (ISSUE 14): the supervisor stamps a
        #: heartbeat around every attempt so a wedged fit is a detected
        #: stall, not a silent never-returning run_once. The lane is
        #: MODEL-scoped (``retrain/<model_id>``) — in a fleet, one
        #: model's wedged fit must not be masked by another model's
        #: beats (watchdog ``bound_for`` prefix matching lets one
        #: ``retrain`` bound cover them all). The daemon's
        #: retrain_supervisor() wires its own registry in.
        self._heartbeats = heartbeats
        self._version = itertools.count(start_version)
        self._runs = _registry.counter(
            "serving_retrain_total",
            "retrain supervisor runs by model and terminal status",
        )
        self._retries = _registry.counter(
            "serving_retrain_retries_total",
            "retrain attempts retried after a transient failure",
        )

    def _publish(self, path: str, forest) -> None:
        if self._publish_fn is not None:
            self._publish_fn(path, forest)
            return
        from ate_replication_causalml_tpu.utils.checkpoint import save_fitted

        save_fitted(path, forest)

    def _candidate_path(self) -> str:
        """The next FRESH versioned path. Numbers already on disk are
        skipped — a refused candidate stays quarantined forever, and a
        restarted supervisor (seeded from the entry's version, which a
        refusal does not advance) must never overwrite it."""
        while True:
            path = os.path.join(
                self._publish_dir,
                f"{self.model_id}-v{next(self._version):04d}.npz",
            )
            if not os.path.exists(path):
                return path

    def _attempt(self) -> tuple[str, str | None]:
        """One fit → publish → rotate attempt; returns ``(status,
        checkpoint_path)``. Raises on failure (classified upstream)."""
        inj = chaos.active()
        with _events.span("retrain_fit", model=self.model_id):
            if inj is not None:
                # The hang: injection site — INSIDE the stamped unit of
                # work, so the retrain lane's heartbeat age grows and
                # the watchdog's detection path is exercised.
                delay = inj.hang_delay_s("retrain", self.model_id)
                if delay > 0:
                    time.sleep(delay)
            if inj is not None and inj.take_rotate_fault(
                "retrain", site=f"retrain/{self.model_id}"
            ):
                raise ChaosRotateFault(
                    f"chaos: injected retrain fault ({self.model_id})"
                )
            forest = self._fit_fn()
        path = self._candidate_path()
        with _events.span("retrain_publish", model=self.model_id, path=path):
            self._publish(path, forest)
            if inj is not None and inj.take_rotate_fault(
                "corrupt", site=path
            ):
                # The artifact a torn publish would leave — AFTER the
                # digest went in, so only the rotation's re-verify can
                # catch it. It must.
                os.truncate(path, max(1, (os.path.getsize(path) * 3) // 5))
        return self._rotate_fn(path), path

    def run_once(self) -> RetrainOutcome:
        """One retrain run under the full discipline. Never raises for
        transient trouble — the outcome record carries the terminal
        status; programming errors (fatal classification) re-raise."""
        cfg = self.config
        budget = (
            None if cfg.deadline_s is None
            else Budget.after(cfg.deadline_s, clock=self._clock)
        )
        out = RetrainOutcome(self.model_id, "failed")
        candidate: str | None = None
        with _events.span("retrain_run", model=self.model_id) as sp:
            while out.attempts < cfg.max_attempts:
                if self._heartbeats is not None:
                    self._heartbeats.beat(f"retrain/{self.model_id}")
                if budget is not None and budget.expired():
                    out.status = "deadline"
                    break
                out.attempts += 1
                try:
                    if candidate is None:
                        status, candidate = self._attempt()
                    else:
                        # A prior attempt already published a verified
                        # candidate and only the rotation claim was
                        # contended ("busy" — a milliseconds-scale
                        # window): retry ONLY the rotation. Re-running
                        # the fit would cost a full refit per contended
                        # claim and litter the publish dir.
                        status = self._rotate_fn(candidate)
                except Exception as e:
                    if classify(e) == "fatal":
                        sp.set_status("error")
                        self._runs.inc(1, model=self.model_id,
                                       status="fatal")
                        raise
                    out.error = f"{type(e).__name__}: {e}"
                    status, candidate = "error", None
                if status == "rotated":
                    out.status, out.checkpoint, out.error = (
                        "rotated", candidate, None
                    )
                    break
                if status in ("refused", "retired_model", "unknown_model"):
                    # Typed terminals, not retries: a refused candidate
                    # would be refused again, and a retired/unknown
                    # model id will not come back on backoff. The error
                    # field describes THIS terminal, not a stale
                    # earlier-attempt transient.
                    out.status, out.checkpoint, out.error = (
                        status, candidate, None
                    )
                    break
                if status != "busy":
                    candidate = None  # refit on the next attempt
                # transient ("error" from the fit/publish, or "busy"
                # from a contended rotation claim): back off and retry.
                out.status = "busy" if status == "busy" else "failed"
                if out.attempts >= cfg.max_attempts:
                    break
                delay = retrain_backoff_delay(
                    self.model_id, out.attempts, cfg.backoff_s
                )
                if budget is not None and not budget.affords(delay):
                    out.status = "deadline"
                    break
                self._retries.inc(1, model=self.model_id)
                _events.emit(
                    "retrain_retry", status="retrying",
                    model=self.model_id, attempt=out.attempts,
                    error=out.error or status,
                )
                self._sleep(delay)
            if out.status != "rotated":
                sp.set_status("error")
                if out.status == "deadline":
                    _events.emit(
                        "retrain_deadline", status="error",
                        model=self.model_id, attempts=out.attempts,
                        deadline_s=cfg.deadline_s,
                    )
        if self._heartbeats is not None:
            self._heartbeats.beat(f"retrain/{self.model_id}")
        self._runs.inc(1, model=self.model_id, status=out.status)
        return out

"""Tracing / profiling (SURVEY.md §5.1).

The reference's only performance instrumentation is two "~1min"
comments (``ate_functions.R:168, 230``); the north star here is a
wall-clock metric, so timing is a first-class subsystem:

* :class:`StageTimer` — accumulates named wall-clock stage timings;
  the L5 driver (pipeline.py) times every estimator through one of
  these and persists the result next to each checkpoint row. Callers
  must sync device work themselves (convert outputs via ``float(...)``
  / ``np.asarray`` — reliable on every platform, including axon where
  ``block_until_ready`` is not dependable).
* :func:`stage` — one-off variant logging a single block's duration.
* :func:`xla_trace` — wraps ``jax.profiler.trace`` when a trace dir is
  set (``ATE_TPU_TRACE_DIR`` env var or argument) and is a no-op
  otherwise, so production code can leave the hook in place.
* :func:`xprof_run` / :func:`xprof_annotation` — the ISSUE 5 device-
  profile correlation pair: with ``ATE_TPU_XPROF=<dir>`` the sweep
  captures ONE whole-run ``jax.profiler.trace`` and each stage enters a
  ``jax.profiler.TraceAnnotation`` named like its host span, so the XLA
  timeline lines up with the host trace's tracks name-for-name. Device
  capture is process-global, so the driver falls back to the
  sequential scheduler while either xprof env var is set; the host
  trace (``observability/trace.py``) needs no profiler and keeps
  working under the concurrent engine.

All three are thin emitters into the unified telemetry layer
(``observability/``): stage durations land in the
``stage_seconds`` histogram and as spans in the event log; trace
activations are counted. ``ATE_TPU_TELEMETRY=0`` reduces every emit to
one cached-bool check.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator

import jax

from ate_replication_causalml_tpu import observability as obs

_TRACE_ENV = "ATE_TPU_TRACE_DIR"
_XPROF_ENV = "ATE_TPU_XPROF"


class StageTimer:
    """Accumulates wall-clock seconds per named stage."""

    def __init__(self):
        self.seconds: dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            with obs.span("stage", stage=name):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            obs.histogram("stage_seconds", "StageTimer stage durations").observe(
                dt, stage=name
            )

    def report(self) -> str:
        total = sum(self.seconds.values())
        lines = [
            f"{name:<40s} {sec:8.3f}s"
            for name, sec in sorted(self.seconds.items(), key=lambda kv: -kv[1])
        ]
        lines.append(f"{'TOTAL':<40s} {total:8.3f}s")
        return "\n".join(lines)

    def dump(self, path: str) -> None:
        # Atomic (tmp + os.replace): a kill mid-dump must not leave a
        # truncated JSON next to a valid checkpoint.
        obs.atomic_write_json(path, self.seconds, indent=2, sort_keys=True)


@contextlib.contextmanager
def stage(name: str, log=None) -> Iterator[None]:
    """Time one stage; ``log`` (e.g. ``print``) receives `name: N.NNNs`."""
    t0 = time.perf_counter()
    try:
        with obs.span("stage", stage=name):
            yield
    finally:
        dt = time.perf_counter() - t0
        obs.histogram("stage_seconds", "StageTimer stage durations").observe(
            dt, stage=name
        )
        if log is not None:
            log(f"{name}: {dt:.3f}s")


@contextlib.contextmanager
def xla_trace(label: str = "trace", trace_dir: str | None = None) -> Iterator[None]:
    """``jax.profiler.trace`` scoped to a block when a trace directory is
    configured; no-op otherwise. View with TensorBoard / xprof.

    The label becomes a trace DIRECTORY name, so it is sanitized here
    (any char outside ``[A-Za-z0-9_-]`` → ``_``) regardless of what the
    caller passes — sweep method names like ``Causal Forest(GRF)`` or
    ``Belloni et.al`` would otherwise hit the filesystem verbatim."""
    trace_dir = trace_dir or os.environ.get(_TRACE_ENV)
    if not trace_dir:
        yield
        return
    label = obs.sanitize_label(label)
    path = os.path.join(trace_dir, label)
    os.makedirs(path, exist_ok=True)
    obs.counter("xla_trace_total", "jax.profiler.trace activations").inc(
        1, label=label
    )
    with jax.profiler.trace(path):
        yield


def xprof_dir() -> str | None:
    """The device-profile correlation dir (``ATE_TPU_XPROF``), or None."""
    return os.environ.get(_XPROF_ENV) or None


@contextlib.contextmanager
def xprof_run(label: str = "run") -> Iterator[None]:
    """One whole-run ``jax.profiler.trace`` under ``$ATE_TPU_XPROF``
    (no-op without it). Unlike :func:`xla_trace`'s per-stage capture
    dirs, a single capture spans the run, and stages are told apart by
    their :func:`xprof_annotation` names — the host-span names — so the
    XLA timeline and the host trace line up."""
    d = xprof_dir()
    if not d:
        yield
        return
    label = obs.sanitize_label(label)
    path = os.path.join(d, label)
    os.makedirs(path, exist_ok=True)
    obs.counter("xprof_trace_total", "whole-run xprof captures").inc(
        1, label=label
    )
    with jax.profiler.trace(path):
        yield


@contextlib.contextmanager
def xprof_annotation(label: str) -> Iterator[None]:
    """``jax.profiler.TraceAnnotation`` named like the host span
    (sanitized identically), active only under ``$ATE_TPU_XPROF``.
    Annotations are per-thread and nestable — safe wherever a host span
    is safe — but the driver still serializes the sweep while a device
    capture is armed (process-global profiler state)."""
    if not xprof_dir():
        yield
        return
    annot = getattr(jax.profiler, "TraceAnnotation", None)
    if annot is None:  # very old jaxlib: correlation simply degrades
        yield
        return
    with annot(obs.sanitize_label(label)):
        yield

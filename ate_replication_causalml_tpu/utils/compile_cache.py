"""Persistent XLA compilation cache — one switch for every entry point.

Forest/estimator executables take minutes to compile through the remote
TPU compile service; cached binaries carry across processes (verified:
the forest bench's first call drops 170 s → 63 s). bench.py, the sweep
driver, and the reticulate bridge all call
:func:`enable_persistent_cache`; the test suite uses its own dir in
``tests/conftest.py``.
"""

from __future__ import annotations

import os
import warnings


def _default_cache_dir() -> str:
    env = os.environ.get("ATE_COMPILE_CACHE")
    if env:
        return env
    # Repo checkout: cache beside the package (gitignored). Installed
    # package (site-packages is often read-only): user cache dir.
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    candidate = os.path.join(repo_root, ".jax_cache_tpu")
    probe_root = repo_root if os.path.isdir(repo_root) else None
    if probe_root and os.access(probe_root, os.W_OK):
        return candidate
    return os.path.join(
        os.path.expanduser("~"), ".cache", "ate_replication_causalml_tpu",
        "jax_cache",
    )


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: repo-local when writable, else ``~/.cache/...``;
    overridable via ``ATE_COMPILE_CACHE``). Returns the dir, or None if
    configuration failed — with a visible warning, never silently."""
    import jax

    existing = jax.config.jax_compilation_cache_dir
    if existing:
        # Respect a cache already configured by the embedding process
        # (e.g. the test suite's conftest dir) — don't silently retarget.
        return existing

    cache_dir = cache_dir or _default_cache_dir()
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (AttributeError, ValueError) as e:  # unknown flag after upgrade
        warnings.warn(
            f"persistent compilation cache disabled ({e}); first calls will "
            "be compile-dominated",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
    return cache_dir

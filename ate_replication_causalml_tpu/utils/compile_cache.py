"""Persistent XLA compilation cache — one switch for every entry point.

Forest/estimator executables take minutes to compile through the remote
TPU compile service; cached binaries carry across processes (verified:
the forest bench's first call drops 170 s → 63 s). bench.py, the sweep
driver, and the reticulate bridge all call
:func:`enable_persistent_cache`; the test suite uses its own dir in
``tests/conftest.py``.
"""

from __future__ import annotations

import os
import warnings


def _host_tag() -> str:
    """Short host fingerprint. XLA:CPU AOT cache entries embed the
    COMPILE machine's feature set; loading one produced in a container
    with different CPU flags SIGILLs/segfaults (observed in the test
    suite). The feature set XLA embeds also includes jaxlib-version-
    dependent tuning flags (e.g. ``+prefer-no-gather``) that /proc/
    cpuinfo can't see — a jaxlib upgrade made same-host entries fatal
    in round 3 — so the tag keys on the jax/jaxlib versions too."""
    import hashlib
    import platform

    import jax

    try:
        with open("/proc/cpuinfo") as f:
            sig = next(l for l in f if l.startswith("flags"))
    except (OSError, StopIteration):
        sig = platform.processor() or platform.machine()
    try:
        import jaxlib

        sig += jaxlib.__version__
    except (ImportError, AttributeError):  # version probe only
        pass
    sig += jax.__version__
    return hashlib.sha1(sig.encode()).hexdigest()[:10]


def _default_cache_dir() -> str:
    env = os.environ.get("ATE_COMPILE_CACHE")
    if env:
        return env
    # Repo checkout (detected by a repo marker, not mere writability —
    # a venv's site-packages parent is writable too): cache beside the
    # package, gitignored. Installed package: user cache dir.
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    is_checkout = os.path.exists(os.path.join(repo_root, ".git"))
    if is_checkout and os.access(repo_root, os.W_OK):
        return os.path.join(repo_root, f".jax_cache_tpu-{_host_tag()}")
    return os.path.join(
        os.path.expanduser("~"), ".cache", "ate_replication_causalml_tpu",
        f"jax_cache-{_host_tag()}",
    )


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: repo-local when writable, else ``~/.cache/...``;
    overridable via ``ATE_COMPILE_CACHE``). Returns the dir, or None if
    configuration failed — with a visible warning, never silently.

    ``ATE_NO_COMPILE_CACHE=1`` makes this a no-op: the CPU backend's
    cache (de)serializer segfaults on this image's jaxlib late in long
    processes (round 3 — crashes in put_/get_executable_and_time, and a
    crashed write leaves a truncated entry that crashes the next read).
    The test suite sets the kill switch so library imports (rbridge,
    pipeline) can't re-enable the cache mid-suite; TPU entry points keep
    it (only the XLA:CPU serializer has misbehaved)."""
    import jax

    from ate_replication_causalml_tpu import observability as obs

    # Bridge jax.monitoring's cache events (hits / misses / retrieval
    # time / time saved) into the metrics registry, and pre-create the
    # counters at zero — metrics.json always carries the cache keys,
    # even on the kill-switch path below.
    obs.install_jax_monitoring()
    if os.environ.get("ATE_NO_COMPILE_CACHE") == "1":
        obs.gauge("compile_cache_enabled", "persistent cache active").set(0.0)
        return None
    cache_dir = cache_dir or _default_cache_dir()
    try:
        existing = jax.config.jax_compilation_cache_dir
        if existing:
            # Respect a cache already configured by the embedding process
            # (e.g. the test suite's conftest dir) — don't retarget.
            obs.gauge("compile_cache_enabled", "persistent cache active").set(1.0)
            obs.watch_cache_dir(existing)
            return existing
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (AttributeError, ValueError) as e:  # flag renamed/removed
        obs.gauge("compile_cache_enabled", "persistent cache active").set(0.0)
        warnings.warn(
            f"persistent compilation cache disabled ({e}); first calls will "
            "be compile-dominated",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
    obs.gauge("compile_cache_enabled", "persistent cache active").set(1.0)
    # Entry-count / total-bytes gauges, refreshed at every metrics
    # snapshot (the write counter the cache API itself doesn't expose).
    obs.watch_cache_dir(cache_dir)
    try:
        # 0.1 s (round 5; was 1.0): on the remote-compile toolchain even
        # primitive-sized executables cost 0.5-2 s of wall-clock to
        # compile, so sub-second entries are exactly the ones a fresh
        # process wants back. Entry files are a few KB each.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except (AttributeError, ValueError) as e:
        # Cache dir IS active at this point — report the partial state
        # accurately rather than claiming the cache is off.
        warnings.warn(
            f"compilation cache enabled at {cache_dir}, but the min-compile-"
            f"time threshold could not be set ({e}); JAX's default applies",
            RuntimeWarning,
            stacklevel=2,
        )
    return cache_dir

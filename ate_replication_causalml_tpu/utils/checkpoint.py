"""Model checkpointing — persist fitted nuisances and forests.

The reference recomputes everything on every knit (knitr caching is not
even enabled — SURVEY.md §5.4); the expensive fits it would want to keep
are the forests (minutes of CPU) and the GLM/LASSO nuisances. Here any
of the framework's fitted objects round-trips through one ``.npz`` file:

* registered pytree dataclasses (``Forest``, ``CausalForest``,
  ``FittedCausalForest``), nested arbitrarily;
* NamedTuple results (``GlmResult``, ``CvGlmnetResult``, …);
* plain dicts / lists / scalars / arrays.

Arrays are stored once each under their tree path; static metadata
(ints, strings, None) and the structure itself live in a JSON manifest
inside the same archive — no pickle, so checkpoints are portable and
inspectable (``np.load(path).files``). The L4 driver persists *result
rows* via its own jsonl checkpoint (pipeline.py); this module is the
model-level complement.

Integrity (ISSUE 3): :func:`save_fitted` is atomic (tmp + ``os.replace``
via the observability export helpers) and embeds a SHA-256 digest over
the manifest and every array's contents; :func:`load_fitted` recomputes
and compares it, raising :class:`CheckpointCorrupt` (naming the path)
on any mismatch, unreadable archive, or missing manifest — a torn or
bit-flipped checkpoint can fail loudly but can never hand back wrong
arrays. Archives written before the digest existed load with a
``checkpoint_unverified`` event. The ``fs:corrupt_npz`` chaos scope
injects a truncated write here, which is how the refusal path is
proven in tests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import importlib
import json
import os
from typing import Any

import jax
import numpy as np

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability.export import atomic_file
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import CheckpointCorrupt

__all__ = ["CheckpointCorrupt", "load_fitted", "save_fitted"]

_ARR = "__array__"
_MANIFEST = "__manifest__"
_DIGEST = "__sha256__"


def _is_namedtuple(obj) -> bool:
    return isinstance(obj, tuple) and hasattr(obj, "_fields")


def _encode(obj: Any, path: str, arrays: dict[str, np.ndarray]):
    """Structure manifest for ``obj``; arrays stored out-of-band under
    sequential keys (tree paths can collide — dict keys may contain
    '.' — so they appear only in the manifest, not as archive keys)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.ndarray, jax.Array, np.generic)):
        key = f"arr_{len(arrays)}"
        arrays[key] = np.asarray(obj)
        return {_ARR: key, "path": path}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cls = type(obj)
        fields = {
            f.name: _encode(getattr(obj, f.name), f"{path}.{f.name}", arrays)
            for f in dataclasses.fields(obj)
        }
        return {"__dataclass__": f"{cls.__module__}:{cls.__qualname__}", "fields": fields}
    if _is_namedtuple(obj):
        cls = type(obj)
        fields = {
            name: _encode(val, f"{path}.{name}", arrays)
            for name, val in zip(obj._fields, obj)
        }
        return {"__namedtuple__": f"{cls.__module__}:{cls.__qualname__}", "fields": fields}
    if isinstance(obj, dict):
        if not all(isinstance(k, str) for k in obj):
            raise TypeError(f"only string dict keys are checkpointable at {path}")
        return {"__dict__": {k: _encode(v, f"{path}.{k}", arrays) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        kind = "__list__" if isinstance(obj, list) else "__tuple__"
        return {kind: [_encode(v, f"{path}[{i}]", arrays) for i, v in enumerate(obj)]}
    raise TypeError(f"cannot checkpoint {type(obj).__name__} at {path!r}")


_PKG_ROOT = __name__.split(".", 1)[0]  # this framework's package


def _resolve(qualname: str) -> type:
    """Resolve a ``module:QualName`` manifest reference. Restricted to
    dataclass/NamedTuple *types defined in this package*: a manifest is
    data, and letting it import arbitrary modules / call arbitrary
    callables would make loading a checkpoint equivalent to executing
    it. The module-prefix check alone is bypassable via re-exported
    attributes (``pkg.native:subprocess.Popen``), so the resolved object
    itself must also be a package-defined dataclass or NamedTuple type.
    Checkpoints remain trusted inputs (field values reach constructors),
    but the reachable surface is this framework's record types only."""
    mod, _, name = qualname.partition(":")
    if mod.split(".", 1)[0] != _PKG_ROOT:
        raise ValueError(
            f"checkpoint references type {qualname!r} outside {_PKG_ROOT!r}; "
            "refusing to import it"
        )
    obj: Any = importlib.import_module(mod)
    for part in name.split("."):
        obj = getattr(obj, part)
    is_namedtuple_cls = (
        isinstance(obj, type) and issubclass(obj, tuple) and hasattr(obj, "_fields")
    )
    if not (
        isinstance(obj, type)
        and (dataclasses.is_dataclass(obj) or is_namedtuple_cls)
        and getattr(obj, "__module__", "").split(".", 1)[0] == _PKG_ROOT
    ):
        raise ValueError(
            f"checkpoint references {qualname!r}, which is not a "
            f"dataclass/NamedTuple type defined in {_PKG_ROOT!r}; refusing"
        )
    return obj


def _decode(spec: Any, arrays) -> Any:
    if not isinstance(spec, dict):
        return spec
    if _ARR in spec:
        return arrays[spec[_ARR]]
    if "__dataclass__" in spec:
        cls = _resolve(spec["__dataclass__"])
        fields = {k: _decode(v, arrays) for k, v in spec["fields"].items()}
        return cls(**fields)
    if "__namedtuple__" in spec:
        cls = _resolve(spec["__namedtuple__"])
        fields = {k: _decode(v, arrays) for k, v in spec["fields"].items()}
        return cls(**fields)
    if "__dict__" in spec:
        return {k: _decode(v, arrays) for k, v in spec["__dict__"].items()}
    if "__list__" in spec:
        return [_decode(v, arrays) for v in spec["__list__"]]
    if "__tuple__" in spec:
        return tuple(_decode(v, arrays) for v in spec["__tuple__"])
    raise ValueError(f"unrecognized checkpoint spec {spec!r}")


def _npz_path(path: str) -> str:
    # np.savez appends '.npz' when missing but np.load does not;
    # normalize so save/load accept the same string.
    return path if path.endswith(".npz") else path + ".npz"


def _content_digest(manifest_bytes: bytes, arrays: dict[str, np.ndarray]) -> str:
    """SHA-256 over the manifest and every array's identity (name,
    dtype, shape, raw bytes) in sorted key order — the quantity the
    loader re-derives to verify integrity. Computed over the CONTENT,
    not the zip container, so recompression or archive-member reordering
    cannot fake a corruption."""
    h = hashlib.sha256()
    h.update(manifest_bytes)
    for key in sorted(arrays):
        a = np.ascontiguousarray(arrays[key])
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        # Hash the array's buffer in place (same bytes a C-order
        # tobytes() would produce) — a tobytes() copy would double peak
        # memory on a hundreds-of-MB forest checkpoint.
        h.update(memoryview(a).cast("B"))
    return h.hexdigest()


def save_fitted(path: str, obj: Any) -> None:
    """Write ``obj`` (fitted model / pytree of the kinds above) to one
    compressed ``.npz`` (extension appended if missing) — atomically,
    with the content digest embedded for :func:`load_fitted` to verify.
    Under ``ATE_TPU_CHAOS`` ``fs:corrupt_npz`` the archive is written
    deliberately truncated (the torn write the atomic rename otherwise
    makes impossible), proving the loader's refusal path."""
    path = _npz_path(path)
    arrays: dict[str, np.ndarray] = {}
    manifest = _encode(obj, "root", arrays)
    manifest_bytes = json.dumps(manifest).encode()
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    digest = _content_digest(manifest_bytes, arrays)
    # Stream the archive straight to the tmp file (atomic_file renames
    # it over `path` on success) — a hundreds-of-MB forest checkpoint
    # must not be buffered in memory on top of its arrays.
    with atomic_file(path) as tmp:
        # This IS the blessed atomic pattern: the open targets
        # atomic_file's tmp, renamed over `path` only on success.
        # graftlint: disable=JGL005
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                **{
                    _MANIFEST: np.frombuffer(manifest_bytes, dtype=np.uint8),
                    _DIGEST: np.frombuffer(digest.encode(), dtype=np.uint8),
                },
                **arrays,
            )
        inj = chaos.active()
        if inj is not None:
            cut = inj.truncate_npz(os.path.getsize(tmp), site=path)
            if cut is not None:
                os.truncate(tmp, cut)


def load_fitted(path: str, device: bool = True, verify: bool = True) -> Any:
    """Restore an object written by :func:`save_fitted`. With
    ``device=True`` arrays come back as ``jax.Array`` (placed by the
    default device policy) — except 64-bit arrays when x64 is disabled,
    which stay host NumPy rather than silently truncating (JAX converts
    them on first use; the x64 strict-parity tests get exact values).
    ``device=False`` returns host NumPy throughout.

    ``verify=True`` (default) recomputes the embedded SHA-256 and
    raises :class:`CheckpointCorrupt` — naming ``path`` — on mismatch,
    unreadable/torn archive, or missing manifest. Pre-digest legacy
    archives load with a ``checkpoint_unverified`` event."""
    path = _npz_path(path)
    try:
        with np.load(path) as z:
            manifest_bytes = bytes(z[_MANIFEST])
            stored_digest = (
                bytes(z[_DIGEST]).decode() if _DIGEST in z.files else None
            )
            arrays = {
                k: z[k] for k in z.files if k not in (_MANIFEST, _DIGEST)
            }
        manifest = json.loads(manifest_bytes.decode())
    except FileNotFoundError:
        raise
    except Exception as e:  # zipfile/zlib/KeyError/json — a torn or
        # foreign file must become the typed refusal, not whatever
        # partial-read error the stack hit first.
        raise CheckpointCorrupt(path, f"unreadable archive ({e})") from e
    if verify:
        if stored_digest is not None:
            actual = _content_digest(manifest_bytes, arrays)
            if actual != stored_digest:
                raise CheckpointCorrupt(
                    path,
                    f"content digest mismatch (stored {stored_digest[:12]}…, "
                    f"archive hashes to {actual[:12]}…)",
                )
        else:
            _events.emit("checkpoint_unverified", status="warning", path=path,
                         reason="no embedded digest (pre-ISSUE-3 archive)")
    if device:
        x64 = jax.config.read("jax_enable_x64")

        def place(v: np.ndarray):
            if v.dtype.itemsize == 8 and v.dtype.kind in "fiu" and not x64:
                return v
            return jax.numpy.asarray(v)

        arrays = {k: place(v) for k, v in arrays.items()}
    return _decode(manifest, arrays)

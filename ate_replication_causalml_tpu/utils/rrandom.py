"""R-compatible random number generation (host-side, NumPy).

The reference's data pipeline is seeded with R's Mersenne-Twister
(``set.seed(1991)``, ``ate_replication.Rmd:42``) and draws the 50k-row
subsample via ``dplyr::sample_n`` (``Rmd:67``) and bootstrap indices via
``sample(n, n, replace = TRUE)`` (``ate_functions.R:269``). Bit-matching
the R point estimates to 1e-4 (BASELINE.md) therefore requires reproducing

  * R's ``set.seed`` scrambling + MT19937 stream (R's ``RNG.c``
    semantics: 50 LCG warm-up steps, 625 LCG-filled state words, block
    generation with standard MT19937 tempering, output scaled by
    2^-32 with endpoint fixup), and
  * R's ``sample.int`` index algorithms — both the pre-3.6 "Rounding"
    default (``floor(n * unif_rand())``, the one active when the
    reference was written in 2018) and the 3.6+ "Rejection" method.

This is deliberately a **host-side** component: it feeds data prep, not
the TPU hot path. TPU-resident sampling (the 10k-replicate bootstrap)
uses ``jax.random`` threefry keys by default; ``RCompatRNG`` is the
validation mode (SURVEY.md §7.3 item 3).
"""

from __future__ import annotations

import numpy as np

_N = 624
_M = 397
_MATRIX_A = np.uint32(0x9908B0DF)
_UPPER_MASK = np.uint32(0x80000000)
_LOWER_MASK = np.uint32(0x7FFFFFFF)
_I2_32M1 = 2.3283064365386963e-10  # 1 / (2^32 - 1) as used by R's MT scaling


class RCompatRNG:
    """MT19937 stream matching R's ``set.seed(seed)`` / ``runif`` exactly."""

    def __init__(self, seed: int, sample_kind: str = "rounding"):
        if sample_kind not in ("rounding", "rejection"):
            raise ValueError(f"sample_kind must be 'rounding' or 'rejection', got {sample_kind!r}")
        self.sample_kind = sample_kind
        self._set_seed(seed)

    # -- seeding ---------------------------------------------------------
    def _set_seed(self, seed: int) -> None:
        s = np.uint32(seed)
        # R RNG_Init: 50 warm-up LCG steps, then 625 state words
        # (word 0 is the MT position counter, forced to N by FixupSeeds).
        with np.errstate(over="ignore"):
            for _ in range(50):
                s = np.uint32(69069) * s + np.uint32(1)
            state = np.empty(_N + 1, dtype=np.uint32)
            for j in range(_N + 1):
                s = np.uint32(69069) * s + np.uint32(1)
                state[j] = s
        self._mt = state[1:].copy()
        self._mti = _N  # FixupSeeds(initial=True): position = N => regenerate on first draw
        self._block = np.empty(0, dtype=np.float64)
        self._pos = 0

    # -- core generation -------------------------------------------------
    def _regenerate(self) -> None:
        """One MT19937 block update, vectorized (three dependency stages)."""
        mt = self._mt
        nxt = np.roll(mt, -1)
        with np.errstate(over="ignore"):
            # Stage 1: kk in [0, N-M) — depends only on old state.
            y = (mt[: _N - _M] & _UPPER_MASK) | (nxt[: _N - _M] & _LOWER_MASK)
            mt[: _N - _M] = mt[_M:_N] ^ (y >> np.uint32(1)) ^ np.where(
                y & np.uint32(1), _MATRIX_A, np.uint32(0)
            )
            # Stage 2: kk in [N-M, N-1) — mt[kk] depends on mt[kk-227],
            # which for kk >= 2*(N-M) was itself rewritten earlier in
            # stage 2. The dependency stride is N-M = 227, so two
            # sub-slices of width <= 227 are each internally dependency-
            # free: [227, 454) reads stage-1 results, [454, 623) reads
            # the first sub-slice's results.
            for lo, hi in ((_N - _M, 2 * (_N - _M)), (2 * (_N - _M), _N - 1)):
                y = (mt[lo:hi] & _UPPER_MASK) | (mt[lo + 1 : hi + 1] & _LOWER_MASK)
                mt[lo:hi] = mt[lo - (_N - _M) : hi - (_N - _M)] ^ (
                    y >> np.uint32(1)
                ) ^ np.where(y & np.uint32(1), _MATRIX_A, np.uint32(0))
            # Stage 3: the last word wraps to updated mt[0].
            y = (mt[_N - 1] & _UPPER_MASK) | (mt[0] & _LOWER_MASK)
            mt[_N - 1] = mt[_M - 1] ^ (y >> np.uint32(1)) ^ (
                _MATRIX_A if (y & np.uint32(1)) else np.uint32(0)
            )
            # Tempering (vectorized over the whole block).
            t = mt.copy()
            t ^= t >> np.uint32(11)
            t ^= (t << np.uint32(7)) & np.uint32(0x9D2C5680)
            t ^= (t << np.uint32(15)) & np.uint32(0xEFC60000)
            t ^= t >> np.uint32(18)
        u = t.astype(np.float64) * _I2_32M1
        # R's fixup(): keep draws strictly inside (0, 1).
        u = np.where(u <= 0.0, 0.5 * _I2_32M1, u)
        u = np.where(1.0 - u <= 0.0, 1.0 - 0.5 * _I2_32M1, u)
        self._block = u
        self._pos = 0

    def runif(self, n: int) -> np.ndarray:
        """``runif(n)`` — n doubles in (0, 1) from the MT stream."""
        out = np.empty(n, dtype=np.float64)
        filled = 0
        while filled < n:
            if self._pos >= self._block.shape[0]:
                self._regenerate()
            take = min(n - filled, self._block.shape[0] - self._pos)
            out[filled : filled + take] = self._block[self._pos : self._pos + take]
            self._pos += take
            filled += take
        return out

    # -- R sample() ------------------------------------------------------
    def _unif_index(self, dn: int) -> int:
        """R_unif_index for the 'rejection' sample kind (R >= 3.6)."""
        if dn <= 0:
            return 0
        bits = int(np.ceil(np.log2(dn)))
        while True:
            v = 0
            nb = 0
            while nb <= bits:
                v = 65536 * v + int(self.runif(1)[0] * 65536)
                nb += 16
            v &= (1 << bits) - 1
            if v < dn:
                return v

    def _rejection_sample_with_replacement(self, n: int, size: int) -> np.ndarray:
        """Vectorized R>=3.6 rejection sampling with replacement.

        The rejection loop's stream consumption is data-dependent, so a
        deep-copied probe stream first discovers exactly how many
        attempts the serial algorithm would make; the attempt values are
        then computed in bulk and the real stream advanced by precisely
        that many draws — bit-identical to the per-draw loop at
        vectorized speed (the B=1000 x 9k-row R-compat bootstrap needs
        ~1e7 attempts).
        """
        import copy

        bits = int(np.ceil(np.log2(n))) if n > 1 else 0
        count = bits // 16 + 1  # uniforms consumed per attempt
        mask = (1 << bits) - 1
        probe = copy.deepcopy(self)
        chunks: list[np.ndarray] = []
        accepted = 0
        while accepted < size:
            m = max(1024, int((size - accepted) * 2.2))
            u = probe.runif(m * count).reshape(m, count)
            v = np.zeros(m, dtype=np.int64)
            for c in range(count):
                v = 65536 * v + np.floor(u[:, c] * 65536.0).astype(np.int64)
            v &= mask
            chunks.append(v)
            accepted += int((v < n).sum())
        v = np.concatenate(chunks)
        ok_pos = np.nonzero(v < n)[0]
        total_attempts = int(ok_pos[size - 1]) + 1
        self.runif(total_attempts * count)  # advance the real stream
        return v[ok_pos[:size]]

    def sample_int(self, n: int, size: int | None = None, replace: bool = False) -> np.ndarray:
        """R ``sample.int(n, size, replace)`` — 0-based indices.

        R returns 1-based; we return 0-based for direct NumPy indexing.
        """
        if size is None:
            size = n
        if replace:
            if self.sample_kind == "rounding":
                u = self.runif(size)
                return np.floor(n * u).astype(np.int64)
            return self._rejection_sample_with_replacement(n, size)
        if size > n:
            raise ValueError("cannot take a sample larger than the population without replacement")
        # R SampleNoReplace: partial Fisher–Yates with a shrinking pool.
        x = np.arange(n, dtype=np.int64)
        out = np.empty(size, dtype=np.int64)
        if self.sample_kind == "rounding":
            u = self.runif(size)  # exactly one draw per iteration
            m = n
            for i in range(size):
                j = int(m * u[i])
                out[i] = x[j]
                m -= 1
                x[j] = x[m]
        else:
            m = n
            for i in range(size):
                j = self._unif_index(m)
                out[i] = x[j]
                m -= 1
                x[j] = x[m]
        return out

    def sample_n_rows(self, n_rows: int, size: int) -> np.ndarray:
        """``dplyr::sample_n(df, size)`` row indices (0-based): a
        without-replacement ``sample.int(n_rows, size)``."""
        return self.sample_int(n_rows, size, replace=False)

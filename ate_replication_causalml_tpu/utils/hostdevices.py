"""Virtual host-device provisioning across jax versions.

Newer jax exposes ``jax.config.update("jax_num_cpu_devices", n)``;
older jax (this image's 0.4.37) only honors
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` read at backend
init. Four call sites (tests/conftest.py, bench.py's CPU child,
``__graft_entry__``'s dryrun, the multihost test child) need the same
dance with subtly different semantics — one helper so they cannot
drift. Pure ``os``/``re``: importable before jax, never initializes a
backend.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def xla_flags_with_device_count(flags: str, n: int,
                                keep_larger: bool = False) -> tuple[str, int]:
    """Return ``(new_flags, count)``: ``flags`` with the device-count
    flag REPLACED by ``n`` (an inherited smaller count silently shrinks
    every mesh; append-if-absent is the bug, not the feature). With
    ``keep_larger`` a larger inherited count survives — for callers that
    need *at least* ``n`` rather than exactly ``n``."""
    m = re.search(rf"{_FLAG}=(\d+)", flags)
    count = n
    if m and keep_larger:
        count = max(n, int(m.group(1)))
    stripped = re.sub(rf"{_FLAG}=\d+", "", flags)
    return (stripped + f" {_FLAG}={count}").strip(), count


def force_host_device_count(n: int, keep_larger: bool = False) -> int:
    """Provision ``n`` virtual CPU devices on whatever jax is installed.

    Tries the config option first (works even after import, newer jax);
    falls back to rewriting ``XLA_FLAGS`` in ``os.environ`` — which only
    takes effect if the backend has not initialized yet, exactly like
    the config path's own requirement. Returns the count provisioned.
    """
    import jax

    try:
        current = getattr(jax.config, "jax_num_cpu_devices", 0) or 0
        count = max(n, current) if keep_larger else n
        jax.config.update("jax_num_cpu_devices", count)
        return count
    except AttributeError:
        flags, count = xla_flags_with_device_count(
            os.environ.get("XLA_FLAGS", ""), n, keep_larger=keep_larger
        )
        os.environ["XLA_FLAGS"] = flags
        return count

"""Resilience layer: chaos injection, error taxonomy, typed failures.

The sweep's estimators decompose into independent, idempotent, per-key
shards (DML/AIPW cross-fitting, bootstrap-of-little-bags forests), so
recovery is re-execution and partial coverage still yields a valid
estimate. This package supplies the pieces every failure-prone layer
shares:

* :mod:`.chaos` — the ``ATE_TPU_CHAOS`` fault injector (shard faults,
  torn writes, dropped devices, stage failures), seeded + deterministic,
  every injection a structured observability event;
* :mod:`.errors` — the fatal-vs-transient classification the hardened
  shard runner retries by, and the typed failures
  (:class:`CheckpointCorrupt`, :class:`DeadlineExceeded`,
  :class:`NonFiniteResult`, the :class:`ChaosFault` family).

Consumers: ``parallel/retry.py`` (classified retry, deadline, re-probe),
``pipeline.py`` (stage isolation + graceful degradation),
``utils/checkpoint.py`` (verified checkpoints). README "Resilience &
fault injection" documents the operator surface.
"""

from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.errors import (
    FATAL_ERRORS,
    ChaosFault,
    ChaosShardFault,
    ChaosSpecError,
    ChaosStageFault,
    CheckpointCorrupt,
    DeadlineExceeded,
    NonFiniteResult,
    classify,
    transient_errors,
)

__all__ = [
    "FATAL_ERRORS",
    "ChaosFault",
    "ChaosShardFault",
    "ChaosSpecError",
    "ChaosStageFault",
    "CheckpointCorrupt",
    "DeadlineExceeded",
    "NonFiniteResult",
    "chaos",
    "classify",
    "transient_errors",
]

"""Resilience layer: chaos injection, error taxonomy, typed failures.

The sweep's estimators decompose into independent, idempotent, per-key
shards (DML/AIPW cross-fitting, bootstrap-of-little-bags forests), so
recovery is re-execution and partial coverage still yields a valid
estimate. This package supplies the pieces every failure-prone layer
shares:

* :mod:`.chaos` — the ``ATE_TPU_CHAOS`` fault injector (shard faults,
  torn writes, dropped devices, stage failures), seeded + deterministic,
  every injection a structured observability event;
* :mod:`.errors` — the fatal-vs-transient classification the hardened
  shard runner retries by, and the typed failures
  (:class:`CheckpointCorrupt`, :class:`DeadlineExceeded`,
  :class:`NonFiniteResult`, the :class:`ChaosFault` family);
* :mod:`.deadline` — the ONE wall-clock :class:`Budget` type the wire
  ``deadline_ms``, the shard runner's ``deadline_s`` and the drain
  bound all speak (ISSUE 14);
* :mod:`.watchdog` — heartbeat-stamped liveness: per-lane staleness
  bounds (``ATE_TPU_WATCHDOG_*``), stall episodes as events +
  ``watchdog_stalls_total``, injectable clock (ISSUE 14);
* :mod:`.invariants` — the system-wide invariant registry: named
  guarantees evaluated as pure functions of a run's committed
  artifacts (ISSUE 15);
* :mod:`.campaign` — the chaos campaign engine: seeded multi-scope
  fault storms across the four real workloads, judged by the
  invariant registry, with a deterministic failure shrinker
  (ISSUE 15).

Consumers: ``parallel/retry.py`` (classified retry, deadline, re-probe),
``pipeline.py`` (stage isolation + graceful degradation),
``utils/checkpoint.py`` (verified checkpoints), ``serving/`` (deadline
plane, dispatcher watchdog, graceful drain), ``scheduler/engine.py``
(worker/mesh-lane heartbeats + stall diagnostics). README "Resilience &
fault injection" and "Deadlines, watchdog & drain" document the
operator surface.
"""

from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.deadline import Budget
from ate_replication_causalml_tpu.resilience.watchdog import (
    HeartbeatRegistry,
    Watchdog,
    lane_bound_s,
)
from ate_replication_causalml_tpu.resilience.errors import (
    FATAL_ERRORS,
    ChaosFault,
    ChaosShardFault,
    ChaosSpecError,
    ChaosStageFault,
    CheckpointCorrupt,
    DeadlineExceeded,
    NonFiniteResult,
    classify,
    transient_errors,
)

__all__ = [
    "Budget",
    "FATAL_ERRORS",
    "ChaosFault",
    "ChaosShardFault",
    "ChaosSpecError",
    "ChaosStageFault",
    "CheckpointCorrupt",
    "DeadlineExceeded",
    "HeartbeatRegistry",
    "NonFiniteResult",
    "Watchdog",
    "chaos",
    "classify",
    "lane_bound_s",
    "transient_errors",
]

"""Chaos harness: seeded, env-configurable fault injection (ISSUE 3).

The only way to trust a recovery path is to walk it on purpose. One env
var arms deterministic fault injectors at every failure-prone boundary
the framework owns — the shard runner, the checkpoint writers, the
device prober, the sweep stages::

    ATE_TPU_CHAOS="shard:p=0.2,seed=7;fs:torn_write;device:drop=1"

Grammar: scopes separated by ``;``, each ``name:item,item,...`` where an
item is ``key=value`` or a bare flag. Scopes and their keys:

* ``shard`` — ``p`` (selection probability per ``(pool, shard)`` site),
  ``seed``, ``times`` (failing attempts per selected site, default 1),
  ``pool`` (substring filter). A selected shard's first ``times``
  attempts raise :class:`~.errors.ChaosShardFault`.
* ``fs`` — flags ``torn_write`` (the next checkpoint-journal append is
  written truncated, the artifact a kill mid-append leaves) and
  ``corrupt_npz`` (the next ``save_fitted`` writes a truncated archive,
  which the load-side digest must reject); ``times`` budgets each flag.
* ``device`` — ``drop=k``: ``probe_devices`` reports the last ``k``
  devices unhealthy (``times`` probes affected; 0 = every probe).
* ``stage`` — ``fail=<substring>``: the first ``times`` sweep stages
  whose method name contains the substring raise
  :class:`~.errors.ChaosStageFault` (exercising graceful degradation).
* ``serve`` — ``p`` (selection probability per request id), ``seed``,
  ``times`` (faulting attempts per selected id, default 1): the serving
  daemon consults :meth:`ChaosInjector.take_serve_fault` per request;
  a selected request's first ``times`` attempts draw
  :class:`~.errors.ChaosServeFault` — the daemon answers with a typed
  retry-after reject and its degraded-mode recovery. Selection hashes
  the CLIENT-supplied request id, so with a client that retries under
  the same id the planned reject set is identical run to run and a
  chaos-free rerun of the same stream is bit-identical.
* ``hang`` — deterministic stalls at the heartbeat-stamped sites
  (ISSUE 14): ``hang:scope=dispatch|worker|retrain,ms=..,p=..,seed=..,
  times=..``. The named lane's work units consult
  :meth:`ChaosInjector.hang_delay_s` before running; a selected site's
  first ``times`` units sleep ``ms`` — a stall, not a fault: nothing
  raises, the unit just stops making progress, which is exactly what
  the heartbeat watchdog (``resilience/watchdog.py``) must detect
  within its bound. Selection hashes the SITE (the daemon uses the
  batch's first request id; the scheduler uses the node name; the
  retrain supervisor its model id) with the same pure-hash discipline
  as ``serve:``, so planned == observed stalls is assertable and a
  stall-free rerun of the same stream is bit-identical.
* ``tamper`` — SILENT corruption the system is NOT expected to
  tolerate (ISSUE 15): ``tamper:journal,delta=..,times=..`` perturbs
  the ``ate`` field of the next journaled result row by ``delta``
  AFTER the in-memory copy was taken — a valid JSON line with a wrong
  number, the artifact a bit flip or a buggy serializer would leave.
  No reader can reject it (it parses, it resumes); only the campaign
  invariant registry's bit-identity check against a fault-free
  reference (``resilience/invariants.py``) can catch it. The scope
  exists to prove the campaign's DETECTION power and to give the
  failure shrinker a deterministic violation to minimize — arming it
  in production is arming data corruption.
* ``daemon`` — horizontal-fleet process death (ISSUE 18):
  ``daemon:kill=k,seed=..`` SIGKILLs ``k`` of the fleet's serving
  daemons mid-replay. Selection ranks backend NAMES by the pure
  ``(seed, "daemon", name)`` hash (:meth:`ChaosInjector.
  daemon_kill_plan`) so the invariant registry recomputes the victim
  set from the spec alone; ``k`` is capped at fleet size − 1 (killing
  every backend makes zero-silent-drops unprovable by definition).
  The kill is a real ``SIGKILL`` — no atexit, no drain, the wire dies
  mid-frame — exercising the router's circuit-breaker/failover path
  and the client's ``connection_lost`` reconnect-resubmit discipline.
* ``rotate`` — the train-to-serve fleet's failure modes (ISSUE 11),
  each a bare flag budgeted by ``times``: ``retrain`` (the retrain
  supervisor's fit raises :class:`~.errors.ChaosRotateFault` —
  transient, so the classified-retry discipline re-runs it),
  ``corrupt`` (the next published checkpoint is truncated after its
  digest was embedded — rotation's re-verify MUST refuse it and keep
  the last good model), ``mid_swap`` (the installer raises between
  verify and swap — the rotation must refuse atomically, never leave a
  half-installed model), and ``verify_ms=<float>`` (the rotation's
  verify step sleeps this long — serving and ``readyz`` must be
  unaffected for the whole window).

Injection decisions are pure functions of ``(seed, scope, site)`` —
never of call order or a global RNG — so a chaos run is reproducible
and, because retried shards carry their own fold-in keys, its surviving
results are bit-identical to a fault-free run's. Every injected fault
is emitted as a structured ``chaos_inject`` observability event and
counted in ``chaos_injections_total``, so chaos runs are auditable from
``events.jsonl`` alone.

This module imports no jax (decisions are host-side hashing), so it is
usable from any layer without initializing a backend.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import threading
from typing import Callable, Iterator, Sequence

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry
from ate_replication_causalml_tpu.resilience.errors import (
    ChaosShardFault,
    ChaosSpecError,
    ChaosStageFault,
)

ENV_VAR = "ATE_TPU_CHAOS"

#: scope -> key -> expected type (bool keys are the bare flags).
_SCOPE_SCHEMA: dict[str, dict[str, type]] = {
    "shard": {"p": float, "seed": int, "times": int, "pool": str},
    "fs": {"torn_write": bool, "corrupt_npz": bool, "times": int},
    "device": {"drop": int, "times": int},
    "stage": {"fail": str, "times": int},
    "serve": {"p": float, "seed": int, "times": int},
    "hang": {"scope": str, "ms": float, "p": float, "seed": int,
             "times": int},
    "rotate": {"corrupt": bool, "mid_swap": bool, "retrain": bool,
               "verify_ms": float, "times": int},
    "tamper": {"journal": bool, "delta": float, "times": int},
    "daemon": {"kill": int, "seed": int},
}

#: lanes the ``hang`` scope may target — the heartbeat-stamped sites.
HANG_SCOPES = ("dispatch", "worker", "retrain")

_SCOPE_DEFAULTS: dict[str, dict[str, object]] = {
    "shard": {"p": 0.0, "seed": 0, "times": 1, "pool": ""},
    "fs": {"torn_write": False, "corrupt_npz": False, "times": 1},
    "device": {"drop": 0, "times": 0},  # times=0: every probe
    "stage": {"fail": "", "times": 1},
    "serve": {"p": 0.0, "seed": 0, "times": 1},
    "hang": {"scope": "", "ms": 0.0, "p": 0.0, "seed": 0, "times": 1},
    "rotate": {"corrupt": False, "mid_swap": False, "retrain": False,
               "verify_ms": 0.0, "times": 1},
    "tamper": {"journal": False, "delta": 1e-3, "times": 1},
    "daemon": {"kill": 0, "seed": 0},
}


def _record_injection(scope: str, site: str, **detail) -> None:
    """The single audit channel every injected fault reports through:
    one counter family + one ``chaos_inject`` event shape, shared by
    the injector and the plan-based wrapper so the two can never
    diverge.

    ``chaos_inject`` is a point event, emitted from inside the faulted
    work's own span — so the trace exporter (observability/trace.py)
    renders every injection as an instant marker on the worker/lane
    track that was running the victim, exactly where a reader of the
    timeline would look for the cause of the failure slice."""
    _registry.counter(
        "chaos_injections_total", "faults injected by the chaos harness"
    ).inc(1, scope=scope)
    _events.emit("chaos_inject", status="injected", scope=scope,
                 site=site, **detail)


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Parsed ``ATE_TPU_CHAOS`` spec: ``scopes[name][key]`` with
    defaults filled in. Only scopes named in the spec are armed."""

    spec: str
    scopes: dict  # name -> {key: value}

    def scope(self, name: str) -> dict | None:
        return self.scopes.get(name)


def parse_chaos(spec: str) -> ChaosConfig:
    """Parse the grammar above; unknown scopes/keys and uncoercible
    values raise :class:`ChaosSpecError` — a malformed chaos config must
    fail the run at arm time, not silently inject nothing."""
    scopes: dict[str, dict[str, object]] = {}
    for raw_scope in spec.split(";"):
        raw_scope = raw_scope.strip()
        if not raw_scope:
            continue
        name, sep, body = raw_scope.partition(":")
        name = name.strip()
        schema = _SCOPE_SCHEMA.get(name)
        if schema is None:
            raise ChaosSpecError(
                f"unknown chaos scope {name!r} in {spec!r} "
                f"(known: {', '.join(sorted(_SCOPE_SCHEMA))})"
            )
        params = dict(_SCOPE_DEFAULTS[name])
        if sep:
            for item in body.split(","):
                item = item.strip()
                if not item:
                    continue
                key, eq, value = item.partition("=")
                key = key.strip()
                if key not in schema:
                    raise ChaosSpecError(
                        f"unknown key {key!r} for chaos scope {name!r} "
                        f"(known: {', '.join(sorted(schema))})"
                    )
                typ = schema[key]
                if not eq:
                    if typ is not bool:
                        raise ChaosSpecError(
                            f"chaos key {name}:{key} needs a value "
                            f"({key}=<{typ.__name__}>)"
                        )
                    params[key] = True
                    continue
                try:
                    params[key] = (
                        value.strip() if typ is str
                        else typ(value.strip()) if typ is not bool
                        else value.strip().lower() in ("1", "true", "yes", "on")
                    )
                except ValueError as e:
                    raise ChaosSpecError(
                        f"chaos key {name}:{key}={value!r} is not a "
                        f"{typ.__name__}"
                    ) from e
        if name == "daemon" and int(params["kill"]) < 0:
            raise ChaosSpecError(
                f"daemon:kill={params['kill']} must be >= 0 "
                "(the number of fleet daemons to SIGKILL mid-replay)"
            )
        if name == "hang" and params["scope"] not in HANG_SCOPES:
            # scope is REQUIRED: a hang spec that names no lane injects
            # nothing, and an operator who believes stalls are flowing
            # while nothing runs is the exact silent failure this
            # config-time raise discipline exists to prevent.
            raise ChaosSpecError(
                f"hang:scope={params['scope']!r} is not a stamped lane "
                f"(scope is required; known: {', '.join(HANG_SCOPES)})"
            )
        scopes[name] = params
    return ChaosConfig(spec=spec, scopes=scopes)


def _unit(seed: int, *parts: str) -> float:
    """Deterministic uniform in [0, 1) from (seed, parts) — sha256, no
    global RNG, independent of call order."""
    h = hashlib.sha256(("%d|" % seed + "|".join(parts)).encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0**64


class ChaosInjector:
    """Stateful fault budgets over a parsed :class:`ChaosConfig`.

    *Selection* is stateless (hash of seed + site); *budgets* (``times``)
    are process state guarded by a lock, so one injector arms a whole
    run coherently across the sweep driver, shard loops and writers.
    """

    def __init__(self, config: ChaosConfig):
        self.config = config
        self._lock = threading.Lock()
        self._shard_left: dict[tuple[str, int], int] = {}
        fs = config.scope("fs") or _SCOPE_DEFAULTS["fs"]
        self._fs_left = {
            kind: (int(fs["times"]) if fs.get(kind) else 0)
            for kind in ("torn_write", "corrupt_npz")
        }
        dev = config.scope("device")
        self._device_left = int(dev["times"]) if dev else 0
        self._device_unlimited = bool(dev) and int(dev["times"]) == 0
        stage = config.scope("stage")
        self._stage_left = int(stage["times"]) if stage else 0
        self._serve_attempts: dict[str, int] = {}
        self._hang_attempts: dict[str, int] = {}
        rot = config.scope("rotate") or _SCOPE_DEFAULTS["rotate"]
        self._rotate_left = {
            kind: (int(rot["times"]) if rot.get(kind) else 0)
            for kind in ("corrupt", "mid_swap", "retrain")
        }
        self._rotate_verify_left = (
            int(rot["times"]) if float(rot["verify_ms"]) > 0 else 0
        )
        tam = config.scope("tamper") or _SCOPE_DEFAULTS["tamper"]
        self._tamper_left = int(tam["times"]) if tam.get("journal") else 0
        # daemon scope: one kill per planned backend, ever (a SIGKILL
        # is not repeatable); the set guards double-recording.
        self._daemon_killed: set[str] = set()

    # ── bookkeeping ───────────────────────────────────────────────────

    _record = staticmethod(_record_injection)

    # ── shard scope ───────────────────────────────────────────────────

    def shard_should_fail(self, pool: str, shard: int, attempt: int) -> bool:
        cfg = self.config.scope("shard")
        if cfg is None or cfg["p"] <= 0.0:
            return False
        if cfg["pool"] and cfg["pool"] not in pool:
            return False
        key = (pool, shard)
        with self._lock:
            left = self._shard_left.get(key)
            if left is None:
                selected = _unit(
                    int(cfg["seed"]), "shard", pool, str(shard)
                ) < float(cfg["p"])
                left = int(cfg["times"]) if selected else 0
            if left <= 0:
                self._shard_left[key] = 0
                return False
            self._shard_left[key] = left - 1
        self._record("shard", f"{pool}/{shard}", pool=pool, shard=shard,
                     attempt=attempt)
        return True

    def wrap_shard(
        self, shard_fn: Callable[[int], object], pool: str
    ) -> Callable[[int], object]:
        """The ``run_shards`` injection point: a selected shard's first
        ``times`` attempts raise before the real thunk runs (so the
        injected fault costs no device work, like a preemption would)."""
        attempts: dict[int, int] = {}

        def chaotic(i: int):
            attempts[i] = attempts.get(i, 0) + 1
            if self.shard_should_fail(pool, i, attempts[i]):
                raise ChaosShardFault(
                    f"chaos: injected shard fault (pool={pool!r}, shard={i}, "
                    f"attempt={attempts[i]})"
                )
            return shard_fn(i)

        return chaotic

    # ── fs scope ──────────────────────────────────────────────────────

    def _fs_take(self, kind: str) -> bool:
        with self._lock:
            if self._fs_left.get(kind, 0) <= 0:
                return False
            self._fs_left[kind] -= 1
        return True

    def torn_line(self, line: str, site: str) -> str:
        """Checkpoint-journal injection point: return ``line`` truncated
        mid-record (the artifact a kill mid-append leaves) while the
        budget lasts. The newline is kept so the tear stays confined to
        this record — the run continues, and the reader's torn-line
        skip + recompute-on-resume path is what gets exercised."""
        if not self._fs_take("torn_write"):
            return line
        body = line.rstrip("\n")
        cut = max(1, len(body) // 2)
        self._record("fs", site, kind="torn_write", dropped_chars=len(body) - cut)
        return body[:cut] + "\n"

    def truncate_npz(self, nbytes: int, site: str) -> int | None:
        """Checkpoint-writer injection point: the length to truncate an
        ``nbytes``-long archive to (or None: budget spent / scope off),
        so the on-disk file is exactly what a torn write would leave —
        the load side must refuse it (CheckpointCorrupt), never hand
        back wrong arrays. Size-based so the writer can stream the
        archive to disk and ``os.truncate`` it, instead of buffering
        it in memory for us to slice."""
        if not self._fs_take("corrupt_npz"):
            return None
        cut = max(1, (nbytes * 3) // 5)
        self._record("fs", site, kind="corrupt_npz", dropped_bytes=nbytes - cut)
        return cut

    # ── tamper scope ──────────────────────────────────────────────────

    def tamper_line(self, line: str, site: str) -> str:
        """Silent-corruption injection point (ISSUE 15): perturb the
        ``ate`` field of a serialized journal row by ``delta`` while the
        budget lasts. The returned line PARSES — no torn-line skip, no
        digest mismatch, no typed error: the corruption is invisible to
        every reader the system owns, which is exactly what the
        campaign's bit-identity invariant (and nothing else) must
        catch. Rows without a finite numeric ``ate`` (the journal's
        ``__config__`` header, already-failed rows) pass through
        without consuming budget, so the first REAL result row is the
        deterministic victim."""
        cfg = self.config.scope("tamper")
        if cfg is None or not cfg.get("journal"):
            return line
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return line
        ate = rec.get("ate") if isinstance(rec, dict) else None
        if isinstance(ate, bool) or not isinstance(ate, (int, float)):
            return line
        with self._lock:
            if self._tamper_left <= 0:
                return line
            self._tamper_left -= 1
        rec["ate"] = ate + float(cfg["delta"])
        self._record("tamper", site, kind="journal",
                     delta=float(cfg["delta"]), method=str(rec.get("method")))
        return json.dumps(rec) + "\n"

    # ── device scope ──────────────────────────────────────────────────

    def drop_devices(self, healthy: Sequence) -> list:
        """``probe_devices`` injection point: report the last ``drop``
        devices unhealthy, simulating a preempted slice / dropped
        tunnel. Deterministic — the same devices stay dead on re-probe,
        so redistribution onto the surviving subset is what's tested."""
        cfg = self.config.scope("device")
        devs = list(healthy)
        if cfg is None or int(cfg["drop"]) <= 0 or not devs:
            return devs
        if not self._device_unlimited:
            with self._lock:
                if self._device_left <= 0:
                    return devs
                self._device_left -= 1
        k = min(int(cfg["drop"]), len(devs))
        self._record("device", "probe_devices", dropped=k,
                     remaining=len(devs) - k)
        return devs[: len(devs) - k]

    # ── stage scope ───────────────────────────────────────────────────

    def take_stage_fault(self, method: str, *, record: bool = True) -> bool:
        """Whether this stage draws an injected fault (consuming one
        unit of the ``times`` budget). Selection is the substring match;
        the budget makes it first-``times``-matches — *in whatever order
        this is called*, which is why the concurrent sweep driver plans
        all stage faults up front in declared order
        (:meth:`plan_stage_faults`) instead of racing workers for the
        budget."""
        cfg = self.config.scope("stage")
        if cfg is None or not cfg["fail"] or cfg["fail"] not in method:
            return False
        with self._lock:
            if self._stage_left <= 0:
                return False
            self._stage_left -= 1
        if record:
            self._record("stage", method, fail=cfg["fail"])
        return True

    def record_stage_fault(self, method: str) -> None:
        """Emit the injection event/counter for a *planned* stage fault
        at the moment it is actually raised. Planning selects without
        recording so an aborted sweep never reports a fault injected on
        a stage that was skipped."""
        cfg = self.config.scope("stage")
        self._record("stage", method, fail=cfg["fail"] if cfg else "")

    def plan_stage_faults(self, methods: Sequence[str]) -> frozenset[str]:
        """Consume the stage-fault budget against ``methods`` in the
        given (declared) order and return the set that must fail —
        the deterministic plan the concurrent sweep injects from, so
        worker completion order can never change *which* stages the
        budget selects. Selection is recorded when the fault is raised
        (:meth:`record_stage_fault`), not here."""
        return frozenset(
            m for m in methods if self.take_stage_fault(m, record=False)
        )

    # ── serve scope ───────────────────────────────────────────────────

    def take_serve_fault(self, request_id: str | int) -> bool:
        """Serving-request injection point: whether THIS attempt of
        ``request_id`` draws an injected fault. Selection is the pure
        ``(seed, "serve", id)`` hash — per id, not per arrival order —
        and a selected id's first ``times`` attempts fault (mirroring
        the ``shard`` scope's per-site semantics), so a client that
        retries under the same id converges: attempt ``times``+1 is
        served. With client-stable ids the planned reject set is
        identical run to run regardless of server-side concurrency."""
        cfg = self.config.scope("serve")
        if cfg is None or cfg["p"] <= 0.0:
            return False
        rid = str(request_id)
        # Selection first (pure hash, stateless): attempt bookkeeping is
        # kept ONLY for selected ids, so a long soak at small p does not
        # grow the attempts dict by every request id ever seen.
        if _unit(int(cfg["seed"]), "serve", rid) >= float(cfg["p"]):
            return False
        with self._lock:
            attempt = self._serve_attempts.get(rid, 0) + 1
            self._serve_attempts[rid] = attempt
        if attempt > int(cfg["times"]):
            return False
        self._record("serve", f"req/{rid}", request_id=rid,
                     attempt=attempt)
        return True

    # ── hang scope ────────────────────────────────────────────────────

    def hang_delay_s(self, scope: str, site: str) -> float:
        """Stall-injection point for the heartbeat-stamped lanes
        (ISSUE 14): seconds THIS unit of work must sleep, or 0.0. Only
        the configured ``scope`` lane is eligible; selection is the
        pure ``(seed, "hang", scope, site)`` hash (per site, not per
        arrival order — the serve-scope discipline), and a selected
        site's first ``times`` units stall. The sleep happens INSIDE
        the stamped work unit, so the lane's heartbeat age grows and
        the watchdog's detection path is exactly what a real wedge
        would walk. Nothing raises and no result changes — a stall-free
        rerun of the same stream is bit-identical by construction."""
        cfg = self.config.scope("hang")
        if (
            cfg is None or cfg["scope"] != scope
            or float(cfg["p"]) <= 0.0 or float(cfg["ms"]) <= 0.0
        ):
            return 0.0
        key = f"{scope}/{site}"
        if _unit(int(cfg["seed"]), "hang", scope, str(site)) >= float(cfg["p"]):
            return 0.0
        with self._lock:
            attempt = self._hang_attempts.get(key, 0) + 1
            self._hang_attempts[key] = attempt
        if attempt > int(cfg["times"]):
            return 0.0
        delay = float(cfg["ms"]) / 1e3
        self._record("hang", key, lane=scope, delay_s=delay,
                     attempt=attempt)
        return delay

    # ── rotate scope ──────────────────────────────────────────────────

    def take_rotate_fault(self, kind: str, site: str) -> bool:
        """Fleet-rotation injection point: whether this ``kind``
        (``corrupt`` / ``mid_swap`` / ``retrain``) draws a fault,
        consuming one unit of its ``times`` budget. The three kinds are
        budgeted independently so one spec can stack failure modes
        (``rotate:retrain,corrupt,times=2``)."""
        with self._lock:
            if self._rotate_left.get(kind, 0) <= 0:
                return False
            self._rotate_left[kind] -= 1
        self._record("rotate", site, kind=kind)
        return True

    def rotate_verify_delay_s(self, site: str) -> float:
        """Slow-verify injection point: seconds the rotation's verify
        step must sleep (0.0 when the scope is off or the budget is
        spent). Serving must be provably unaffected for the window."""
        cfg = self.config.scope("rotate")
        if cfg is None or float(cfg["verify_ms"]) <= 0:
            return 0.0
        with self._lock:
            if self._rotate_verify_left <= 0:
                return 0.0
            self._rotate_verify_left -= 1
        delay = float(cfg["verify_ms"]) / 1e3
        self._record("rotate", site, kind="slow_verify", delay_s=delay)
        return delay

    def maybe_fail_stage(self, method: str) -> None:
        """Sweep-stage injection point: raise for the first ``times``
        stages whose method name contains the configured substring."""
        if self.take_stage_fault(method):
            cfg = self.config.scope("stage")
            raise ChaosStageFault(
                f"chaos: injected stage fault on {method!r} "
                f"(fail={cfg['fail']!r})"
            )

    # ── daemon scope ──────────────────────────────────────────────────

    def daemon_kill_plan(self, names: Sequence[str]) -> tuple[str, ...]:
        """Which fleet daemons a ``daemon:kill=k,seed=..`` spec SIGKILLs
        (ISSUE 18): rank ``names`` by the pure ``(seed, "daemon",
        name)`` hash and take the ``k`` lowest — per name, not per
        process id or startup order, so the same fleet draws the same
        victims in every run and the invariant registry can recompute
        the plan from the spec alone. The plan never selects the WHOLE
        fleet (``k`` is capped at ``len(names) - 1``): with every
        backend dead, zero-silent-drops is unachievable by definition
        and the episode would prove nothing. Selection only — recording
        happens at :meth:`record_daemon_kill`, when the signal is
        actually sent."""
        cfg = self.config.scope("daemon")
        if cfg is None or int(cfg["kill"]) < 1 or not names:
            return ()
        k = min(int(cfg["kill"]), len(names) - 1)
        ranked = sorted(
            names, key=lambda n: _unit(int(cfg["seed"]), "daemon", str(n))
        )
        return tuple(ranked[:k])

    def record_daemon_kill(self, name: str) -> bool:
        """Emit the injection event/counter for a planned daemon kill at
        the moment SIGKILL is sent (the stage scope's plan/record
        split). Returns False — and records nothing — on a repeat for
        the same daemon: one SIGKILL per victim, ever."""
        with self._lock:
            if name in self._daemon_killed:
                return False
            self._daemon_killed.add(name)
        self._record("daemon", f"daemon/{name}", kind="kill")
        return True


def plan_faults(
    shard_fn: Callable[[int], object], fail_plan: dict[int, int]
) -> Callable[[int], object]:
    """Plan-based shard injection: ``fail_plan[i] = k`` makes shard
    ``i``'s first ``k`` attempts raise :class:`ChaosShardFault`. The
    exact-plan complement to the probabilistic ``shard`` scope (tests
    that need "shard 3 fails twice" rather than "20% of shards fail"),
    reporting through the same ``chaos_inject`` event channel."""
    remaining = dict(fail_plan)

    def chaotic(i: int):
        if remaining.get(i, 0) > 0:
            remaining[i] -= 1
            _record_injection("shard", f"plan/{i}", shard=i)
            raise ChaosShardFault(f"injected fault on shard {i}")
        return shard_fn(i)

    return chaotic


# ── process-wide arming ───────────────────────────────────────────────

_INJECTORS: dict[str, ChaosInjector] = {}
_ARM_LOCK = threading.Lock()


def active() -> ChaosInjector | None:
    """The armed injector for the current ``ATE_TPU_CHAOS`` value, or
    None when chaos is off. Injectors are cached per spec string so
    fault *budgets* are shared across injection points — one arming
    covers a whole run coherently. The cache lives until :func:`reset`:
    ``run_sweep`` resets at run start so each sweep gets full budgets
    (and so a malformed spec fails there, at config time); library
    callers driving injection points directly should do the same, or
    depleted budgets from an earlier run (including an A→B→A env
    flip back to an already-armed spec) silently inject nothing."""
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    inj = _INJECTORS.get(spec)
    if inj is None:
        with _ARM_LOCK:
            inj = _INJECTORS.get(spec)
            if inj is None:
                inj = _INJECTORS[spec] = ChaosInjector(parse_chaos(spec))
    return inj


def reset() -> None:
    """Drop all armed injectors (tests: fresh budgets per case)."""
    with _ARM_LOCK:
        _INJECTORS.clear()


@contextlib.contextmanager
def override(spec: str | None) -> Iterator[ChaosInjector | None]:
    """Test helper: arm ``spec`` (None/"" disarms) for the duration of
    the block with fresh budgets, restoring the env var after."""
    old = os.environ.get(ENV_VAR)
    reset()
    if spec:
        os.environ[ENV_VAR] = spec
    else:
        os.environ.pop(ENV_VAR, None)
    try:
        yield active()
    finally:
        if old is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = old
        reset()

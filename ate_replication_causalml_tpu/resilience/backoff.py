"""THE crc32-jittered exponential backoff (ISSUE 3's discipline, made
single-source in ISSUE 11 — no jax).

Three retry loops share the same schedule — the shard runner
(``parallel/retry.backoff_delay``), the serving client's typed-reject
retries (``serving/client.retry_backoff_delay``) and the retrain
supervisor (``serving/retrain.retrain_backoff_delay``). Each keeps its
own thin wrapper (the domain-specific jitter KEY is the contract their
tests pin), but the formula lives here exactly once: exponential in
the attempt, deterministic jitter in [0, 25%) from a crc32 of the
key, capped at ``cap_mult × base_s`` (and optionally an absolute
ceiling). A pure function of its arguments — retries de-herd across
sites with zero nondeterminism, and tests can assert the exact sleep
schedule.

This module must stay importable without jax: the client and the
retrain supervisor run on hosts that never initialize a backend
(``parallel/retry.py`` imports jax at module level, which is why the
formula cannot live there).
"""

from __future__ import annotations

import zlib

#: Backoff growth is capped at this multiple of the base delay — after
#: a few doublings a longer sleep stops buying recovery probability
#: and only burns the pool deadline / the client's patience.
BACKOFF_CAP_MULT = 8.0


def jittered_backoff_delay(
    key: str,
    attempt: int,
    base_s: float,
    cap_mult: float = BACKOFF_CAP_MULT,
    cap_s: float | None = None,
) -> float:
    """Seconds to sleep before retry ``attempt`` (1-based) of the work
    identified by ``key``."""
    if base_s <= 0.0:
        return 0.0
    raw = base_s * (2.0 ** (attempt - 1))
    jitter = zlib.crc32(key.encode()) / 2.0**32
    delay = min(raw * (1.0 + 0.25 * jitter), cap_mult * base_s)
    return delay if cap_s is None else min(delay, cap_s)

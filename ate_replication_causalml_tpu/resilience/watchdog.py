"""Heartbeat watchdog: liveness for every long-lived lane (ISSUE 14 —
no jax).

The repo already proves it never serves a *wrong* value; nothing before
this module proved it keeps serving at all. The failure class is the
hang: PR 4's notes document a live XLA collective-rendezvous deadlock,
the serving daemon's single dispatcher thread can wedge forever inside
one device call while ``/healthz`` answers 200, and a SweepEngine whose
mesh lane deadlocks just sits there with ready nodes and no progress.

The contract is deliberately minimal and jax-free:

* every long-lived lane — the daemon dispatcher, scheduler workers,
  the mesh lane, the retrain supervisor, the admin server — stamps a
  monotonic heartbeat into a :class:`HeartbeatRegistry` around every
  unit of work (and on every idle loop iteration, which is why the
  graftlint JGL012 rule bans unbounded blocking calls in those lanes:
  a lane that blocks forever outside its stamped sites is invisible);
* ONE :class:`Watchdog` evaluates heartbeat *ages* against per-lane
  bounds (``ATE_TPU_WATCHDOG_<LANE>_S``; <= 0 = unwatched) from an
  injectable clock, so detection-within-the-bound is provable without
  sleeping. A lane whose age crosses its bound starts a *stall
  episode*: ``watchdog_stalls_total{lane}`` increments once, a
  ``watchdog_stall`` event carries the age, and the ``on_stall``
  callback runs (the daemon flips to degraded — readyz 503, typed
  rejects — instead of queueing into a black hole). The next heartbeat
  ends the episode (``watchdog_recovered`` + ``on_recover``).

Injected stalls (the ``hang:`` chaos scope in :mod:`.chaos`) sleep at
the heartbeat-stamped sites, so tier-1 can assert planned == observed
stalls, detection within the bound, and recovery — deterministically.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry

#: env prefix for per-lane staleness bounds: ``ATE_TPU_WATCHDOG_<LANE>_S``
#: (lane upper-cased, ``/``/``-`` → ``_``). <= 0 disables the lane.
ENV_PREFIX = "ATE_TPU_WATCHDOG_"

#: default watchdog poll cadence (seconds); ``ATE_TPU_WATCHDOG_POLL_MS``
#: overrides. The poll only bounds detection LATENCY (age is measured
#: from the stamp, not the poll), so a coarse default is cheap and safe.
DEFAULT_POLL_S = 0.25


def _env_name(lane: str) -> str:
    return ENV_PREFIX + "".join(
        c if c.isalnum() else "_" for c in lane.upper()
    ) + "_S"


def lane_bound_s(lane: str, default: float = 0.0) -> float:
    """The staleness bound for ``lane``: ``ATE_TPU_WATCHDOG_<LANE>_S``
    if set, else ``default``. A malformed value raises at CONFIG time
    (the chaos-spec discipline: a watchdog that silently watches
    nothing is worse than none)."""
    raw = os.environ.get(_env_name(lane), "").strip()
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError as e:
        raise ValueError(
            f"{_env_name(lane)}={raw!r} is not a number of seconds"
        ) from e


def poll_s_from_env(default: float = DEFAULT_POLL_S) -> float:
    raw = os.environ.get(ENV_PREFIX + "POLL_MS", "").strip()
    if not raw:
        return float(default)
    try:
        return float(raw) / 1e3
    except ValueError as e:
        raise ValueError(
            f"{ENV_PREFIX}POLL_MS={raw!r} is not a number of ms"
        ) from e


class HeartbeatRegistry:
    """Last-heartbeat instants per lane. ``beat`` is the hot path —
    one lock acquisition and one float store — cheap enough to stamp
    per dispatch/loop iteration."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: dict[str, float] = {}

    def beat(self, lane: str) -> None:
        now = self._clock()
        with self._lock:
            self._beats[lane] = now

    def clear(self, lane: str) -> None:
        """Retire a lane (clean shutdown) — a stopped dispatcher is
        absent, not stalled."""
        with self._lock:
            self._beats.pop(lane, None)

    def lanes(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._beats))

    def age(self, lane: str, now: float | None = None) -> float | None:
        with self._lock:
            beat = self._beats.get(lane)
        if beat is None:
            return None
        return (self._clock() if now is None else now) - beat

    def ages(self, now: float | None = None) -> dict[str, float]:
        """Per-lane heartbeat ages — the ``/healthz`` body and the
        stall diagnostic's raw material."""
        now = self._clock() if now is None else now
        with self._lock:
            beats = dict(self._beats)
        return {lane: now - beat for lane, beat in sorted(beats.items())}


class Watchdog:
    """Evaluates one :class:`HeartbeatRegistry` against per-lane bounds.

    ``check()`` is the pure core (call it with an injected ``now`` in
    tests — no thread, no sleeping); ``start()`` runs it on a daemon
    thread every ``poll_s`` (the Event wait is bounded — JGL012 applies
    to the watchdog itself). Callbacks run OUTSIDE the internal lock
    and fire once per episode."""

    def __init__(
        self,
        heartbeats: HeartbeatRegistry,
        bounds: dict[str, float],
        *,
        clock: Callable[[], float] = time.monotonic,
        poll_s: float | None = None,
        on_stall: Callable[[str, float], None] | None = None,
        on_recover: Callable[[str, float], None] | None = None,
    ):
        self.heartbeats = heartbeats
        #: lane -> staleness bound (seconds); <= 0 means unwatched.
        self.bounds = {k: float(v) for k, v in bounds.items()}
        self._clock = clock
        self.poll_s = poll_s_from_env() if poll_s is None else float(poll_s)
        self._on_stall = on_stall
        self._on_recover = on_recover
        self._lock = threading.Lock()
        self._stalled: dict[str, float] = {}  # lane -> stall-start mono
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stalls = _registry.counter(
            "watchdog_stalls_total",
            "watchdog-detected lane stall episodes",
        )
        self._stalls.inc(0)

    # ── pure evaluation ──────────────────────────────────────────────

    def bound_for(self, lane: str) -> float:
        """Exact lane bound, else the bound of the lane's first
        ``/``-segment (``worker/sweep-worker-3`` → ``worker``), else 0
        (unwatched)."""
        if lane in self.bounds:
            return self.bounds[lane]
        return self.bounds.get(lane.split("/", 1)[0], 0.0)

    def check(self, now: float | None = None) -> list[str]:
        """One evaluation pass; returns the lanes that NEWLY stalled.
        Also ends episodes whose lane has beaten since (recovery)."""
        now = self._clock() if now is None else now
        ages = self.heartbeats.ages(now)
        newly: list[tuple[str, float]] = []
        recovered: list[tuple[str, float]] = []
        with self._lock:
            for lane, age in ages.items():
                bound = self.bound_for(lane)
                stalled_since = self._stalled.get(lane)
                if bound > 0.0 and age > bound:
                    if stalled_since is None:
                        self._stalled[lane] = now
                        newly.append((lane, age))
                elif stalled_since is not None:
                    del self._stalled[lane]
                    recovered.append((lane, now - stalled_since))
            # A cleared (retired) lane ends its episode silently.
            for lane in list(self._stalled):
                if lane not in ages:
                    del self._stalled[lane]
        for lane, age in newly:
            self._stalls.inc(1, lane=lane)
            _events.emit(
                "watchdog_stall", status="error", lane=lane,
                age_s=round(age, 6), bound_s=self.bound_for(lane),
            )
            if self._on_stall is not None:
                self._on_stall(lane, age)
        for lane, stalled_s in recovered:
            _events.emit(
                "watchdog_recovered", status="ok", lane=lane,
                stalled_s=round(stalled_s, 6),
            )
            if self._on_recover is not None:
                self._on_recover(lane, stalled_s)
        return [lane for lane, _ in newly]

    def stalled(self) -> tuple[str, ...]:
        """Lanes currently inside a stall episode."""
        with self._lock:
            return tuple(sorted(self._stalled))

    def is_stalled(self, lane: str) -> bool:
        with self._lock:
            return lane in self._stalled

    # ── background thread ────────────────────────────────────────────

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            t = threading.Thread(
                target=self._run, name="watchdog", daemon=True
            )
            self._thread = t
        t.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self, timeout: float | None = 5.0) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

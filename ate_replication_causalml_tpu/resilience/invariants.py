"""System-wide invariant registry (ISSUE 15, tentpole part 2).

Every guarantee the repo has proven one scope at a time — computed rows
bit-identical to a fault-free reference (PR 3), journal integrity under
torn appends (PR 3), the serving report's silent-drop reconciliation
(PR 11), the steady-state zero-compile window (PR 6), typed-reject
accounting (PR 7), drain-loses-nothing (PR 14), degrade-exactly-where-
faulted (PR 3/4) — promoted to named, reusable :class:`Invariant`
objects with ONE contract: an invariant is a pure function of a run's
**committed artifacts** (journals, ``answers.npz``/``refs.npz``,
``serving_report.json``, ``metrics.json``, the workload's
``campaign_summary.json``) for a chaos episode and its fault-free
reference of the same seed. Nothing here re-runs anything or reads
process state — a verdict can be recomputed from the artifact
directories alone, which is what makes ``campaign_report.json``
reproducible and the failure shrinker's re-runs comparable.

Verdicts are ``pass`` / ``fail`` / ``skip`` (not applicable to the
workload). Pass-verdict details are deliberately DETERMINISTIC —
no wall-clock, no load-dependent counts — so a campaign report is
byte-identical across reruns of the same seed; failure details carry
whatever diagnosis needs.

jax-free (numpy only, for the committed answer arrays) so the registry
is importable from the validator and the CLI without a backend.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Callable

import numpy as np

from ate_replication_causalml_tpu.observability import stathealth as _stathealth
from ate_replication_causalml_tpu.resilience import chaos as _chaos

#: the journal basename per journaled workload.
SUMMARY_BASENAME = "campaign_summary.json"

#: statistical payload compared for journal bit-identity; ``seconds``
#: and attempt bookkeeping are run-local and deliberately excluded.
_PAYLOAD_KEYS = ("ate", "se", "lower_ci", "upper_ci", "tau_true")


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One invariant's structured outcome for one episode."""

    invariant: str
    verdict: str                    # "pass" | "fail" | "skip"
    detail: str = ""
    data: dict = dataclasses.field(default_factory=dict)

    def as_json(self) -> dict:
        return {
            "invariant": self.invariant,
            "verdict": self.verdict,
            "detail": self.detail,
            "data": self.data,
        }


class RunArtifacts:
    """Read-side handle on one committed run directory (an episode or
    its reference): the workload summary plus lazy, cached parses of
    the journal, the served-answer arrays and the serving report."""

    def __init__(self, outdir: str):
        self.outdir = outdir
        with open(os.path.join(outdir, SUMMARY_BASENAME)) as f:
            self.summary = json.load(f)
        self.workload = self.summary["workload"]
        self._journal = None
        self._answers = None
        self._refs = None

    def path(self, name: str) -> str:
        return os.path.join(self.outdir, name)

    def has(self, name: str) -> bool:
        return os.path.exists(self.path(name))

    def load_json(self, name: str) -> dict | None:
        if not self.has(name):
            return None
        with open(self.path(name)) as f:
            return json.load(f)

    def journal(self) -> tuple[dict[str, dict], int]:
        """``(rows keyed by method, torn line count)`` — the same
        torn-tolerant parse the resume path applies."""
        if self._journal is None:
            rows: dict[str, dict] = {}
            torn = 0
            name = self.summary.get("journal")
            if name and self.has(name):
                with open(self.path(name)) as f:
                    for line in f:
                        if not line.strip():
                            continue
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            torn += 1
                            continue
                        if rec.get("method") != "__config__":
                            rows[rec["method"]] = rec
            self._journal = (rows, torn)
        return self._journal

    def answers(self):
        if self._answers is None and self.has("answers.npz"):
            self._answers = np.load(self.path("answers.npz"))
        return self._answers

    def refs(self):
        if self._refs is None and self.has("refs.npz"):
            self._refs = np.load(self.path("refs.npz"))
        return self._refs

    def faults(self, scope: str | None = None) -> list[dict]:
        """Observed chaos injections the workload recorded (the summary
        mirrors the run's ``chaos_inject`` events for the DETERMINISTIC
        scopes; ``hang:`` stalls are deliberately absent — a stall
        changes no answer, and the daemon's stall sites are
        batch-composition-dependent)."""
        out = self.summary.get("faults", [])
        if scope is not None:
            out = [f for f in out if f.get("scope") == scope]
        return out


def _values_equal(a, b) -> bool:
    """Bit-equality on the JSON round-trip with NaN == NaN (the no-SE
    LASSO rows serialize se as null; json round-trips floats via repr
    exactly, so == IS bit-identity here)."""
    if a is None and b is None:
        return True
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
    return a == b


def _payload(rec: dict) -> dict:
    return {k: rec.get(k) for k in _PAYLOAD_KEYS if k in rec}


# ── registry ──────────────────────────────────────────────────────────


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One named guarantee. ``workloads=None`` applies everywhere;
    otherwise the listed workload names (anything else → ``skip``)."""

    name: str
    description: str
    fn: Callable[[RunArtifacts, RunArtifacts], Verdict]
    workloads: tuple[str, ...] | None = None


REGISTRY: dict[str, Invariant] = {}


def register(name: str, description: str,
             workloads: tuple[str, ...] | None = None):
    def deco(fn):
        if name in REGISTRY:
            raise ValueError(f"duplicate invariant {name!r}")
        REGISTRY[name] = Invariant(name, description, fn, workloads)
        return fn

    return deco


def registered_names() -> tuple[str, ...]:
    """Declaration order — the canonical verdict order in
    ``campaign_report.json`` (the validator checks the set)."""
    return tuple(REGISTRY)


def evaluate_all(episode: RunArtifacts,
                 reference: RunArtifacts) -> list[Verdict]:
    """Every registered invariant, in declaration order — skips are
    explicit verdicts, so a campaign report always carries the FULL
    registry per episode (the validator's "every verdict present"
    check)."""
    out: list[Verdict] = []
    for inv in REGISTRY.values():
        if inv.workloads is not None and episode.workload not in inv.workloads:
            out.append(Verdict(inv.name, "skip",
                               f"not applicable to {episode.workload}"))
            continue
        try:
            out.append(inv.fn(episode, reference))
        except Exception as e:  # noqa: BLE001 — an invariant that cannot
            # be evaluated (missing artifact, torn file) is a FAILURE of
            # the system's artifact contract, not a crash of the judge.
            out.append(Verdict(
                inv.name, "fail",
                f"evaluation error: {type(e).__name__}: {e}",
            ))
    return out


# ── journaled workloads (sweep / matrix) ──────────────────────────────


_JOURNALED = ("sweep", "matrix")
_SERVING = ("serving", "rotation")


@register(
    "bit_identity",
    "every computed row / served answer is bit-identical to the "
    "fault-free reference of the same seed",
)
def _bit_identity(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    if ep.workload in _JOURNALED:
        rows, _ = ep.journal()
        ref_rows, ref_torn = ref.journal()
        mismatched = []
        compared = 0
        for key, rec in sorted(rows.items()):
            if rec.get("status", "ok") != "ok":
                continue  # degraded rows are the degrade invariant's job
            ref_rec = ref_rows.get(key)
            if ref_rec is None:
                # The reference lost this row only to its own torn
                # append (references run fault-free, so only a crashed
                # reference could); treat as incomparable.
                continue
            compared += 1
            if _payload(rec) != _payload(ref_rec) and not all(
                _values_equal(rec.get(k), ref_rec.get(k))
                for k in _PAYLOAD_KEYS
            ):
                mismatched.append(key)
        if mismatched:
            return Verdict(
                "bit_identity", "fail",
                f"{len(mismatched)} computed row(s) diverge from the "
                f"fault-free reference",
                {"mismatched": mismatched, "compared": compared},
            )
        if compared == 0:
            return Verdict("bit_identity", "fail",
                           "no comparable computed rows")
        return Verdict("bit_identity", "pass",
                       f"{compared} computed rows bit-identical",
                       {"compared": compared})
    # Serving: every served answer equals the REFERENCE run's offline
    # per-version prediction for the rows and model version this
    # request actually bound.
    ans = ep.answers()
    refs = ref.refs()
    if ans is None or refs is None:
        return Verdict("bit_identity", "fail",
                       "answers.npz / reference refs.npz missing")
    rows = ans["rows"]
    versions = ans["versions"]
    cate, var = ans["cate"], ans["var"]
    off = 0
    bad = []
    for i in range(len(rows)):
        n = int(rows[i])
        v = int(versions[i])
        rc = refs[f"cate_v{v}"][off:off + n]
        rv = refs[f"var_v{v}"][off:off + n]
        if not (np.array_equal(cate[off:off + n], rc)
                and np.array_equal(var[off:off + n], rv)):
            bad.append(i)
        off += n
    if bad:
        return Verdict(
            "bit_identity", "fail",
            f"{len(bad)} served answer(s) diverge from the reference's "
            "offline prediction at their bound version",
            {"mismatched_indices": bad, "compared": int(len(rows))},
        )
    return Verdict("bit_identity", "pass",
                   f"{int(len(rows))} served answers bit-identical",
                   {"compared": int(len(rows))})


@register(
    "journal_integrity",
    "the journal parses after torn appends: every expected row is "
    "present or accounted to a recorded torn line, the config header "
    "survived, and torn lines == recorded fs injections",
    workloads=_JOURNALED,
)
def _journal_integrity(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    rows, torn = ep.journal()
    expected = list(ep.summary.get("expected_rows", []))
    jpath = ep.path(ep.summary["journal"])
    with open(jpath) as f:
        first = f.readline()
    try:
        header = json.loads(first)
        header_ok = header.get("method") == "__config__" and bool(
            header.get("fingerprint")
        )
    except json.JSONDecodeError:
        header_ok = False
    torn_recorded = len(ep.faults("fs"))
    missing = [k for k in expected if k not in rows]
    problems = []
    if not header_ok:
        problems.append("config header missing or torn")
    if torn != torn_recorded:
        problems.append(
            f"{torn} torn line(s) on disk vs {torn_recorded} recorded "
            "fs injections"
        )
    if len(missing) != torn:
        problems.append(
            f"{len(missing)} expected row(s) absent vs {torn} torn "
            f"line(s): {missing[:8]}"
        )
    if problems:
        return Verdict("journal_integrity", "fail", "; ".join(problems),
                       {"torn": torn, "missing": missing})
    return Verdict(
        "journal_integrity", "pass",
        f"{len(rows)} rows parsed, {torn} torn line(s) all accounted",
        {"rows": len(rows), "torn": torn},
    )


@register(
    "degraded_where_faulted",
    "degraded rows / faulted requests sit exactly where the chaos "
    "harness recorded an injection — no silent extra damage, no "
    "unrecorded fault",
)
def _degraded_where_faulted(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    if ep.workload in _JOURNALED:
        rows, _ = ep.journal()
        failed = {k for k, r in rows.items()
                  if r.get("status", "ok") != "ok"}
        sites = {f["site"] for f in ep.faults("stage")}
        if ep.workload == "sweep":
            expected_failed = set(sites)
        else:
            batches = ep.summary.get("batches", {})
            expected_failed = set()
            for site in sites:
                expected_failed |= set(batches.get(site, []))
        # Only rows the journal still carries are judged here — a row
        # LOST to a torn append is journal_integrity's accounting, not
        # an unexplained degradation.
        expected_failed &= set(rows)
        if failed != expected_failed:
            return Verdict(
                "degraded_where_faulted", "fail",
                "failed rows do not match recorded stage faults",
                {"failed": sorted(failed),
                 "expected": sorted(expected_failed)},
            )
        return Verdict(
            "degraded_where_faulted", "pass",
            f"{len(failed)} degraded row(s), all at recorded fault sites",
            {"failed": sorted(failed)},
        )
    # Serving: the serve-scope fault set must equal the pure-hash plan
    # over the replayed request ids, and every rotate-kind fault must
    # be consistent with the recorded rotation outcome.
    spec = ep.summary.get("chaos_spec", "")
    cfg = _chaos.parse_chaos(spec) if spec else None
    serve = cfg.scope("serve") if cfg else None
    ids = ep.summary.get("request_ids", [])
    planned = set()
    if serve and float(serve["p"]) > 0:
        planned = {
            rid for rid in ids
            if _chaos._unit(int(serve["seed"]), "serve", rid)
            < float(serve["p"])
        }
    observed = {
        f["site"].removeprefix("req/") for f in ep.faults("serve")
    }
    problems = []
    if observed != planned:
        problems.append(
            f"serve faults observed != planned "
            f"({sorted(observed ^ planned)[:8]})"
        )
    rotate_kinds = {f.get("kind") for f in ep.faults("rotate")}
    status = (ep.summary.get("serving") or {}).get("rotation_status")
    # corrupt AND mid_swap both end in an atomic refusal (the last good
    # model keeps serving); slow_verify and a retried retrain-fit fault
    # still rotate. A refusal with no recorded refusing fault — or a
    # refusing fault that somehow rotated — is exactly the silent
    # inconsistency this invariant exists to catch.
    refusing = {"corrupt", "mid_swap"} & rotate_kinds
    if refusing and status != "refused":
        problems.append(
            f"{sorted(refusing)} fault recorded but "
            f"rotation_status={status!r}"
        )
    if status == "refused" and not refusing:
        problems.append("rotation refused without a recorded "
                        "corrupt/mid_swap fault")
    if problems:
        return Verdict("degraded_where_faulted", "fail",
                       "; ".join(problems),
                       {"planned": sorted(planned),
                        "observed": sorted(observed)})
    return Verdict(
        "degraded_where_faulted", "pass",
        f"{len(planned)} planned serve fault(s) all observed; rotation "
        "outcome consistent",
        {"planned_serve_faults": len(planned)},
    )


# ── serving workloads ─────────────────────────────────────────────────


@register(
    "serving_reconciliation",
    "the serving report's request reconciliation closes: "
    "silent_drops == 0",
    workloads=_SERVING,
)
def _serving_reconciliation(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    report = ep.load_json("serving_report.json")
    if report is None:
        return Verdict("serving_reconciliation", "fail",
                       "serving_report.json missing")
    rec = report.get("reconciliation") or {}
    drops = rec.get("silent_drops")
    if drops != 0:
        return Verdict("serving_reconciliation", "fail",
                       f"silent_drops={drops!r}", {"reconciliation": rec})
    return Verdict("serving_reconciliation", "pass", "silent_drops == 0")


@register(
    "zero_compile_window",
    "the serving window recorded zero jax compile/trace events "
    "(the steady state provably never compiles)",
    workloads=_SERVING,
)
def _zero_compile_window(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    delta = (ep.summary.get("serving") or {}).get("compile_events_in_window")
    if delta != 0:
        return Verdict("zero_compile_window", "fail",
                       f"compile events in window: {delta!r}")
    return Verdict("zero_compile_window", "pass",
                   "0 compile events in the serving window")


@register(
    "typed_rejects_accounted",
    "every rejection is typed and accounted: the serving report's "
    "reject timeline count == Σ by-reason == the metered "
    "serving_rejected_total delta",
    workloads=_SERVING,
)
def _typed_rejects_accounted(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    report = ep.load_json("serving_report.json")
    if report is None:
        return Verdict("typed_rejects_accounted", "fail",
                       "serving_report.json missing")
    rej = report.get("rejects") or {}
    count = rej.get("count", 0)
    by_reason = rej.get("by_reason") or {}
    metered = (ep.summary.get("serving") or {}).get(
        "rejected_metered_delta", 0
    )
    if not (count == sum(by_reason.values()) == metered):
        return Verdict(
            "typed_rejects_accounted", "fail",
            "reject accounting does not close",
            {"report_count": count, "by_reason_sum": sum(by_reason.values()),
             "metered_delta": metered},
        )
    return Verdict("typed_rejects_accounted", "pass",
                   "reject accounting closes (timeline == Σ reasons == "
                   "metered)")


@register(
    "drain_no_loss",
    "graceful drain completed with zero in-flight work lost: every "
    "replayed request was served before the drain reported 'drained'",
    workloads=_SERVING,
)
def _drain_no_loss(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    serving = ep.summary.get("serving") or {}
    outcome = serving.get("drain_outcome")
    served = serving.get("served")
    n = ep.summary.get("n_requests")
    if outcome != "drained":
        return Verdict("drain_no_loss", "fail",
                       f"drain outcome {outcome!r}")
    if served != n:
        return Verdict("drain_no_loss", "fail",
                       f"served {served!r} of {n!r} replayed requests",
                       {"served": served, "requests": n})
    return Verdict("drain_no_loss", "pass",
                   "drained with every replayed request served")


@register(
    "stat_drift",
    "the exported statistical-health report is a pure function of its "
    "embedded sketch state (recompute == artifact, bit-for-bit), its "
    "sketch mass is conserved, and drift series values are in range",
    workloads=_SERVING,
)
def _stat_drift(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    report = ep.load_json(_stathealth.STAT_HEALTH_BASENAME)
    if report is None:
        # Pre-stathealth artifact directories (and workloads that never
        # dumped) simply have nothing to judge — explicit skip, so the
        # campaign report's verdict set stays complete.
        return Verdict("stat_drift", "skip",
                       f"{_stathealth.STAT_HEALTH_BASENAME} not exported")
    recomputed = _stathealth.stat_health_report(report["state"])
    if recomputed != report:
        return Verdict(
            "stat_drift", "fail",
            "stat_health report is not the pure function of its own "
            "embedded state (recompute diverges from the artifact)",
        )
    problems = []
    for model, mstate in (report["state"].get("models") or {}).items():
        for ch, cstate in (mstate.get("channels") or {}).items():
            where = f"{model}/{ch}"
            total = _stathealth_cells(cstate.get("total"))
            acc = _stathealth_cells(cstate.get("current", {}).get("sketch"))
            if total is None or acc is None:
                problems.append(f"{where}: malformed sketch")
                continue
            for w in cstate.get("windows") or ():
                cells = _stathealth_cells(w.get("sketch"))
                if cells is None:
                    problems.append(f"{where}: malformed window sketch")
                    break
                acc = [a + c for a, c in zip(acc, cells)]
            else:
                if acc != total:
                    problems.append(f"{where}: sketch mass not conserved "
                                    "(current + windows != total)")
            for entry in cstate.get("series") or ():
                psi_v, ks_v = entry.get("psi"), entry.get("ks")
                if psi_v is not None and psi_v < 0:
                    problems.append(f"{where}: negative PSI {psi_v}")
                if ks_v is not None and not 0.0 <= ks_v <= 1.0:
                    problems.append(f"{where}: KS {ks_v} outside [0, 1]")
    if problems:
        return Verdict("stat_drift", "fail", "; ".join(problems[:4]),
                       {"problems": problems})
    models = sorted((report["state"].get("models") or {}))
    return Verdict(
        "stat_drift", "pass",
        "stat_health report reproduces bit-for-bit from its state; "
        "sketch mass conserved and drift values in range",
        {"models": models},
    )


def _stathealth_cells(sketch: dict | None) -> list[int] | None:
    """Flat integer cell list of a serialized sketch, or ``None`` when
    the dict is not a well-formed fixed-bin sketch."""
    if not isinstance(sketch, dict) or sketch.get("kind") != "fixed_bin":
        return None
    counts = sketch.get("counts")
    tails = [sketch.get("underflow"), sketch.get("overflow"),
             sketch.get("nan")]
    if not isinstance(counts, list):
        return None
    cells = list(counts) + tails
    if any(not isinstance(c, int) or c < 0 for c in cells):
        return None
    return cells


@register(
    "fleet_failover",
    "a SIGKILLed backend costs nothing observable: every replayed "
    "request was served (zero silent drops through failover + client "
    "resubmit), each planned kill was recorded exactly once, and the "
    "rolling rotation visited every backend exactly once with zero "
    "downtime and zero post-swap compiles",
    workloads=("fleet",),
)
def _fleet_failover(ep: RunArtifacts, ref: RunArtifacts) -> Verdict:
    fleet = ep.summary.get("fleet")
    if not isinstance(fleet, dict):
        return Verdict("fleet_failover", "fail",
                       "summary carries no fleet section")
    problems = []
    n = ep.summary.get("n_requests")
    served = fleet.get("served")
    if served != n:
        problems.append(f"served {served!r} of {n!r} replayed requests")
    killed = sorted(fleet.get("killed") or [])
    recorded = sorted(
        f["site"].split("/", 1)[1] for f in ep.faults("daemon")
    )
    if killed != recorded:
        problems.append(
            f"killed backends {killed} != recorded daemon injections "
            f"{recorded}"
        )
    backends = sorted(fleet.get("backends") or [])
    if killed and set(killed) >= set(backends):
        problems.append("the whole fleet was killed — nothing proven")
    rotation = fleet.get("rotation") or {}
    statuses = rotation.get("statuses") or {}
    if sorted(statuses) != backends:
        problems.append(
            f"rotation visited {sorted(statuses)}, fleet is {backends}"
        )
    bad = {b: s for b, s in statuses.items() if s != "rotated"}
    if bad:
        problems.append(f"rotation statuses not all 'rotated': {bad}")
    if rotation.get("zero_downtime") is not True:
        problems.append("rotation reported a downtime window")
    compiles = rotation.get("post_swap_compiles") or {}
    hot = {b: c for b, c in compiles.items() if c}
    if hot:
        problems.append(f"post-swap compiles observed: {hot}")
    drains = fleet.get("survivor_exit_codes") or []
    if any(rc != 0 for rc in drains):
        problems.append(f"survivor drain exit codes {drains} not all 0")
    # The reference runs the SAME workload fault-free — its fleet must
    # have no kills at all, or the chaos plumbing leaked into it.
    ref_fleet = ref.summary.get("fleet") or {}
    if ref_fleet.get("killed"):
        problems.append(
            f"fault-free reference recorded kills: {ref_fleet['killed']}"
        )
    if problems:
        return Verdict("fleet_failover", "fail", "; ".join(problems),
                       {"killed": killed, "statuses": statuses})
    return Verdict(
        "fleet_failover", "pass",
        f"{served} requests served across {len(backends)} backends with "
        f"{len(killed)} kill(s); rotation green on every backend",
        {"backends": backends, "killed": killed},
    )

"""Chaos campaign engine (ISSUE 15, tentpole).

Seven chaos scopes exist (``shard``/``fs``/``device``/``stage`` PR 3,
``serve`` PR 6, ``rotate`` PR 11, ``hang`` PR 14) but until now each
was only ever armed in isolation, proving one hand-picked invariant in
its own test file. Real incidents are composed — a rotation lands
during a dispatcher stall while a shard retries and a journal line
tears — and this module searches that product space:

* **Campaign generator** — from one root seed, deterministically
  compose multi-scope ``ATE_TPU_CHAOS`` specs (seeded parameters drawn
  from declared per-scope ranges) crossed with the four real workloads
  (quick sweep, scenario matrix, serving daemon + seeded loadgen-style
  replay, fleet rotation under load); a fifth, subprocess-heavy
  ``fleet`` workload (ISSUE 18 — three daemons behind the serving
  router, judged against ``daemon:`` SIGKILLs) is registered but opt-in
  only, never part of the default plan. Every draw is a pure sha256 hash
  of ``(root_seed, path)`` — no global RNG — so the same seed plans
  the identical campaign forever.
* **Reference discipline** — every episode runs against a fault-free
  reference of the SAME workload seed (cached per ``(workload,
  seed)``), and the :mod:`~.invariants` registry judges the episode
  from the two runs' committed artifacts alone.
* **Deterministic shrinker** — on any invariant violation, delta-debug
  the episode's composed fault set (chaos plans are pure functions of
  seed, so re-runs are exact) down to a minimal failing subset and
  emit a one-line repro (``ATE_TPU_CHAOS=<minimal spec>`` + workload +
  seed) as the report's headline; the minimal spec is re-run once more
  to confirm it re-fails.

``campaign_report.json`` is byte-identical for the same root seed: it
carries no wall-clock and no load-dependent numbers (those live in the
per-episode artifact dirs and the bench record). Schema validated by
``scripts/check_metrics_schema.py``.

Module top is jax-free; workload runners import jax lazily.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Sequence

import numpy as np

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience import invariants as inv

ENV_SEED = "ATE_TPU_CAMPAIGN_SEED"
ENV_EPISODES = "ATE_TPU_CAMPAIGN_EPISODES"
ENV_REQUESTS = "ATE_TPU_CAMPAIGN_REQUESTS"
ENV_REPS = "ATE_TPU_CAMPAIGN_REPS"

SCHEMA_VERSION = 1

#: scopes whose observed injection SITES are load-dependent (the
#: daemon's hang sites are batch-composition ids): excluded from the
#: summary fault list so reports and invariants stay deterministic. A
#: stall changes no answer, so nothing is lost by not judging it.
NONDETERMINISTIC_SCOPES = ("hang",)

#: canonical scope order inside a composed spec (stable spec strings).
_SCOPE_ORDER = ("shard", "fs", "device", "stage", "serve", "hang",
                "rotate", "tamper", "daemon")


def _env_int(name: str, default: int) -> int:
    """Config-time raise on a bad knob (the repo-wide env discipline)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a positive integer") \
            from None
    if value < 1:
        raise ValueError(f"{name}={value}: expected a positive integer")
    return value


def default_seed() -> int:
    """``ATE_TPU_CAMPAIGN_SEED`` (0 allowed — it is a seed, not a
    budget), validated at config time."""
    raw = os.environ.get(ENV_SEED, "").strip()
    if not raw:
        return 0
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{ENV_SEED}={raw!r}: expected an integer"
        ) from None
    if value < 0:
        raise ValueError(f"{ENV_SEED}={value}: expected >= 0")
    return value


# ── seeded pure draws ─────────────────────────────────────────────────


class Draw:
    """Stateless seeded draw source: every value is the pure hash
    ``_unit(root, "campaign", *path, name)`` — independent of call
    order, so adding a draw can never reshuffle existing ones."""

    def __init__(self, root: int, *path: object):
        self.root = int(root)
        self.path = tuple(str(p) for p in path)

    def sub(self, *path: object) -> "Draw":
        return Draw(self.root, *self.path, *path)

    def unit(self, name: str, lo: float = 0.0, hi: float = 1.0) -> float:
        u = chaos._unit(self.root, "campaign", *self.path, name)
        return lo + u * (hi - lo)

    def int(self, name: str, lo: int = 1, hi: int = 999_983) -> int:
        u = chaos._unit(self.root, "campaign", *self.path, name)
        return lo + min(int(u * (hi - lo + 1)), hi - lo)

    def choice(self, name: str, options: Sequence):
        return options[self.int(name, 0, len(options) - 1)]


# ── episode budget (scale) ────────────────────────────────────────────


@dataclasses.dataclass(frozen=True)
class CampaignScale:
    """Episode budget knobs. ``micro`` matches the tier-1 rig's MICRO
    sweep shapes (tests/test_pipeline_driver.py) so in-suite campaigns
    share warm executables; ``quick`` is the @slow/bench heavy tier."""

    name: str
    sweep_n_obs: int
    sweep_pool: int
    sweep_trees: int
    sweep_depth: int
    sweep_balance_iters: int
    matrix_n: int
    matrix_reps: int
    matrix_width: int
    serve_requests: int
    serve_rate_hz: float


MICRO = CampaignScale(
    name="micro", sweep_n_obs=1200, sweep_pool=3000, sweep_trees=16,
    sweep_depth=4, sweep_balance_iters=600, matrix_n=128, matrix_reps=8,
    matrix_width=4, serve_requests=24, serve_rate_hz=800.0,
)
QUICK = CampaignScale(
    name="quick", sweep_n_obs=2000, sweep_pool=4000, sweep_trees=32,
    sweep_depth=5, sweep_balance_iters=1200, matrix_n=256,
    matrix_reps=24, matrix_width=8, serve_requests=80,
    serve_rate_hz=1500.0,
)
SCALES = {s.name: s for s in (MICRO, QUICK)}


def resolve_scale(scale: "str | CampaignScale") -> CampaignScale:
    """Named scale + env budget overrides, validated at config time."""
    if isinstance(scale, CampaignScale):
        base = scale
    else:
        if scale not in SCALES:
            raise ValueError(
                f"unknown campaign scale {scale!r} (known: "
                f"{sorted(SCALES)})"
            )
        base = SCALES[scale]
    return dataclasses.replace(
        base,
        matrix_reps=_env_int(ENV_REPS, base.matrix_reps),
        serve_requests=_env_int(ENV_REQUESTS, base.serve_requests),
    )


# ── workloads & per-scope parameter ranges ────────────────────────────


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """One campaign workload: its runner and the chaos scopes that are
    meaningful against it (the generator only composes these)."""

    name: str
    scopes: tuple[str, ...]
    run: Callable  # (outdir, seed, scale) -> None; commits artifacts


def draw_atom(workload: str, scope: str, d: Draw) -> str:
    """One seeded scope fragment from the scope's declared parameter
    range for this workload — the campaign's per-scope range table."""
    if scope == "shard":
        return (f"shard:p={d.unit('p', 0.15, 0.45):.3f},"
                f"seed={d.int('seed')},times={d.int('times', 1, 2)}")
    if scope == "fs":
        return f"fs:torn_write,times={d.int('times', 1, 2)}"
    if scope == "stage":
        fail = (
            d.choice("fail", ("residual_balancing",
                              "Propensity_Weighting", "Usual"))
            if workload == "sweep"
            else d.choice("fail", ("naive#b0", "ipw_logit#b0"))
        )
        return f"stage:fail={fail},times=1"
    if scope == "serve":
        return (f"serve:p={d.unit('p', 0.08, 0.25):.3f},"
                f"seed={d.int('seed')},times={d.int('times', 1, 2)}")
    if scope == "hang":
        lane = "dispatch" if workload in ("serving", "rotation") else "worker"
        return (f"hang:scope={lane},ms={d.unit('ms', 10, 50):.1f},"
                f"p={d.unit('p', 0.2, 0.7):.3f},seed={d.int('seed')},"
                f"times=1")
    if scope == "rotate":
        kind = d.choice("kind", ("corrupt", "mid_swap", "verify_ms"))
        if kind == "verify_ms":
            return f"rotate:verify_ms={d.unit('ms', 30, 90):.0f},times=1"
        return f"rotate:{kind},times=1"
    if scope == "daemon":
        # One SIGKILLed backend per episode (ISSUE 18): the victim is
        # the pure (seed, name) hash, so the invariant registry can
        # recompute the plan from the spec alone.
        return f"daemon:kill=1,seed={d.int('seed')}"
    raise ValueError(f"no campaign range declared for scope {scope!r}")


@dataclasses.dataclass(frozen=True)
class Episode:
    """One planned chaos episode: a workload seed plus the composed
    scope atoms. Everything downstream (the spec string, the shrinker's
    subsets, the repro line) derives from these fields alone."""

    index: int
    workload: str
    seed: int
    atoms: tuple[tuple[str, str], ...]  # (scope, spec fragment)

    @property
    def spec(self) -> str:
        return compose(self.atoms)


def compose(atoms: Sequence[tuple[str, str]]) -> str:
    return ";".join(spec for _, spec in atoms)


def plan_campaign(
    root_seed: int, n_episodes: int,
    workloads: Sequence[str] | None = None,
) -> list[Episode]:
    """The deterministic plan: workload round-robin, a drawn subset of
    ≥2 applicable scopes per episode, seeded params per scope. Pure
    function of ``(root_seed, n_episodes, workloads)``."""
    names = tuple(workloads) if workloads else WORKLOAD_ORDER
    for w in names:
        if w not in WORKLOADS:
            raise ValueError(
                f"unknown campaign workload {w!r} (known: "
                f"{sorted(WORKLOADS)})"
            )
    episodes: list[Episode] = []
    for i in range(n_episodes):
        w = names[i % len(names)]
        d = Draw(root_seed, "ep", i)
        scopes = WORKLOADS[w].scopes
        k = d.int("nscopes", min(2, len(scopes)), len(scopes))
        ranked = sorted(scopes, key=lambda s: d.unit(f"pick.{s}"))
        chosen = sorted(ranked[:k], key=_SCOPE_ORDER.index)
        atoms = tuple(
            (s, draw_atom(w, s, d.sub("scope", s))) for s in chosen
        )
        episodes.append(Episode(i, w, d.int("seed", 1, 1_000_000), atoms))
    return episodes


# ── fault-window capture ──────────────────────────────────────────────


class _FaultWindow:
    """Collects the ``chaos_inject`` events a workload run emitted (by
    monotonic window over the process-global ring), excluding the
    nondeterministic scopes — the summary's committed fault record the
    invariants judge against."""

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        return False

    def collect(self) -> list[dict]:
        out = []
        for r in obs.EVENTS.records():
            if r.get("name") != "chaos_inject":
                continue
            if r.get("start_mono_s", 0.0) < self.t0:
                continue
            at = r.get("attrs", {})
            if at.get("scope") in NONDETERMINISTIC_SCOPES:
                continue
            f = {"scope": at.get("scope"), "site": at.get("site")}
            if "kind" in at:
                f["kind"] = at["kind"]
            out.append(f)
        return sorted(
            out, key=lambda f: (f["scope"], f["site"], f.get("kind", ""))
        )


def _write_summary(outdir: str, summary: dict) -> None:
    summary = dict(summary)
    summary["chaos_spec"] = os.environ.get(chaos.ENV_VAR, "").strip()
    obs.atomic_write_json(
        os.path.join(outdir, inv.SUMMARY_BASENAME), summary
    )


# ── the four workload runners ─────────────────────────────────────────


def _silent(_msg: str) -> None:
    pass


def _run_sweep_workload(outdir: str, seed: int, scale: CampaignScale):
    from ate_replication_causalml_tpu.data.pipeline import PrepConfig
    from ate_replication_causalml_tpu.pipeline import (
        SWEEP_METHODS,
        SweepConfig,
        run_sweep,
    )

    cfg = dataclasses.replace(
        SweepConfig().quick(),
        prep=PrepConfig(n_obs=scale.sweep_n_obs),
        synthetic_pool=scale.sweep_pool,
        synthetic_seed=seed,
        seed=seed,
        dr_trees=scale.sweep_trees, dml_trees=scale.sweep_trees,
        cf_trees=scale.sweep_trees,
        cf_nuisance_trees=scale.sweep_trees,
        forest_depth=scale.sweep_depth,
        balance_iters=scale.sweep_balance_iters,
    )
    with _FaultWindow() as win:
        run_sweep(cfg, outdir=outdir, plots=False, log=_silent)
    _write_summary(outdir, {
        "workload": "sweep",
        "seed": seed,
        "journal": "results.jsonl",
        "expected_rows": ["oracle"] + list(SWEEP_METHODS),
        "faults": win.collect(),
    })


def _run_matrix_workload(outdir: str, seed: int, scale: CampaignScale):
    from ate_replication_causalml_tpu.scenarios.dgp import STOCK_DGPS
    from ate_replication_causalml_tpu.scenarios.matrix import (
        MatrixSpec,
        cell_row_id,
        plan_columns,
        run_matrix,
    )

    calib = dataclasses.replace(STOCK_DGPS["calibration"],
                                n=scale.matrix_n)
    spec = MatrixSpec(
        dgps=(calib,), estimators=("naive", "ipw_logit"),
        n_reps=scale.matrix_reps, batch_width=scale.matrix_width,
        seed=seed, shard=False,
        # The invariants read the per-cell table (cells.jsonl rows,
        # cell-granular resume) — pin the PR 13 rows mode whatever the
        # ISSUE 19 streaming default or ATE_TPU_SCENARIO_ROWS says.
        rows=True,
    )
    plans, _skipped = plan_columns(spec)
    batches = {
        f"{p.name}#b{bi}": [
            cell_row_id(p.dgp.name, p.estimator, r) for r in batch
        ]
        for p in plans for bi, batch in enumerate(p.batches)
    }
    expected = [
        cell_row_id(p.dgp.name, p.estimator, r)
        for p in plans for r in range(spec.n_reps)
    ]
    with _FaultWindow() as win:
        run_matrix(spec, outdir=outdir, log=_silent)
    _write_summary(outdir, {
        "workload": "matrix",
        "seed": seed,
        "journal": "cells.jsonl",
        "expected_rows": expected,
        "batches": batches,
        "faults": win.collect(),
    })


def _synthetic_serving_forest(rng):
    """Same micro geometry as the serving/fleet rigs — small enough
    that per-episode AOT startup is cheap, real enough that the full
    predict path (routing, leaf stats, variance) runs."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import (
        CausalForest,
    )

    T, D, n, p, nb = 8, 3, 50, 4, 8
    return CausalForest(
        split_feat=jnp.asarray(
            rng.integers(0, p, size=(T, D, 1 << D)).astype(np.int32)
        ),
        split_bin=jnp.asarray(
            rng.integers(0, nb - 1, size=(T, D, 1 << D)).astype(np.int32)
        ),
        leaf_stats=jnp.asarray(
            (np.abs(rng.normal(size=(T, 1 << D, 5))) + 0.5)
            .astype(np.float32)
        ),
        in_sample=jnp.asarray(rng.uniform(size=(T, n)) < 0.5),
        bin_edges=jnp.asarray(
            np.sort(rng.normal(size=(p, nb - 1)), axis=1)
            .astype(np.float32)
        ),
        ci_group_size=2,
    )


def _counter_sum(name: str) -> float:
    return float(sum((obs.REGISTRY.peek(name) or {}).values()))


def _serve_retry(server, rid: str, x, max_attempts: int = 500):
    """Blocking serve with the polite-client retry discipline. The
    SPAN path (``serve_request``), deliberately: raw ``submit()``
    requests never enter the serving report's phase section, and the
    reconciliation invariant would report them as silent drops — the
    exact gotcha PR 11 turned into a checked number."""
    from ate_replication_causalml_tpu.serving.daemon import RejectedRequest

    for _ in range(max_attempts):
        try:
            return server.serve_request(rid, x, timeout=60.0)
        except RejectedRequest as rej:
            if rej.code in ("bad_request", "unknown_model",
                            "retired_model"):
                raise
            time.sleep(rej.retry_after_s or 0.002)
    raise RuntimeError(f"no progress on request {rid}")


def _serving_workload(rotate: bool):
    def run(outdir: str, seed: int, scale: CampaignScale):
        import jax.numpy as jnp

        from ate_replication_causalml_tpu.models.causal_forest import (
            predict_cate,
        )
        from ate_replication_causalml_tpu.serving import loadgen
        from ate_replication_causalml_tpu.serving.coalescer import (
            BucketPlan,
        )
        from ate_replication_causalml_tpu.serving.daemon import (
            CateServer,
            ServeConfig,
        )
        from ate_replication_causalml_tpu.utils.checkpoint import (
            save_fitted,
        )

        rng = np.random.default_rng(seed)
        forests = {1: _synthetic_serving_forest(rng)}
        if rotate:
            forests[2] = _synthetic_serving_forest(rng)
        ckpt = os.path.join(outdir, "model-v1.npz")
        save_fitted(ckpt, forests[1])

        schedule = loadgen.build_schedule(
            seed, scale.serve_requests, rate_hz=scale.serve_rate_hz,
            mix="1:2,3:2,4:1", id_prefix=f"c{seed}x",
        )
        queries = loadgen.build_queries(seed, schedule, features=4)
        # Offline per-version references BEFORE startup — the
        # process-global no-compile-window gotcha (README "Serving
        # gotchas"); committed as refs.npz, the bit-identity
        # invariant's comparison base.
        cat = jnp.asarray(np.concatenate(queries))
        refs = {}
        for v, forest in forests.items():
            out = predict_cate(forest, cat, oob=False,
                               row_backend="matmul")
            refs[f"cate_v{v}"] = np.asarray(out.cate)
            refs[f"var_v{v}"] = np.asarray(out.variance)
        np.savez(os.path.join(outdir, "refs.npz"), **refs)

        rejected_before = _counter_sum("serving_rejected_total")
        rotation_status = None
        with _FaultWindow() as win:
            server = CateServer(ServeConfig(
                checkpoint=ckpt,
                buckets=BucketPlan.parse("4"),
                window_s=0.002,
                max_depth=32,
                retry_after_s=0.002,
                # The campaign's zero_compile_window invariant does the
                # judging (a strict stop() would crash the episode
                # instead of recording the verdict).
                strict_no_compile=False,
            ))
            server.startup()
            try:
                half = len(schedule) // 2 if rotate else len(schedule)
                reqs = []
                for i, sched in enumerate(schedule[:half]):
                    reqs.append(_serve_retry(
                        server, sched.request_id, queries[i]
                    ))
                if rotate:
                    # Fleet rotation under load: publish a candidate
                    # through the retrain supervisor (the path the
                    # rotate: scope faults) between the two replay
                    # halves, so which version each request binds is
                    # deterministic whatever the rotation outcome.
                    sup = server.retrain_supervisor(
                        "default", lambda: forests[2],
                        publish_dir=outdir,
                    )
                    rotation_status = sup.run_once().status
                    for i, sched in enumerate(schedule[half:], half):
                        reqs.append(_serve_retry(
                            server, sched.request_id, queries[i]
                        ))
                compile_delta = server.compile_events_in_window()
                server.dump_artifacts(outdir)
                rejected_delta = (
                    _counter_sum("serving_rejected_total")
                    - rejected_before
                )
                drain_outcome = server.drain(timeout_s=60.0)
            finally:
                server.stop()  # idempotent after a clean drain

        rows = np.asarray([q.shape[0] for q in queries], np.int64)
        versions = np.asarray(
            [int(r.model_version or 1) for r in reqs], np.int64
        )
        np.savez(
            os.path.join(outdir, "answers.npz"),
            rows=rows,
            versions=versions,
            cate=np.concatenate([np.asarray(r.result[0]) for r in reqs]),
            var=np.concatenate([np.asarray(r.result[1]) for r in reqs]),
        )
        _write_summary(outdir, {
            "workload": "rotation" if rotate else "serving",
            "seed": seed,
            "n_requests": len(schedule),
            "request_ids": [s.request_id for s in schedule],
            "faults": win.collect(),
            "serving": {
                "compile_events_in_window": compile_delta,
                "drain_outcome": drain_outcome,
                "served": sum(1 for r in reqs if r.error is None),
                "rejected_metered_delta": rejected_delta,
                "rotation_status": rotation_status,
            },
        })

    return run


# ── the horizontal-fleet workload (ISSUE 18) ──────────────────────────
#
# Three REAL serving daemons (subprocesses of scripts/serve.py, each
# binding the same three models to the same published v1 checkpoint)
# behind an in-process RouterServer, replayed through a CateClient
# against the router port: first half of the seeded schedule, one
# fleet-wide rolling rotation of "default" onto v2, second half, and —
# when a ``daemon:`` chaos scope is armed — a SIGKILL of the planned
# victim at the 3/4 mark. Every request must still be served (router
# failover + the client's connection_lost resubmit), bit-identical per
# bound model version to the offline refs. Subprocess-heavy, so it is
# NOT in WORKLOAD_ORDER: campaign plans/reports for existing seeds are
# unchanged, and the fleet episode runs via explicit ``workloads=`` /
# ``run_repro`` (the @slow fleet test and the README runbook).


def _spawn_fleet_daemon(name: str, ckpt: str, logdir: str):
    """One scripts/serve.py subprocess serving default+m2+m3 from the
    same checkpoint on ephemeral serving/admin ports. Returns
    ``(proc, lines, stderr_thread)`` — ports are parsed later from the
    captured stderr lines."""
    import subprocess
    import sys as _sys
    import threading

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    for k in ("ATE_TPU_CHAOS", "ATE_TPU_METRICS_DIR",
              "ATE_TPU_SERVE_FLEET", "ATE_TPU_SERVE_ADMIN_PORT"):
        env.pop(k, None)  # daemons run fault-free; chaos lives up here
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [_sys.executable, os.path.join(root, "scripts", "serve.py"),
         "--checkpoint", ckpt, "--port", "0", "--admin-port", "0",
         "--fleet", f"m2={ckpt},m3={ckpt}",
         "--buckets", "4", "--window-ms", "2"],
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL,
        env=env, text=True,
    )
    lines: list[str] = []

    def _drain():
        for line in proc.stderr:
            lines.append(line)

    t = threading.Thread(target=_drain, name=f"fleet-stderr-{name}",
                         daemon=True)
    t.start()
    return proc, lines, t


def _fleet_ports(proc, lines, deadline_s: float = 180.0) -> tuple[int, int]:
    """Parse ``(serve_port, admin_port)`` from a daemon's stderr."""
    import re

    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        text = "".join(lines)
        served = re.search(r"# serving on [^:]+:(\d+)", text)
        admin = re.search(r"# admin endpoint on 127\.0\.0\.1:(\d+)", text)
        if served and admin:
            return int(served.group(1)), int(admin.group(1))
        if proc.poll() is not None:
            raise RuntimeError(
                f"fleet daemon exited rc={proc.returncode} before "
                f"binding: {text[-2000:]}"
            )
        time.sleep(0.05)
    raise RuntimeError("fleet daemon did not bind within the deadline")


def _peek_delta(name: str, before: dict) -> dict:
    now = obs.REGISTRY.peek(name) or {}
    return {k: v - before.get(k, 0.0) for k, v in now.items()
            if v - before.get(k, 0.0)}


def _run_fleet_workload(outdir: str, seed: int, scale: CampaignScale):
    import signal

    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import (
        predict_cate,
    )
    from ate_replication_causalml_tpu.serving import loadgen
    from ate_replication_causalml_tpu.serving import router as rt
    from ate_replication_causalml_tpu.serving.client import CateClient
    from ate_replication_causalml_tpu.utils.checkpoint import save_fitted

    rng = np.random.default_rng(seed)
    forests = {1: _synthetic_serving_forest(rng),
               2: _synthetic_serving_forest(rng)}
    ckpt_v1 = os.path.join(outdir, "model-v1.npz")
    ckpt_v2 = os.path.join(outdir, "model-v2.npz")
    save_fitted(ckpt_v1, forests[1])
    save_fitted(ckpt_v2, forests[2])

    models = ("default", "m2", "m3")
    schedule = loadgen.build_schedule(
        seed, scale.serve_requests, rate_hz=scale.serve_rate_hz,
        mix="1:2,3:2,4:1", id_prefix=f"f{seed}x", models=models,
    )
    queries = loadgen.build_queries(seed, schedule, features=4)
    # Offline per-version references BEFORE any jax serving work — the
    # committed comparison base for bit_identity. Every model id binds
    # the same v1 checkpoint, and the rotation moves only "default" to
    # v2, so refs keyed by version alone cover all three models.
    cat = jnp.asarray(np.concatenate(queries))
    refs = {}
    for v, forest in forests.items():
        out = predict_cate(forest, cat, oob=False, row_backend="matmul")
        refs[f"cate_v{v}"] = np.asarray(out.cate)
        refs[f"var_v{v}"] = np.asarray(out.variance)
    np.savez(os.path.join(outdir, "refs.npz"), **refs)

    names = ("b0", "b1", "b2")
    inj = chaos.active()
    victims = inj.daemon_kill_plan(names) if inj is not None else ()

    req_before = dict(obs.REGISTRY.peek("router_requests_total") or {})
    fo_before = dict(obs.REGISTRY.peek("router_failover_total") or {})
    procs: dict[str, object] = {}
    router = None
    serve_thread = None
    with _FaultWindow() as win:
        try:
            spawned = {n: _spawn_fleet_daemon(n, ckpt_v1, outdir)
                       for n in names}
            specs = []
            for n in names:
                proc, lines, _t = spawned[n]
                procs[n] = proc
                port, admin = _fleet_ports(proc, lines)
                specs.append(rt.BackendSpec(n, "127.0.0.1", port, admin))
            router = rt.RouterServer(rt.RouterConfig(
                backends=tuple(specs), probe_interval_s=0.1,
            ))
            router.start()
            supervisor = rt.FleetSupervisor(router)

            import threading

            bound: list[int] = []
            ready = threading.Event()

            def _on_bound(p: int) -> None:
                bound.append(p)
                ready.set()

            serve_thread = threading.Thread(
                target=rt.serve_socket, args=(router,),
                kwargs={"on_bound": _on_bound}, name="fleet-router",
                daemon=True,
            )
            serve_thread.start()
            if not ready.wait(timeout=30.0):
                raise RuntimeError("router did not bind")

            client = CateClient.connect("127.0.0.1", bound[0],
                                        timeout=60.0)
            half = len(schedule) // 2
            kill_at = (3 * len(schedule)) // 4
            rotation = None
            replies = []
            try:
                for i, sched in enumerate(schedule):
                    if i == half:
                        # Fleet-wide rolling rotation between the two
                        # replay halves: every daemon drains through
                        # cordon and swaps "default" onto the SAME
                        # published v2 path, one at a time.
                        rotation = supervisor.rotate_all(
                            ckpt_v2, model="default", timeout_s=120.0,
                        )
                    if i == kill_at:
                        for victim in victims:
                            if inj.record_daemon_kill(victim):
                                procs[victim].send_signal(signal.SIGKILL)
                    replies.append(client.predict_full(
                        queries[i], request_id=sched.request_id,
                        model=sched.model, max_retries=64,
                    ))
                if rotation is None:  # degenerate 1-request schedules
                    rotation = supervisor.rotate_all(
                        ckpt_v2, model="default", timeout_s=120.0,
                    )
                # The kill must be fully OBSERVED before the dump: the
                # replay normally trips the victim's breaker through
                # organic failover hops, but a probe tick can race the
                # victim out of the candidate list first — drive any
                # remaining failures over the ops channel so the merged
                # timeline always carries the breaker-open instant.
                for victim in victims:
                    for _ in range(router.config.failure_threshold):
                        state = router.stats()["backends"][victim][
                            "breaker"]
                        if state == "open":
                            break
                        try:
                            router.call_backend(victim, {"op": "stats"})
                        except Exception:  # noqa: BLE001 — dead by plan
                            pass
                router.dump_fleet(os.path.join(outdir, "fleet_dump"))
                client_retries = dict(client.retry_counts)
            finally:
                client.close()
        finally:
            if router is not None:
                router.stop()
            survivors = [n for n in procs if n not in victims]
            for n in survivors:
                procs[n].send_signal(signal.SIGTERM)
            for n, proc in procs.items():
                try:
                    proc.wait(timeout=60.0)
                except Exception:  # noqa: BLE001 — a wedged daemon
                    proc.kill()    # must not wedge the campaign
                    proc.wait(timeout=10.0)
            if serve_thread is not None:
                serve_thread.join(timeout=10.0)

    rows = np.asarray([q.shape[0] for q in queries], np.int64)
    versions = np.asarray(
        [int(h.get("model_version") or 1) for _, _, h in replies],
        np.int64,
    )
    np.savez(
        os.path.join(outdir, "answers.npz"),
        rows=rows,
        versions=versions,
        cate=np.concatenate([np.asarray(c) for c, _, _ in replies]),
        var=np.concatenate([np.asarray(v) for _, v, _ in replies]),
    )
    drains = [procs[n].returncode for n in procs if n not in victims]
    _write_summary(outdir, {
        "workload": "fleet",
        "seed": seed,
        "n_requests": len(schedule),
        "request_ids": [s.request_id for s in schedule],
        "faults": win.collect(),
        "fleet": {
            "backends": list(names),
            "killed": sorted(victims),
            "served": len(replies),
            "rotation": rotation,
            "survivor_exit_codes": drains,
            "client_retries": client_retries,
            "router_requests_delta": _peek_delta(
                "router_requests_total", req_before),
            "router_failover_delta": _peek_delta(
                "router_failover_total", fo_before),
        },
    })


WORKLOADS: dict[str, WorkloadSpec] = {
    "sweep": WorkloadSpec(
        "sweep", ("shard", "fs", "stage", "hang"), _run_sweep_workload
    ),
    "matrix": WorkloadSpec(
        "matrix", ("fs", "stage", "hang"), _run_matrix_workload
    ),
    "serving": WorkloadSpec(
        "serving", ("serve", "hang"), _serving_workload(rotate=False)
    ),
    "rotation": WorkloadSpec(
        "rotation", ("serve", "hang", "rotate"),
        _serving_workload(rotate=True)
    ),
    # The horizontal-fleet episode (ISSUE 18). Deliberately NOT in
    # WORKLOAD_ORDER: it spawns three daemon subprocesses per run, and
    # adding it to the default rotation would both blow the campaign's
    # time budget and reshuffle every existing seed's plan. It runs via
    # explicit ``workloads=("fleet",)`` or ``run_repro("fleet", ...)``.
    "fleet": WorkloadSpec("fleet", ("daemon",), _run_fleet_workload),
}
WORKLOAD_ORDER = ("sweep", "matrix", "serving", "rotation")


# ── episode execution ─────────────────────────────────────────────────


def _require_telemetry() -> None:
    """The campaign's entire fault accounting (summary fault lists,
    journal torn-line reconciliation, counter metering) reads the
    telemetry plane — with ``ATE_TPU_TELEMETRY=0`` every injection
    would be invisible and green episodes would report as spurious
    violations. Refuse at config time, the env-knob discipline."""
    if not obs.enabled():
        raise RuntimeError(
            "chaos campaigns need telemetry: ATE_TPU_TELEMETRY=0 hides "
            "every chaos_inject event the invariant registry accounts "
            "against — unset it to run a campaign"
        )


def _run_workload(workload: str, outdir: str, seed: int,
                  scale: CampaignScale) -> inv.RunArtifacts:
    if os.path.isdir(outdir) and os.listdir(outdir):
        # A reused outdir would RESUME the old journal — recorded torn
        # lines from the previous run break fault accounting silently.
        raise ValueError(
            f"campaign run dir {outdir!r} is not empty; every "
            "episode/reference run needs a fresh directory"
        )
    os.makedirs(outdir, exist_ok=True)
    WORKLOADS[workload].run(outdir, seed, scale)
    return inv.RunArtifacts(outdir)


def _episode_run(workload: str, seed: int, spec: str, outdir: str,
                 scale: CampaignScale) -> inv.RunArtifacts:
    """Run one (possibly chaos-armed) workload with fresh fault
    budgets; the env var is restored afterwards whatever happens."""
    with chaos.override(spec or None):
        return _run_workload(workload, outdir, seed, scale)


def run_repro(workload: str, seed: int, spec: str, outdir: str,
              scale: "str | CampaignScale" = "micro",
              log: Callable[[str], None] = print) -> list[inv.Verdict]:
    """One episode + its fault-free reference + the full invariant
    registry — the unit the shrinker's one-line repro re-runs. Returns
    the verdicts; the CLI exits nonzero when any fail (that exit IS
    the 're-fails' contract)."""
    _require_telemetry()
    scale = resolve_scale(scale)
    obs.install_jax_monitoring()
    ref = _episode_run(workload, seed, "", os.path.join(outdir, "ref"),
                       scale)
    log(f"[repro] reference done; running {workload} under {spec!r}")
    run = _episode_run(workload, seed, spec,
                       os.path.join(outdir, "episode"), scale)
    return inv.evaluate_all(run, ref)


# ── the shrinker ──────────────────────────────────────────────────────


def _ddmin(atoms: list, fails: Callable[[list], bool]) -> list:
    """Classic delta debugging over the episode's atom list: returns a
    1-minimal failing subset (removing any single tested chunk makes
    the failure disappear). ``fails`` must be deterministic — chaos
    plans are pure functions of seed, so it is."""
    cur = list(atoms)
    n = 2
    while len(cur) >= 2:
        chunk = max(1, len(cur) // n)
        subsets = [cur[i:i + chunk] for i in range(0, len(cur), chunk)]
        reduced = False
        for s in subsets:
            if len(s) < len(cur) and fails(s):
                cur, n, reduced = s, 2, True
                break
        if not reduced:
            for s in subsets:
                comp = [a for a in cur if a not in s]
                if comp and len(comp) < len(cur) and fails(comp):
                    cur, n, reduced = comp, max(n - 1, 2), True
                    break
        if not reduced:
            if n >= len(cur):
                break
            n = min(len(cur), n * 2)
    return cur


#: Public name (ISSUE 19): the frontier search shrinks failing knob
#: vectors through the SAME delta-debugging core the chaos campaign
#: shrinks fault specs with — one minimizer, two atom vocabularies.
ddmin = _ddmin


def shrink_episode(
    episode: Episode, failing: Sequence[str], ref: inv.RunArtifacts,
    outdir: str, scale: CampaignScale,
    log: Callable[[str], None] = print,
) -> dict:
    """Delta-debug ``episode.atoms`` down to a minimal subset that
    still fails at least one of ``failing``, then CONFIRM with one
    fresh (uncached) run of the minimal spec. Every probe is a full
    workload re-run against the shared reference — exact, because the
    chaos plan is a pure function of (spec, seed)."""
    cache: dict[str, bool] = {}
    runs = [0]

    def fails(atoms: list) -> bool:
        spec = compose(atoms)
        if spec in cache:
            return cache[spec]
        runs[0] += 1
        d = os.path.join(
            outdir, f"shrink-ep{episode.index:03d}-{runs[0]:02d}"
        )
        log(f"[shrink] ep{episode.index}: probing {spec!r}")
        run = _episode_run(episode.workload, episode.seed, spec, d, scale)
        verdicts = inv.evaluate_all(run, ref)
        bad = any(
            v.invariant in failing and v.verdict == "fail"
            for v in verdicts
        )
        cache[spec] = bad
        return bad

    minimal = _ddmin(list(episode.atoms), fails)
    spec_min = compose(minimal)
    # Fresh confirmation run — the repro must re-fail on a clean
    # directory, not merely have failed once during the search.
    cache.pop(spec_min, None)
    confirmed = fails(minimal)
    repro = (
        f"ATE_TPU_CHAOS='{spec_min}' python scripts/chaos_campaign.py "
        f"--repro --workload {episode.workload} --seed {episode.seed} "
        f"--scale {scale.name}"
    )
    return {
        "episode": episode.index,
        "workload": episode.workload,
        "seed": episode.seed,
        "failing": sorted(failing),
        "minimal_atoms": [
            {"scope": sc, "spec": sp} for sc, sp in minimal
        ],
        "repro": repro,
        "confirmed": confirmed,
        "n_probe_runs": runs[0],
    }


# ── the campaign driver ───────────────────────────────────────────────


def run_campaign(
    outdir: str,
    root_seed: int | None = None,
    n_episodes: int | None = None,
    workloads: Sequence[str] | None = None,
    scale: "str | CampaignScale" = "micro",
    shrink: bool = True,
    episodes: Sequence[Episode] | None = None,
    log: Callable[[str], None] = print,
) -> dict:
    """Run a full campaign and write ``campaign_report.json`` into
    ``outdir``. ``episodes`` overrides the generator (tests plant
    hand-built episodes — e.g. a ``tamper:journal`` violation — through
    the same engine). Returns the report dict; same root seed ⇒
    byte-identical report file."""
    _require_telemetry()
    obs.install_jax_monitoring()
    scale = resolve_scale(scale)
    if root_seed is None:
        root_seed = default_seed()
    if episodes is None:
        n = n_episodes if n_episodes is not None else _env_int(
            ENV_EPISODES, 4
        )
        episodes = plan_campaign(root_seed, n, workloads)
    os.makedirs(outdir, exist_ok=True)

    ep_counter = obs.counter(
        "chaos_campaign_episodes_total",
        "chaos-campaign episodes by workload and green/violated status",
    )
    check_counter = obs.counter(
        "chaos_invariant_checks_total",
        "campaign invariant evaluations by invariant and verdict",
    )

    refs: dict[tuple[str, int], inv.RunArtifacts] = {}
    report_eps: list[dict] = []
    violations: list[int] = []
    shrink_entries: list[dict] = []
    walls: list[float] = []
    for ep in episodes:
        key = (ep.workload, ep.seed)
        if key not in refs:
            log(f"[campaign] reference: {ep.workload} seed={ep.seed}")
            refs[key] = _episode_run(
                ep.workload, ep.seed, "",
                os.path.join(outdir, f"ref-{ep.workload}-{ep.seed}"),
                scale,
            )
        t0 = time.monotonic()
        log(f"[campaign] ep{ep.index}: {ep.workload} under {ep.spec!r}")
        run = _episode_run(
            ep.workload, ep.seed, ep.spec,
            os.path.join(outdir, f"ep{ep.index:03d}"), scale,
        )
        walls.append(time.monotonic() - t0)
        verdicts = inv.evaluate_all(run, refs[key])
        for v in verdicts:
            check_counter.inc(1, invariant=v.invariant, verdict=v.verdict)
        failing = sorted(
            v.invariant for v in verdicts if v.verdict == "fail"
        )
        status = "violated" if failing else "green"
        ep_counter.inc(1, workload=ep.workload, status=status)
        obs.emit("chaos_campaign_episode", status=status,
                 workload=ep.workload, episode=ep.index, spec=ep.spec)
        report_eps.append({
            "index": ep.index,
            "workload": ep.workload,
            "seed": ep.seed,
            "spec": ep.spec,
            "atoms": [{"scope": sc, "spec": sp} for sc, sp in ep.atoms],
            "status": status,
            "invariants": [v.as_json() for v in verdicts],
        })
        if failing:
            violations.append(ep.index)
            log(f"[campaign] ep{ep.index} VIOLATED: {failing}")
            if shrink:
                shrink_entries.append(shrink_episode(
                    ep, failing, refs[key], outdir, scale, log
                ))

    by_workload: dict[str, dict[str, int]] = {}
    for rec in report_eps:
        w = by_workload.setdefault(
            rec["workload"], {"green": 0, "violated": 0}
        )
        w[rec["status"]] += 1
    if shrink_entries:
        headline = shrink_entries[0]["repro"]
    elif violations:
        headline = (
            f"VIOLATED (unshrunk): episodes {violations}"
        )
    else:
        headline = (
            f"all green: {len(report_eps)} episodes x "
            f"{len(inv.registered_names())} invariants"
        )
    report = {
        "schema_version": SCHEMA_VERSION,
        "root_seed": root_seed,
        "scale": scale.name,
        "invariant_registry": list(inv.registered_names()),
        "n_episodes": len(report_eps),
        "episodes": report_eps,
        "by_workload": by_workload,
        "violations": violations,
        "shrink": shrink_entries,
        "headline": headline,
    }
    # Canonical dump: sorted keys, no wall-clock anywhere — same root
    # seed must produce a byte-identical file (asserted in tier-1).
    obs.atomic_write_text(
        os.path.join(outdir, "campaign_report.json"),
        json.dumps(report, indent=2, sort_keys=True) + "\n",
    )
    # Wall-clock lives BESIDE the canonical report, never in it — the
    # bench record reads this sidecar for its per-episode walls.
    obs.atomic_write_json(
        os.path.join(outdir, "campaign_walls.json"),
        {"episode_wall_s": [round(w, 3) for w in walls]},
    )
    obs.gauge(
        "chaos_campaign_episode_seconds",
        "wall seconds per chaos-campaign episode (last run)",
    ).set(max(walls) if walls else 0.0)
    log(f"[campaign] {headline}")
    return report

"""Error taxonomy for the resilience layer (ISSUE 3).

The reference's only robustness is numerical (propensity clipping,
``na.rm`` — SURVEY.md §5.3); a production sweep must instead decide, per
exception, whether re-execution can help. That decision is a *type*
question, made once here instead of ad hoc at every retry loop:

* **fatal** — programming errors (``TypeError``, ``ValueError``,
  ``AssertionError``, …). Retrying replays the same bug three times with
  backoff in between and then reports a "shard failure" that was never a
  shard's fault; these raise immediately.
* **transient** — device/runtime/IO failures (``JaxRuntimeError``,
  ``OSError``, plain ``RuntimeError``). The framework's unit of work is
  idempotent (every shard owns its fold-in key), so re-execution is
  recovery, bit-identically.
* ``KeyboardInterrupt``/``SystemExit`` are ``BaseException`` and are
  never caught by any retry or isolation layer.

Also home to the typed failures the layer itself raises, so callers can
``except`` precisely: :class:`CheckpointCorrupt` (a verified checkpoint
failed its digest — never silently returns wrong arrays),
:class:`DeadlineExceeded`, :class:`NonFiniteResult` (a computed row
failed the finite-value guard) and the :class:`ChaosFault` family
(injected by :mod:`.chaos`; transient by construction, so injected
faults exercise exactly the recovery paths real ones would).
"""

from __future__ import annotations


class ChaosFault(RuntimeError):
    """Base of all deliberately injected faults. Subclasses
    ``RuntimeError`` so the classifier treats injections as transient —
    chaos must walk the same recovery path a real fault would."""


class ChaosShardFault(ChaosFault):
    """Injected in place of a shard thunk's result (``run_shards``)."""


class ChaosStageFault(ChaosFault):
    """Injected at a sweep stage boundary (``pipeline.stage``)."""


class ChaosServeFault(ChaosFault):
    """Injected at the serving daemon's request boundary (``serving/``).
    The daemon answers it with a typed reject-with-retry-after and walks
    its degraded-mode recovery (checkpoint re-verify + reload), so the
    injection proves the client-visible contract: never a crash, never a
    wrong value, just a bounded retry."""


class ChaosRotateFault(ChaosFault):
    """Injected along the train-to-serve rotation path (``rotate:``
    scope): a retrain fit that dies, or a fault between a candidate
    checkpoint's verify and its swap. Transient by the family contract —
    the retrain supervisor's classified retry re-runs a dead fit, and a
    mid-swap fault must become a typed rotation refusal (last good model
    kept), never a half-installed one."""


class ChaosSpecError(ValueError):
    """The ``ATE_TPU_CHAOS`` spec string does not parse. A ValueError —
    a malformed chaos config is a programming error, fatal-fast, never
    something to retry through."""


class DeadlineExceeded(RuntimeError):
    """A shard pool's wall-clock deadline passed before the work did."""


class NonFiniteResult(RuntimeError):
    """An estimator produced a NaN/Inf point estimate from finite
    inputs — recorded as a failed row, never as a silent garbage row."""


class CheckpointCorrupt(RuntimeError):
    """A fitted-model checkpoint failed integrity verification. Always
    names the offending path so operators can quarantine the file."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"checkpoint {path!r} is corrupt: {reason}")
        self.path = path
        self.reason = reason


#: Exception types where re-execution replays the bug: raise, don't
#: retry. NotImplementedError subclasses RuntimeError, so it must be
#: listed here to beat the transient check.
FATAL_ERRORS: tuple[type[BaseException], ...] = (
    TypeError,
    ValueError,
    AssertionError,
    KeyError,
    IndexError,
    AttributeError,
    NameError,
    NotImplementedError,
    RecursionError,
)

_TRANSIENT_CACHE: tuple[type[BaseException], ...] | None = None


def transient_errors() -> tuple[type[BaseException], ...]:
    """Types worth retrying. ``jax.errors.JaxRuntimeError`` (a
    ``RuntimeError`` subclass on current jax, but listed explicitly in
    case that changes) is resolved lazily so this module never forces a
    backend import."""
    global _TRANSIENT_CACHE
    if _TRANSIENT_CACHE is None:
        types: list[type[BaseException]] = [RuntimeError, OSError]
        try:
            from jax.errors import JaxRuntimeError

            types.insert(0, JaxRuntimeError)
        except Exception:  # noqa: BLE001 — jax absent/ancient: stdlib set suffices
            pass
        _TRANSIENT_CACHE = tuple(types)
    return _TRANSIENT_CACHE


def classify(exc: BaseException) -> str:
    """``"fatal"`` or ``"transient"``. Fatal wins ties (e.g.
    ``NotImplementedError`` is both a RuntimeError and a programming
    error); unknown ``Exception`` subclasses are fatal — an error the
    taxonomy has never seen must surface, not burn retry budget."""
    if isinstance(exc, FATAL_ERRORS):
        return "fatal"
    if isinstance(exc, transient_errors()):
        return "transient"
    return "fatal"

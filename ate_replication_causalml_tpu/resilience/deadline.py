"""THE shared wall-clock budget type (ISSUE 14 — no jax).

Before this module the repo spoke three deadline dialects: ``run_shards``
did raw ``time.monotonic() + deadline_s`` arithmetic, the retrain
supervisor kept its own ``deadline`` local, and the serving plane had no
deadline at all — a request that had already missed its caller's budget
still got dispatched and burned device time. One type now carries the
budget end to end:

* the predict wire header's optional ``deadline_ms`` (the client stamps
  its REMAINING budget at send time) becomes a :class:`Budget` at
  admission and travels on the :class:`~..serving.coalescer.
  PendingRequest`, checked at every hand-off — admission, batch close,
  dispatch pickup — so an expired request is a typed retryable
  ``deadline_exceeded`` reject *before* device dispatch;
* ``run_shards``' per-pool ``deadline_s`` discipline is the same
  arithmetic through the same type, so serving and sweep speak one
  deadline vocabulary (and one set of edge-case semantics: a backoff
  that does not fit the remaining budget cuts the work instead of
  sleeping through the deadline).

The clock is injectable — deadline math must be provable without
sleeping (the coalescer/watchdog discipline) — and monotonic: wall-clock
jumps must never expire (or resurrect) a budget (graftlint JGL009).
"""

from __future__ import annotations

import time
from typing import Callable


class Budget:
    """A monotonic wall-clock budget: "this work is worthless after
    ``expires_mono``". Pure reads — no thread owns it, no lock needed
    (the expiry instant is immutable; only the clock advances)."""

    __slots__ = ("expires_mono", "total_s", "_clock")

    def __init__(
        self,
        expires_mono: float,
        total_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.expires_mono = float(expires_mono)
        #: the originally granted span (reporting only; None when the
        #: budget was built from a bare expiry instant).
        self.total_s = total_s
        self._clock = clock

    @classmethod
    def after(
        cls, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> "Budget":
        """A budget expiring ``seconds`` from now (the ``run_shards``
        / drain form)."""
        seconds = float(seconds)
        return cls(clock() + seconds, total_s=seconds, clock=clock)

    @classmethod
    def from_ms(
        cls, ms: float, clock: Callable[[], float] = time.monotonic
    ) -> "Budget":
        """A budget from a wire ``deadline_ms`` field (the serving
        form). Raises ``ValueError`` on non-numeric input so the
        admission layer can reject it typed."""
        return cls.after(float(ms) / 1e3, clock=clock)

    def remaining_s(self) -> float:
        """Seconds left (negative once expired — callers that want a
        sleep/cap value clamp themselves)."""
        return self.expires_mono - self._clock()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def affords(self, seconds: float) -> bool:
        """Whether ``seconds`` of work/sleep fits strictly inside the
        remaining budget — the ``run_shards`` backoff rule ("an
        unaffordable backoff cuts the shard instead of sleeping through
        the deadline")."""
        return self.remaining_s() > float(seconds)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"Budget(remaining={self.remaining_s():.6f}s)"

"""The uniform estimator result protocol.

Every estimator in the reference returns a one-row
``data.frame(Method, ATE, lower_ci, upper_ci)`` that the notebook
``rbind``s into ``result_df`` (``ate_replication.Rmd:129-132, 140-141,
156-157, ... 272``). SURVEY.md §1 identifies this uniform record as the
single most important API contract; here it is a typed dataclass plus an
accumulating result table.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Iterable

# 95% normal critical value — the reference hardcodes 1.96 everywhere
# (``ate_functions.R:17-18, 35-36, 59-60, ...``).
Z_95 = 1.96


@dataclasses.dataclass(frozen=True)
class EstimatorResult:
    """One estimator's output: point estimate and 95% CI.

    ``se`` is carried explicitly (the reference reconstructs it only
    implicitly via the CI half-width); for estimators with no SE the
    reference sets ``lower_ci == upper_ci == ate``
    (``ate_functions.R:107, 129``) and ``se`` is NaN.

    ``status`` is the resilience layer's degradation marker: ``"ok"``
    for a computed estimate, ``"failed"`` for a stage the sweep isolated
    instead of aborting on (pipeline.py) — such rows carry NaN values
    and render annotated, never as silent garbage.
    """

    method: str
    ate: float
    lower_ci: float
    upper_ci: float
    se: float = float("nan")
    status: str = "ok"

    @classmethod
    def from_point_se(cls, method: str, ate: float, se: float) -> "EstimatorResult":
        ate = float(ate)
        se = float(se)
        return cls(
            method=method,
            ate=ate,
            lower_ci=ate - Z_95 * se,
            upper_ci=ate + Z_95 * se,
            se=se,
        )

    @classmethod
    def point_only(cls, method: str, ate: float) -> "EstimatorResult":
        """No-SE record (single-equation/usual LASSO, ``ate_functions.R:107``)."""
        ate = float(ate)
        return cls(method=method, ate=ate, lower_ci=ate, upper_ci=ate)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ResultTable:
    """Accumulator replacing the notebook's ``result_df`` rbind chain."""

    def __init__(self, rows: Iterable[EstimatorResult] = ()):  # noqa: D401
        self.rows: list[EstimatorResult] = list(rows)

    def append(self, row: EstimatorResult) -> "ResultTable":
        self.rows.append(row)
        return self

    def extend(self, rows: Iterable[EstimatorResult]) -> "ResultTable":
        self.rows.extend(rows)
        return self

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, method: str) -> EstimatorResult:
        for r in self.rows:
            if r.method == method:
                return r
        raise KeyError(method)

    def methods(self) -> list[str]:
        return [r.method for r in self.rows]

    def to_records(self) -> list[dict]:
        return [r.to_dict() for r in self.rows]

    def to_json(self, path: str | None = None) -> str:
        s = json.dumps(self.to_records(), indent=2)
        if path is not None:
            # Local import: this module must stay importable without
            # pulling the observability package's jax-touching parts.
            from ate_replication_causalml_tpu.observability.export import (
                atomic_write_text,
            )

            atomic_write_text(path, s)
        return s

    @classmethod
    def from_json(cls, s: str) -> "ResultTable":
        return cls(EstimatorResult(**row) for row in json.loads(s))

    def __repr__(self) -> str:
        lines = [f"{'Method':<42} {'ATE':>10} {'lower':>10} {'upper':>10}"]
        for r in self.rows:
            lo = "" if math.isnan(r.lower_ci) else f"{r.lower_ci:10.4f}"
            hi = "" if math.isnan(r.upper_ci) else f"{r.upper_ci:10.4f}"
            lines.append(f"{r.method:<42} {r.ate:10.4f} {lo:>10} {hi:>10}")
        return "\n".join(lines)

"""Difference-in-means ATE (the reference's ``naive_ate``).

Reference: ``ate_functions.R:3-21``. Groups by treatment, computes
per-group mean/variance/count, then

    tau = E[Y|W=1] - E[Y|W=0]
    se  = sqrt( var_1/(n_1 - 1) + var_0/(n_0 - 1) )

Note the reference's SE uses ``var/(count-1)`` (R sample variance divided
by n-1 again — ``ate_functions.R:9``); reproduced as-is since it is part
of the published oracle CI.

Run on the *unbiased* RCT frame this is the oracle; on the biased frame
it is the known-bad baseline (SURVEY.md §4).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult


@jax.jit
def _naive_core(w: jax.Array, y: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Masked group reductions — one pass over a (possibly row-sharded)
    vector pair; XLA lowers the masked sums to psums under shard_map."""
    t = w == 1.0
    n1 = jnp.sum(t)
    n0 = w.shape[0] - n1
    mean1 = jnp.sum(jnp.where(t, y, 0.0)) / n1
    mean0 = jnp.sum(jnp.where(t, 0.0, y)) / n0
    # R var(): n-1 denominator.
    var1 = jnp.sum(jnp.where(t, (y - mean1) ** 2, 0.0)) / (n1 - 1)
    var0 = jnp.sum(jnp.where(t, 0.0, (y - mean0) ** 2)) / (n0 - 1)
    tau = mean1 - mean0
    se = jnp.sqrt(var1 / (n1 - 1) + var0 / (n0 - 1))
    return tau, se


def naive_ate(frame: CausalFrame, method: str = "naive") -> EstimatorResult:
    tau, se = _naive_core(frame.w, frame.y)
    return EstimatorResult.from_point_se(method, tau, se)

"""Double machine learning (Chernozhukov et al.) with forest nuisances.

Reference:
  * ``chernozhukov`` (``ate_functions.R:332-369``) — one cross-fit:
    an RF classifier of W on X (trained on fold 1) and an RF classifier
    of the *binary outcome* Y on X (trained on fold 2 — the reference
    treats Y as classification, ``:336, 345-348``); both predicted on
    the FULL sample (vote fractions — in-sample for the fold each was
    trained on: partial cross-fitting only, reproduced); residualize
    ``W~ = W - E[W|X]``, ``Y~ = Y - E[Y|X]``; no-intercept OLS of Y~ on
    W~ gives (tau, se).
  * ``double_ml`` (``ate_functions.R:372-389``) — deterministic
    first-half/second-half split (not randomized), run the cross-fit
    both ways, average the taus AND average the SEs (the reference's
    anti-conservative SE choice, reproduced; a pooled influence SE is
    available via ``se_mode="pooled"``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.models.forest import fit_forest_classifier, predict_forest
from ate_replication_causalml_tpu.ops.linalg import ols_no_intercept_1d


def _rf_prob_on_full(frame: CausalFrame, train_idx, target: jax.Array, key, n_trees,
                     depth, mesh=None):
    """Train a classification forest on ``train_idx`` rows, return vote
    fractions on the FULL sample (``ate_functions.R:352-357``). With a
    ``mesh``, trees shard over its tree axis (the nuisance forests are
    the DML hot loop, SURVEY.md §3.4)."""
    sub = frame.take(train_idx)
    tgt = target[jnp.asarray(train_idx)]
    if mesh is not None:
        from ate_replication_causalml_tpu.models.forest import fit_forest_sharded

        forest = fit_forest_sharded(
            sub.x, tgt, key, mesh, n_trees=n_trees, depth=depth
        )
    else:
        forest = fit_forest_classifier(sub.x, tgt, key, n_trees=n_trees, depth=depth)
    return predict_forest(forest, frame.x).vote


def chernozhukov(
    frame: CausalFrame,
    idx1,
    idx2,
    n_trees: int = 100,
    depth: int = 9,
    key: jax.Array | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """One DML cross-fit; returns (tau_hat, se_hat)."""
    if key is None:
        key = jax.random.key(123)  # the seed the reference *meant* to set
    k1, k2 = jax.random.split(key)
    ew = _rf_prob_on_full(frame, idx1, frame.w, k1, n_trees, depth, mesh=mesh)
    ey = _rf_prob_on_full(frame, idx2, frame.y, k2, n_trees, depth, mesh=mesh)
    w_resid = frame.w - ew
    y_resid = frame.y - ey
    return ols_no_intercept_1d(w_resid, y_resid)


def double_ml(
    frame: CausalFrame,
    n_trees: int = 100,
    depth: int = 9,
    key: jax.Array | None = None,
    se_mode: str = "r",
    mesh=None,
    method: str = "Double Machine Learning",
) -> EstimatorResult:
    """2-fold DML with the reference's deterministic split and averaging."""
    if se_mode not in ("r", "pooled"):
        raise ValueError(f"se_mode must be 'r' or 'pooled', got {se_mode!r}")
    if key is None:
        key = jax.random.key(123)
    n = frame.n
    half = n // 2
    idx1 = np.arange(half)
    idx2 = np.arange(half, n)
    ka, kb = jax.random.split(key)
    tau1, se1 = chernozhukov(frame, idx1, idx2, n_trees, depth, ka, mesh=mesh)
    tau2, se2 = chernozhukov(frame, idx2, idx1, n_trees, depth, kb, mesh=mesh)
    tau = (tau1 + tau2) / 2.0
    if se_mode == "r":
        # The reference averages the two fold SEs (ate_functions.R:383).
        se = (se1 + se2) / 2.0
    else:
        # "pooled" (validated above): treat folds as independent estimates.
        se = jnp.sqrt(se1**2 + se2**2) / 2.0
    return EstimatorResult.from_point_se(method, tau, se)

"""Double machine learning (Chernozhukov et al.) with forest nuisances.

Reference:
  * ``chernozhukov`` (``ate_functions.R:332-369``) — one cross-fit:
    an RF classifier of W on X (trained on fold 1) and an RF classifier
    of the *binary outcome* Y on X (trained on fold 2 — the reference
    treats Y as classification, ``:336, 345-348``); both predicted on
    the FULL sample (vote fractions — in-sample for the fold each was
    trained on: partial cross-fitting only, reproduced); residualize
    ``W~ = W - E[W|X]``, ``Y~ = Y - E[Y|X]``; no-intercept OLS of Y~ on
    W~ gives (tau, se).
  * ``double_ml`` (``ate_functions.R:372-389``) — deterministic
    first-half/second-half split (not randomized), run the cross-fit
    both ways, average the taus AND average the SEs (the reference's
    anti-conservative SE choice, reproduced; a pooled influence SE is
    available via ``se_mode="pooled"``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.models.forest import fit_forest_classifier, predict_forest
from ate_replication_causalml_tpu.ops.linalg import ols_no_intercept_1d


def _fit_nuisance_forest(frame: CausalFrame, train_idx, target: jax.Array, key,
                         n_trees, depth, mesh=None):
    """Classification forest of ``target`` on X over ``train_idx`` rows —
    the one nuisance fit both cross-fitting modes share (a divergence
    here would silently give them different nuisance models). With a
    ``mesh``, trees shard over its tree axis (the nuisance forests are
    the DML hot loop, SURVEY.md §3.4)."""
    sub = frame.take(train_idx)
    tgt = target[jnp.asarray(train_idx)]
    if mesh is not None:
        from ate_replication_causalml_tpu.models.forest import fit_forest_sharded

        return fit_forest_sharded(sub.x, tgt, key, mesh, n_trees=n_trees, depth=depth)
    return fit_forest_classifier(sub.x, tgt, key, n_trees=n_trees, depth=depth)


def _rf_prob_on_full(frame: CausalFrame, train_idx, target: jax.Array, key, n_trees,
                     depth, mesh=None):
    """Vote fractions on the FULL sample (``ate_functions.R:352-357`` —
    in-sample for the training fold: the reference's partial
    cross-fitting)."""
    forest = _fit_nuisance_forest(frame, train_idx, target, key, n_trees, depth, mesh)
    return predict_forest(forest, frame.x).vote


def chernozhukov(
    frame: CausalFrame,
    idx1,
    idx2,
    n_trees: int = 100,
    depth: int = 9,
    key: jax.Array | None = None,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """One DML cross-fit; returns (tau_hat, se_hat)."""
    if key is None:
        key = jax.random.key(123)  # the seed the reference *meant* to set
    k1, k2 = jax.random.split(key)
    ew = _rf_prob_on_full(frame, idx1, frame.w, k1, n_trees, depth, mesh=mesh)
    ey = _rf_prob_on_full(frame, idx2, frame.y, k2, n_trees, depth, mesh=mesh)
    w_resid = frame.w - ew
    y_resid = frame.y - ey
    return ols_no_intercept_1d(w_resid, y_resid)


def _rf_prob_oof(frame: CausalFrame, train_idx, pred_idx, target, key, n_trees,
                 depth, mesh=None):
    """Train on ``train_idx``, predict vote fractions ONLY on ``pred_idx``
    (the held-out fold) — the proper cross-fitting primitive."""
    forest = _fit_nuisance_forest(frame, train_idx, target, key, n_trees, depth, mesh)
    return predict_forest(forest, frame.x[jnp.asarray(pred_idx)]).vote


def double_ml(
    frame: CausalFrame,
    n_trees: int = 100,
    depth: int = 9,
    key: jax.Array | None = None,
    se_mode: str = "r",
    crossfit: str = "r",
    mesh=None,
    method: str = "Double Machine Learning",
) -> EstimatorResult:
    """2-fold DML with the reference's deterministic split.

    ``crossfit="r"`` (default) reproduces the reference's PARTIAL
    cross-fitting: each nuisance forest predicts on the full sample,
    in-sample for the fold it was trained on (``ate_functions.R:352-357``
    — the W-model sees fold 1 at train AND predict time), and the two
    fold estimates are averaged with ``se_mode`` ("r" = averaged SEs,
    the reference's anti-conservative choice; "pooled" available).

    ``crossfit="full"`` is textbook DML (Chernozhukov et al. 2018):
    BOTH nuisances for each fold are trained on the other fold only —
    out-of-fold predictions everywhere (4 forest fits, the same count
    as the "r" path's two chernozhukov calls) — stitched into
    full-sample residuals, with one pooled no-intercept OLS giving
    (tau, se).
    ``se_mode`` is ignored in this mode (there is one regression, no SE
    averaging quirk to choose between).
    """
    if se_mode not in ("r", "pooled"):
        raise ValueError(f"se_mode must be 'r' or 'pooled', got {se_mode!r}")
    if crossfit not in ("r", "full"):
        raise ValueError(f"crossfit must be 'r' or 'full', got {crossfit!r}")
    if key is None:
        key = jax.random.key(123)
    n = frame.n
    half = n // 2
    idx1 = np.arange(half)
    idx2 = np.arange(half, n)
    ka, kb = jax.random.split(key)
    if crossfit == "full":
        kw1, ky1 = jax.random.split(ka)
        kw2, ky2 = jax.random.split(kb)
        # Accumulate at the frame's precision (f64 under x64 stays f64 —
        # advisor r3) but never below f32: the votes are fractions, and
        # an integer-dtype frame must not truncate them.
        ew = jnp.zeros(n, jnp.result_type(frame.w.dtype, jnp.float32))
        ey = jnp.zeros(n, jnp.result_type(frame.y.dtype, jnp.float32))
        # Fold k's nuisances come from the OTHER fold's rows only.
        ew = ew.at[idx1].set(_rf_prob_oof(frame, idx2, idx1, frame.w, kw1, n_trees, depth, mesh))
        ew = ew.at[idx2].set(_rf_prob_oof(frame, idx1, idx2, frame.w, kw2, n_trees, depth, mesh))
        ey = ey.at[idx1].set(_rf_prob_oof(frame, idx2, idx1, frame.y, ky1, n_trees, depth, mesh))
        ey = ey.at[idx2].set(_rf_prob_oof(frame, idx1, idx2, frame.y, ky2, n_trees, depth, mesh))
        tau, se = ols_no_intercept_1d(frame.w - ew, frame.y - ey)
        return EstimatorResult.from_point_se(method, tau, se)
    tau1, se1 = chernozhukov(frame, idx1, idx2, n_trees, depth, ka, mesh=mesh)
    tau2, se2 = chernozhukov(frame, idx2, idx1, n_trees, depth, kb, mesh=mesh)
    tau = (tau1 + tau2) / 2.0
    if se_mode == "r":
        # The reference averages the two fold SEs (ate_functions.R:383).
        se = (se1 + se2) / 2.0
    else:
        # "pooled" (validated above): treat folds as independent estimates.
        se = jnp.sqrt(se1**2 + se2**2) / 2.0
    return EstimatorResult.from_point_se(method, tau, se)

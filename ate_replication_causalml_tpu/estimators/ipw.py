"""Inverse-propensity estimators: weighting and weighted regression.

Reference:
  * ``prop_score_weight`` (``ate_functions.R:44-63``) — the
    transformed-outcome IPW: per-row ``tau_i = ((W-p)·Y)/(p(1-p))``,
    point estimate ``mean(tau_i)``; the SE regresses ``tau_i`` on
    ``d = X·(W-p)`` (covariates scaled by the propensity residual) and
    uses ``sqrt(mean(resid²))/sqrt(N)`` — a Hirano/Imbens-style variance
    reduction.
  * ``prop_score_ols`` (``ate_functions.R:67-86``) — WLS of ``Y ~ W``
    with weights ``W/p + (1-W)/(1-p)``; tau and SE from the W coefficient.
  * the inline logistic propensity (``ate_replication.Rmd:164-168``):
    ``glm(W ~ X, binomial)`` fitted probabilities, in-sample.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.glm import logistic_glm
from ate_replication_causalml_tpu.ops.linalg import add_intercept, ols, wls


@jax.jit
def logistic_propensity(x: jax.Array, w: jax.Array) -> jax.Array:
    """In-sample logistic propensity p(W=1|X) (``ate_replication.Rmd:164-168``)."""
    return logistic_glm(add_intercept(x), w).fitted


@jax.jit
def _psw_core(x, w, y, p):
    tau_i = ((w - p) * y) / (p * (1.0 - p))
    ps_er = w - p
    d = x * ps_er[:, None]
    fit = ols(add_intercept(d), tau_i)
    e = fit.residuals
    n = x.shape[0]
    se = jnp.sqrt(jnp.mean(e * e)) / jnp.sqrt(jnp.asarray(n, x.dtype))
    return jnp.mean(tau_i), se


def prop_score_weight(
    frame: CausalFrame, p: jax.Array, method: str = "Propensity_Weighting"
) -> EstimatorResult:
    tau, se = _psw_core(frame.x, frame.w, frame.y, jnp.asarray(p, frame.x.dtype))
    return EstimatorResult.from_point_se(method, tau, se)


@jax.jit
def _psols_core(w, y, p):
    weights = w / p + (1.0 - w) / (1.0 - p)
    design = jnp.stack([jnp.ones_like(w), w], axis=1)
    fit = wls(design, y, weights)
    return fit.coef[1], fit.se[1]


def prop_score_ols(
    frame: CausalFrame, p: jax.Array, method: str = "Propensity_Regression"
) -> EstimatorResult:
    tau, se = _psols_core(frame.w, frame.y, jnp.asarray(p, frame.w.dtype))
    return EstimatorResult.from_point_se(method, tau, se)

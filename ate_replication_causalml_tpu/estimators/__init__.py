"""Estimator library — the TPU-native counterpart of ``ate_functions.R``.

Every estimator takes a :class:`~ate_replication_causalml_tpu.data.frame.CausalFrame`
and returns the uniform :class:`EstimatorResult` record (SURVEY.md §1:
``data.frame(Method, ATE, lower_ci, upper_ci)``).
"""

from ate_replication_causalml_tpu.estimators.aipw import (
    doubly_robust,
    doubly_robust_glm,
    outcome_model_mu,
)
from ate_replication_causalml_tpu.estimators.balance import (
    approx_balance,
    residual_balance_ate,
)
from ate_replication_causalml_tpu.estimators.base import (
    EstimatorResult,
    ResultTable,
    Z_95,
)
from ate_replication_causalml_tpu.estimators.belloni import belloni
from ate_replication_causalml_tpu.estimators.causal_forest_est import (
    causal_forest_ate,
    causal_forest_report,
)
from ate_replication_causalml_tpu.estimators.dml import chernozhukov, double_ml
from ate_replication_causalml_tpu.estimators.ipw import (
    logistic_propensity,
    prop_score_ols,
    prop_score_weight,
)
from ate_replication_causalml_tpu.estimators.lasso_est import (
    ate_condmean_lasso,
    ate_lasso,
    prop_score_lasso,
)
from ate_replication_causalml_tpu.estimators.naive import naive_ate
from ate_replication_causalml_tpu.estimators.ols import ate_condmean_ols

__all__ = [
    "EstimatorResult",
    "ResultTable",
    "Z_95",
    "approx_balance",
    "ate_condmean_lasso",
    "ate_condmean_ols",
    "ate_lasso",
    "belloni",
    "causal_forest_ate",
    "causal_forest_report",
    "chernozhukov",
    "double_ml",
    "doubly_robust",
    "doubly_robust_glm",
    "logistic_propensity",
    "naive_ate",
    "outcome_model_mu",
    "prop_score_lasso",
    "prop_score_ols",
    "prop_score_weight",
    "residual_balance_ate",
]

"""Approximate residual balancing (Athey–Imbens–Wager) — the TPU-native
equivalent of ``balanceHD::residualBalance.ate`` as invoked by
``residual_balance_ATE`` (``ate_functions.R:393-405``,
``ate_replication.Rmd:240-243``).

The reference delegates wholesale to the balanceHD package, which per arm:
(1) computes balancing weights over the arm's rows toward the population
covariate mean by a constrained QP (quadprog or pogs — here the graph-form
ADMM in ``ops/qp.py``); (2) fits an elastic-net outcome regression on the
arm; (3) combines them as

    mu_hat(arm) = target . beta_hat + sum_i gamma_i * (Y_i - X_i . beta_hat)

— the regression predicts at the target point and the weights mop up the
residual bias. tau_hat = mu_hat(treated) - mu_hat(control). The SE is the
plug-in sqrt(sum_arm sigma2_arm * sum(gamma_arm^2)) with sigma2 from the
arm's regression residuals.

Quirk ledger (SURVEY.md §2.1 #14): the reference's wrapper ignores its
``dataset`` argument and reads the notebook globals ``df_mod``/``covariates``
(``ate_functions.R:394-396``) — its caller even passes an undefined symbol,
surviving only via R lazy evaluation. Here the frame is an explicit
argument; the produced estimate is what the reference's call computes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.lasso import cv_glmnet, predict_path
from ate_replication_causalml_tpu.ops.qp import balance_qp


def approx_balance(
    x: jax.Array,
    target: jax.Array,
    zeta: float = 0.5,
    ub: float = jnp.inf,
    max_iters: int = 4000,
) -> jax.Array:
    """Balancing weights over rows of ``x`` toward covariate mean ``target``
    (balanceHD ``approx.balance``): argmin zeta*||g||^2 +
    (1-zeta)*||X^T g - target||_inf^2 over the (capped) simplex."""
    return balance_qp(x, target, zeta=zeta, ub=ub, max_iters=max_iters).gamma


@functools.partial(jax.jit, static_argnames=("zeta", "max_iters"))
def _arm_mu_var(x_arm, y_arm, target, key, zeta, max_iters):
    """One arm's counterfactual mean and variance contribution.

    ``x_arm``/``y_arm`` are the arm's rows (compressed host-side — the
    two arms have different n, so each arm gets its own compiled
    instance; both are one-shot fits).
    """
    qp = balance_qp(x_arm, target, zeta=zeta, max_iters=max_iters)
    gamma = qp.gamma

    # Elastic net outcome regression on the arm (balanceHD fits the
    # outcome model with an elastic-net penalty, alpha=0.9 default),
    # lambda by 10-fold CV.
    cv = cv_glmnet(x_arm, y_arm, family="gaussian", alpha=0.9, key=key)
    idx = cv.index_min
    eta = predict_path(cv.path, x_arm, idx)
    beta = cv.path.coefs[idx]
    mu_reg = cv.path.intercepts[idx] + jnp.dot(target, beta)
    resid = y_arm - eta
    mu = mu_reg + jnp.dot(gamma, resid)

    n_arm = x_arm.shape[0]
    df = jnp.sum(jnp.abs(beta) > 0) + 1.0
    sigma2 = jnp.sum(resid**2) / jnp.maximum(n_arm - df, 1.0)
    var = sigma2 * jnp.sum(gamma**2)
    return mu, var, qp.primal_resid, qp.iters


def residual_balance_ate(
    frame: CausalFrame,
    zeta: float = 0.5,
    max_iters: int = 4000,
    key: jax.Array | None = None,
    method: str = "residual_balancing",
    estimate_se: bool = True,
) -> EstimatorResult:
    """ATE by approximate residual balancing, matching the reference row
    ``Method = "residual_balancing"`` (``ate_functions.R:400-403``)."""
    if key is None:
        key = jax.random.key(0)
    k0, k1 = jax.random.split(key)
    x, w, y = frame.x, frame.w, frame.y
    target = jnp.mean(x, axis=0)

    treated = np.asarray(w) > 0.5
    mu1, var1, rp1, it1 = _arm_mu_var(x[treated], y[treated], target, k1, zeta, max_iters)
    mu0, var0, rp0, it0 = _arm_mu_var(x[~treated], y[~treated], target, k0, zeta, max_iters)
    for arm, rp, it in (("treated", rp1, it1), ("control", rp0, it0)):
        if int(it) >= max_iters and float(rp) > 1e-5:
            import warnings

            warnings.warn(
                f"balance QP ({arm} arm) hit max_iters={max_iters} with primal "
                f"residual {float(rp):.2e}; weights may be inexact — raise "
                "max_iters for wide covariate sets",
                RuntimeWarning,
                stacklevel=2,
            )
    tau = float(mu1 - mu0)
    if not estimate_se:
        return EstimatorResult.point_only(method, tau)
    se = float(jnp.sqrt(var1 + var0))
    return EstimatorResult.from_point_se(method, tau, se)

"""Approximate residual balancing (Athey–Imbens–Wager) — the TPU-native
equivalent of ``balanceHD::residualBalance.ate`` as invoked by
``residual_balance_ATE`` (``ate_functions.R:393-405``,
``ate_replication.Rmd:240-243``).

The reference delegates wholesale to the balanceHD package, which per arm:
(1) computes balancing weights over the arm's rows toward the population
covariate mean by a constrained QP (quadprog or pogs — here the graph-form
ADMM in ``ops/qp.py``); (2) fits an elastic-net outcome regression on the
arm; (3) combines them as

    mu_hat(arm) = target . beta_hat + sum_i gamma_i * (Y_i - X_i . beta_hat)

— the regression predicts at the target point and the weights mop up the
residual bias. tau_hat = mu_hat(treated) - mu_hat(control). The SE is the
plug-in sqrt(sum_arm sigma2_arm * sum(gamma_arm^2)) with sigma2 from the
arm's regression residuals.

Quirk ledger (SURVEY.md §2.1 #14): the reference's wrapper ignores its
``dataset`` argument and reads the notebook globals ``df_mod``/``covariates``
(``ate_functions.R:394-396``) — its caller even passes an undefined symbol,
surviving only via R lazy evaluation. Here the frame is an explicit
argument; the produced estimate is what the reference's call computes.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.lasso import cv_glmnet, predict_path
from ate_replication_causalml_tpu.ops.qp import balance_qp_x64


def approx_balance(
    x: jax.Array,
    target: jax.Array,
    zeta: float = 0.5,
    ub: float = jnp.inf,
    max_iters: int = 4000,
) -> jax.Array:
    """Balancing weights over rows of ``x`` toward covariate mean ``target``
    (balanceHD ``approx.balance``): argmin zeta*||g||^2 +
    (1-zeta)*||X^T g - target||_inf^2 over the (capped) simplex.

    Solved in f64 (see :func:`~..ops.qp.balance_qp_x64`: f32 ADMM floors
    three orders of magnitude short of quadprog's stationarity)."""
    return approx_balance_sol(x, target, zeta=zeta, ub=ub, max_iters=max_iters)[0]


def approx_balance_sol(x, target, zeta=0.5, ub=jnp.inf, max_iters=4000):
    """(gamma_f32, worst_resid, iters) from the f64 balance QP —
    ``worst_resid`` is max(primal, dual), the quantity the stopping rule
    tests, so callers' inexactness warnings can't be silenced by
    one-sided convergence."""
    qp = balance_qp_x64(
        x, target, zeta=zeta, ub=float(ub), max_iters=max_iters
    )
    worst = jnp.maximum(qp.primal_resid, qp.dual_resid)
    return jnp.asarray(qp.gamma, jnp.float32), worst, qp.iters


@jax.jit
def _arm_mu_var(x_arm, y_arm, target, key, gamma):
    """One arm's counterfactual mean and variance contribution, given its
    precomputed balancing weights.

    ``x_arm``/``y_arm`` are the arm's rows (compressed host-side — the
    two arms have different n, so each arm gets its own compiled
    instance; both are one-shot fits).
    """
    # Elastic net outcome regression on the arm (balanceHD fits the
    # outcome model with an elastic-net penalty, alpha=0.9 default),
    # lambda by 10-fold CV.
    cv = cv_glmnet(x_arm, y_arm, family="gaussian", alpha=0.9, key=key)
    idx = cv.index_min
    eta = predict_path(cv.path, x_arm, idx)
    beta = cv.path.coefs[idx]
    mu_reg = cv.path.intercepts[idx] + jnp.dot(target, beta)
    resid = y_arm - eta
    mu = mu_reg + jnp.dot(gamma, resid)

    n_arm = x_arm.shape[0]
    df = jnp.sum(jnp.abs(beta) > 0) + 1.0
    sigma2 = jnp.sum(resid**2) / jnp.maximum(n_arm - df, 1.0)
    var = sigma2 * jnp.sum(gamma**2)
    return mu, var


def residual_balance_ate(
    frame: CausalFrame,
    zeta: float = 0.5,
    max_iters: int = 4000,
    key: jax.Array | None = None,
    method: str = "residual_balancing",
    estimate_se: bool = True,
) -> EstimatorResult:
    """ATE by approximate residual balancing, matching the reference row
    ``Method = "residual_balancing"`` (``ate_functions.R:400-403``)."""
    if key is None:
        key = jax.random.key(0)
    k0, k1 = jax.random.split(key)
    x, w, y = frame.x, frame.w, frame.y
    target = jnp.mean(x, axis=0)

    treated = np.asarray(w) > 0.5
    g1, rp1, it1 = approx_balance_sol(x[treated], target, zeta=zeta, max_iters=max_iters)
    g0, rp0, it0 = approx_balance_sol(x[~treated], target, zeta=zeta, max_iters=max_iters)
    mu1, var1 = _arm_mu_var(x[treated], y[treated], target, k1, g1)
    mu0, var0 = _arm_mu_var(x[~treated], y[~treated], target, k0, g0)
    for arm, rp, it in (("treated", rp1, it1), ("control", rp0, it0)):
        if int(it) >= max_iters and float(rp) > 1e-5:
            import warnings

            warnings.warn(
                f"balance QP ({arm} arm) hit max_iters={max_iters} with "
                f"worst residual {float(rp):.2e}; weights may be inexact — "
                "raise max_iters for wide covariate sets",
                RuntimeWarning,
                stacklevel=2,
            )
    tau = float(mu1 - mu0)
    if not estimate_se:
        return EstimatorResult.point_only(method, tau)
    se = float(jnp.sqrt(var1 + var0))
    return EstimatorResult.from_point_se(method, tau, se)

"""Belloni–Chernozhukov–Hansen (2013) post-double-selection.

Reference: ``belloni`` (``ate_functions.R:286-328``):

  1. expand X to all pairwise products — both orders AND self-squares,
     k + k² columns total (``ate_functions.R:289-296``; duplicated
     interactions enter the design twice, as published);
  2. two gaussian CV-LASSOs: X→W and X→Y (``:304-305``);
  3. take coefficients — with the reference's **wrong-λ bug**: both
     models are evaluated at ``model_xw$lambda.min`` (``:308-309``),
     which for model_xy is an off-path value that R's ``coef`` serves by
     linear interpolation in λ (glmnet ``lambda.interp``) — reproduced;
  4. support union — with the reference's **sign bug**: ``> 0`` keeps
     only positive coefficients (``:312-313``) — reproduced in
     ``compat="r"`` (default), ``compat="fixed"`` uses ``!= 0``;
  5. OLS of Y on [X_selected, W]; ATE and SE from W's coefficient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.lasso import cv_glmnet
from ate_replication_causalml_tpu.ops.linalg import add_intercept, alias_filter, ols


def interaction_expand(x: jax.Array) -> jax.Array:
    """[X, all pairwise products x_i*x_j in the reference's double-loop
    order] — (n, k + k^2)."""
    n, k = x.shape
    prods = jnp.einsum("ni,nj->nij", x, x).reshape(n, k * k)
    return jnp.concatenate([x, prods], axis=1)


def _interp_coef_at(path_lambdas, coefs, s):
    """R glmnet ``coef(fit, s=)`` off-path behavior: linear interpolation
    between the two bracketing path λs (``lambda.interp``), constant
    extrapolation outside the path."""
    lams = path_lambdas
    L = lams.shape[0]
    s = jnp.clip(s, lams[-1], lams[0])
    # Path is decreasing; find right bracket.
    right = jnp.clip(jnp.searchsorted(-lams, -s), 1, L - 1)
    left = right - 1
    frac = (s - lams[right]) / (lams[left] - lams[right])
    return frac * coefs[left] + (1.0 - frac) * coefs[right]


def belloni(
    frame: CausalFrame,
    foldid_xw=None,
    foldid_xy=None,
    key: jax.Array | None = None,
    fold_axis: str | None = None,
    compat: str = "r",
    method: str = "Belloni et.al",
) -> EstimatorResult:
    if key is None:
        key = jax.random.key(0)
    kxw, kxy = jax.random.split(key)
    x_big = interaction_expand(frame.x)

    cv_xw = cv_glmnet(x_big, frame.w, family="gaussian", foldid=foldid_xw, key=kxw,
                      fold_axis=fold_axis)
    cv_xy = cv_glmnet(x_big, frame.y, family="gaussian", foldid=foldid_xy, key=kxy,
                      fold_axis=fold_axis)

    lam = cv_xw.lambda_min
    c_xw = _interp_coef_at(cv_xw.path.lambdas, cv_xw.path.coefs, lam)
    # The wrong-λ bug: model_xy evaluated at model_xw's lambda.min.
    c_xy = _interp_coef_at(cv_xy.path.lambdas, cv_xy.path.coefs, lam)

    if compat == "r":
        sel = (np.asarray(c_xw) > 0) | (np.asarray(c_xy) > 0)
    elif compat == "fixed":
        sel = (np.asarray(c_xw) != 0) | (np.asarray(c_xy) != 0)
    else:
        raise ValueError(f"compat must be 'r' or 'fixed', got {compat!r}")
    sel_idx = np.nonzero(sel)[0]

    # The expansion contains aliased columns: exact duplicates (c1*c2 and
    # c2*c1; squares of binary flags reproduce the flag itself) and any
    # linear dependencies among selected columns (three-way collinear
    # combinations, constants). R's lm() drops them during its pivoted QR
    # with left-to-right preference (``ate_functions.R:317-320`` relies
    # on that); alias_filter reproduces the same rule so the
    # normal-equations solve sees a full-rank design. W's coefficient is
    # identical either way.
    cols = np.asarray(x_big[:, sel_idx])
    keep = alias_filter(cols, with_intercept=True)
    x_restricted = jnp.concatenate(
        [jnp.asarray(cols[:, keep]), frame.w[:, None]], axis=1
    )
    fit = ols(add_intercept(x_restricted), frame.y)
    tau, se = fit.coef[-1], fit.se[-1]
    return EstimatorResult.from_point_se(method, tau, se)

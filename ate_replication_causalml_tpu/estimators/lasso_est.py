"""LASSO-based estimators: single-equation, usual, and LASSO propensity.

Reference:
  * ``ate_condmean_lasso`` (``ate_functions.R:89-108``): gaussian
    ``cv.glmnet`` of Y on [X, W] with **penalty.factor 0 on W** (W never
    shrunk); the ATE is W's coefficient at the CV-selected λ. R's
    ``coef(cvfit)`` defaults to ``s = "lambda.1se"`` — reproduced.
    Returns a point estimate with no SE (``lower_ci == upper_ci``).
  * ``ate_lasso`` (``ate_functions.R:111-130``): identical but W is
    penalized like every other column.
  * ``prop_score_lasso`` (``ate_functions.R:133-146``): binomial-logit
    LASSO of W on X; returns **in-sample** fitted probabilities at
    ``lambda.1se`` (a vector, not a result row), which the notebook
    feeds to the IPW estimator (``ate_replication.Rmd:183-188``).

Note the reference treats the binary outcome as *gaussian* in both
outcome LASSOs — that is the published behavior and is kept.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.lasso import cv_glmnet, predict_path


def _xw_design(frame: CausalFrame) -> jax.Array:
    """[X, W] matrix — covariates in schema order then treatment
    (``ate_functions.R:91-94``)."""
    return jnp.concatenate([frame.x, frame.w[:, None]], axis=1)


def ate_condmean_lasso(
    frame: CausalFrame,
    foldid=None,
    key: jax.Array | None = None,
    fold_axis: str | None = None,
    method: str = "Single-equation LASSO",
) -> EstimatorResult:
    x = _xw_design(frame)
    pfac = jnp.concatenate([jnp.ones(frame.p, x.dtype), jnp.zeros(1, x.dtype)])
    cv = cv_glmnet(x, frame.y, family="gaussian", penalty_factor=pfac, foldid=foldid,
                   key=key, fold_axis=fold_axis)
    _, coefs = cv.coef_at("1se")
    return EstimatorResult.point_only(method, coefs[-1])


def ate_lasso(
    frame: CausalFrame,
    foldid=None,
    key: jax.Array | None = None,
    fold_axis: str | None = None,
    method: str = "Usual LASSO",
) -> EstimatorResult:
    x = _xw_design(frame)
    cv = cv_glmnet(x, frame.y, family="gaussian", foldid=foldid, key=key,
                   fold_axis=fold_axis)
    _, coefs = cv.coef_at("1se")
    return EstimatorResult.point_only(method, coefs[-1])


def prop_score_lasso(
    frame: CausalFrame, foldid=None, key: jax.Array | None = None,
    fold_axis: str | None = None,
) -> jax.Array:
    """LASSO-logit propensity vector at lambda.1se, in-sample."""
    cv = cv_glmnet(frame.x, frame.w, family="binomial", foldid=foldid, key=key,
                   fold_axis=fold_axis)
    idx = cv.index_1se
    eta = predict_path(cv.path, frame.x, idx)
    return jax.nn.sigmoid(eta)

"""Regression adjustment ("Direct Method") — OLS of Y on covariates + W.

Reference: ``ate_condmean_ols`` (``ate_functions.R:25-39``): fit
``lm(Y ~ .)`` on the frame, report the W coefficient and its classical
standard error. The design matrix is [1, X, W] in schema order, matching
R's formula expansion on a frame laid out [covariates..., W, Y].
"""

from __future__ import annotations

import jax

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops.linalg import ols


@jax.jit
def _direct_core(x, w, y):
    import jax.numpy as jnp

    design = jnp.concatenate([jnp.ones((x.shape[0], 1), x.dtype), x, w[:, None]], axis=1)
    fit = ols(design, y)
    return fit.coef[-1], fit.se[-1]


def ate_condmean_ols(frame: CausalFrame, method: str = "Direct Method") -> EstimatorResult:
    tau, se = _direct_core(frame.x, frame.w, frame.y)
    return EstimatorResult.from_point_se(method, tau, se)

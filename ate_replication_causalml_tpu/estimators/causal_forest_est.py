"""Causal-forest ATE estimator — the reference's estimator #15, which is
implemented inline in the notebook rather than in ``ate_functions.R``
(``ate_replication.Rmd:249-272``, SURVEY.md §2.1 #15)."""

from __future__ import annotations

from typing import NamedTuple

import jax

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.models.causal_forest import (
    average_treatment_effect,
    fit_causal_forest,
    incorrect_forest_ate,
    predict_cate,
)


class CausalForestReport(NamedTuple):
    """Everything the notebook's causal-forest chunk produces: the
    deliberately 'incorrect' mean-CATE ATE/SE it prints
    (``Rmd:258-262``) plus the correct doubly-robust result row."""

    result: EstimatorResult
    incorrect_ate: float
    incorrect_se: float


def causal_forest_ate(
    frame: CausalFrame,
    key: jax.Array | None = None,
    n_trees: int = 2000,
    method_name: str = "Causal Forest(GRF)",
    **fit_kwargs,
) -> EstimatorResult:
    """Honest causal forest → doubly-robust ATE
    (``grf::estimate_average_effect``, ``ate_replication.Rmd:265-270``)."""
    fitted = fit_causal_forest(frame, key=key, n_trees=n_trees, **fit_kwargs)
    eff = average_treatment_effect(fitted)
    return EstimatorResult.from_point_se(
        method_name, float(eff.estimate), float(eff.std_err)
    )


def causal_forest_report(
    frame: CausalFrame,
    key: jax.Array | None = None,
    n_trees: int = 2000,
    method_name: str = "Causal Forest(GRF)",
    variance_compat: str = "unbiased",
    **fit_kwargs,
) -> CausalForestReport:
    """One fit, both outputs of the notebook chunk: the incorrect
    mean-of-CATEs ATE/SE demo and the correct AIPW result row — sharing
    the fitted forest and its CATE predictions. ``variance_compat``:
    see :func:`models.causal_forest.predict_cate` (grf's num_groups df
    vs the unbiased gn−1 default)."""
    fitted = fit_causal_forest(frame, key=key, n_trees=n_trees, **fit_kwargs)
    cate = predict_cate(
        fitted.forest, fitted.x, oob=True, variance_compat=variance_compat
    )
    ate_bad, se_bad = incorrect_forest_ate(cate)
    eff = average_treatment_effect(fitted, cate=cate)
    return CausalForestReport(
        result=EstimatorResult.from_point_se(
            method_name, float(eff.estimate), float(eff.std_err)
        ),
        incorrect_ate=float(ate_bad),
        incorrect_se=float(se_bad),
    )

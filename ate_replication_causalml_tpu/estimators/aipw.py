"""AIPW / doubly-robust estimators with sandwich and bootstrap SEs.

Reference: ``doubly_robust`` (``ate_functions.R:149-207``, random-forest
propensity) and ``doubly_robust_glm`` (``ate_functions.R:211-264``,
logistic propensity). Both share the same skeleton:

  1. outcome model: binomial-logit GLM of Y on [X, W] fit on the full
     sample (no cross-fitting — a reference quirk, SURVEY.md §2.1 #8);
     mu1/mu0 predicted with W forced to 1/0;
  2. a propensity model (RF OOB votes or in-sample GLM);
  3. the AIPW combination
     ``tau = mean(W(Y-mu1)/p + (1-W)(Y-mu0)/(1-p)) + mean(mu1-mu0)``;
  4. SE: either B=1000 nonparametric bootstrap of the combination step
     only — nuisances are NOT refit (``ate_functions.R:267-283``) — or
     the closed-form influence-function ("sandwich") SE
     ``sqrt(sum(I_i^2)/n^2)`` (``ate_functions.R:198-199``).

The RF path clips p away from {0,1} to the smallest/largest interior
value observed (``ate_functions.R:181-182``); the GLM path does not —
both behaviors reproduced.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.ops import bootstrap as bt
from ate_replication_causalml_tpu.ops.glm import logistic_glm, predict_proba
from ate_replication_causalml_tpu.ops.linalg import add_intercept


def aipw_tau(w, y, p, mu0, mu1, compat: str = "r") -> jax.Array:
    """The AIPW combination (``ate_functions.R:183-185``).

    ``compat="r"`` reproduces the reference's published formula, which
    ADDS the control augmentation term — a sign quirk: standard AIPW
    subtracts it, and the reference's own sandwich influence function
    (``ate_functions.R:197``) uses the standard convention. The "r"
    estimator is consistent when both nuisances are correct but is NOT
    doubly robust. ``compat="fixed"`` is textbook AIPW (doubly robust;
    property-tested in tests/test_estimators_e2e.py). See
    ``ops.bootstrap._aipw_tau``."""
    return bt._aipw_tau(w, y, p, mu0, mu1, _control_sign(compat))


def _control_sign(compat: str) -> float:
    if compat == "r":
        return 1.0
    if compat == "fixed":
        return -1.0
    raise ValueError(f"compat must be 'r' or 'fixed', got {compat!r}")


@jax.jit
def aipw_sandwich_se(w, y, p, mu0, mu1, tau) -> jax.Array:
    """Influence-function SE (``ate_functions.R:198-199``)."""
    ii = (
        (w * y) / p
        - mu1 * (w - p) / p
        - (((1.0 - w) * y / (1.0 - p)) + (mu0 * (w - p) / (1.0 - p)))
        - tau
    )
    n = ii.shape[0]
    return jnp.sqrt(jnp.sum(ii * ii) / (n * n))


@jax.jit
def clip_propensity(p: jax.Array) -> jax.Array:
    """Replace exact 0/1 propensities with the nearest interior value
    observed (``ate_functions.R:181-182``)."""
    pmin = jnp.min(jnp.where(p > 0.0, p, jnp.inf))
    pmax = jnp.max(jnp.where(p < 1.0, p, -jnp.inf))
    p = jnp.where(p == 0.0, pmin, p)
    return jnp.where(p == 1.0, pmax, p)


@jax.jit
def _outcome_model_mu(x, w, y):
    """Logit outcome model on [1, X, W]; mu1/mu0 via W := 1/0
    (``ate_functions.R:156-166``)."""
    base = add_intercept(x)
    with_w = lambda col: jnp.concatenate([base, col[:, None]], axis=1)
    fit = logistic_glm(with_w(w), y)
    return (
        predict_proba(fit.coef, with_w(jnp.zeros_like(w))),
        predict_proba(fit.coef, with_w(jnp.ones_like(w))),
    )


def outcome_model_mu(frame: CausalFrame) -> tuple[jax.Array, jax.Array]:
    """The shared AIPW nuisance, public: ``(mu0, mu1)`` from the
    full-sample logit outcome model. Both doubly-robust estimators
    consume exactly this fit (same inputs, same function), which makes
    it a declared artifact in the sweep scheduler (ISSUE 4) — pass the
    result back through ``doubly_robust(..., mu=...)`` /
    ``doubly_robust_glm(..., mu=...)`` to share one fit."""
    return _outcome_model_mu(frame.x, frame.w, frame.y)


def _aipw_result(
    frame: CausalFrame,
    p: jax.Array,
    method: str,
    bootstrap_se: bool,
    n_boot: int,
    key: jax.Array | None,
    boot_indices,
    sharded: bool,
    compat: str = "r",
    mu: tuple[jax.Array, jax.Array] | None = None,
) -> EstimatorResult:
    w, y = frame.w, frame.y
    cs = _control_sign(compat)
    # ``mu`` lets the sweep scheduler share one outcome-model fit across
    # both DR stages; fitting here is bit-identical (same jitted fn,
    # same inputs).
    mu0, mu1 = mu if mu is not None else _outcome_model_mu(frame.x, w, y)
    tau = aipw_tau(w, y, p, mu0, mu1, compat=compat)
    if bootstrap_se:
        if boot_indices is not None:
            se = bt.aipw_bootstrap_se(
                w, y, p, mu0, mu1, indices=jnp.asarray(boot_indices),
                control_sign=cs,
            )
        elif sharded:
            se = bt.aipw_bootstrap_se_sharded(
                w, y, p, mu0, mu1, key=key, n_boot=n_boot, control_sign=cs
            )
        else:
            se = bt.aipw_bootstrap_se(
                w, y, p, mu0, mu1, key=key, n_boot=n_boot, control_sign=cs
            )
    else:
        # The sandwich influence function is the STANDARD (minus-sign)
        # one in the reference too — under compat="r" the pairing of the
        # "+" point estimate with the "-" IF is itself part of the
        # published behavior being reproduced.
        se = aipw_sandwich_se(w, y, p, mu0, mu1, tau)
    return EstimatorResult.from_point_se(method, tau, se)


def doubly_robust_glm(
    frame: CausalFrame,
    bootstrap_se: bool = False,
    n_boot: int = 1000,
    key: jax.Array | None = None,
    boot_indices=None,
    sharded: bool = False,
    method: str = "Doubly Robust with logistic regression PS",
    compat: str = "r",
    p: jax.Array | None = None,
    mu: tuple[jax.Array, jax.Array] | None = None,
) -> EstimatorResult:
    """AIPW with in-sample GLM propensity, no clipping
    (``ate_functions.R:211-264``). ``compat``: see :func:`aipw_tau`.

    ``p``/``mu`` accept precomputed nuisances (the sweep scheduler's
    shared artifacts): ``p`` must be the in-sample logistic propensity
    — exactly :func:`~..ipw.logistic_propensity` — and ``mu`` the
    :func:`outcome_model_mu` pair; omitted, both are fit here from the
    same functions, bit-identically."""
    _control_sign(compat)  # reject typos before the nuisance fit
    if p is None:
        p = logistic_glm(add_intercept(frame.x), frame.w).fitted
    if bootstrap_se and key is None and boot_indices is None:
        key = jax.random.key(0)
    return _aipw_result(
        frame, p, method, bootstrap_se, n_boot, key, boot_indices, sharded,
        compat, mu=mu,
    )


def doubly_robust(
    frame: CausalFrame,
    propensity_fn: Callable[[CausalFrame], jax.Array],
    bootstrap_se: bool = False,
    n_boot: int = 1000,
    key: jax.Array | None = None,
    boot_indices=None,
    sharded: bool = False,
    method: str = "Doubly Robust with Random Forest PS",
    compat: str = "r",
    mu: tuple[jax.Array, jax.Array] | None = None,
) -> EstimatorResult:
    """AIPW with a pluggable propensity model and the reference's
    clip-to-interior rule (``ate_functions.R:149-207``). The canonical
    ``propensity_fn`` is a random-forest OOB propensity (the reference
    uses ``randomForest`` OOB votes); see ``models.forest`` once the
    forest engine lands — any callable ``CausalFrame -> (n,) probs``
    works. ``mu``: precomputed :func:`outcome_model_mu` pair (the
    sweep's shared artifact)."""
    _control_sign(compat)  # reject typos before the forest fit
    p = clip_propensity(jnp.asarray(propensity_fn(frame)))
    if bootstrap_se and key is None and boot_indices is None:
        key = jax.random.key(0)
    return _aipw_result(
        frame, p, method, bootstrap_se, n_boot, key, boot_indices, sharded,
        compat, mu=mu,
    )

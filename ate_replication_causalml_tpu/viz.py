"""Comparison figures — the notebook's three ggplot pointrange charts
(``ate_replication.Rmd:146-150, 209-213, 277-281``), the reference's only
"dashboard" (SURVEY.md §5.5).

Design deviates from the ggplot default deliberately: methods go on the
y-axis (long labels read horizontally instead of at 45°), every estimate
uses one hue (identity is carried by the axis label, not color), and the
RCT oracle is drawn as a reference band behind the marks so "which CI
brackets the truth" — the chart's actual question — is answerable at a
glance. Matplotlib renders to PNG next to the result table.
"""

from __future__ import annotations

import math
from typing import Iterable, NamedTuple, Sequence

from ate_replication_causalml_tpu.estimators.base import EstimatorResult


class PointrangeMark(NamedTuple):
    """One plotted row: what the chart actually drew (testable without
    parsing pixels — a blank-axes regression has an empty mark list)."""

    method: str
    ate: float
    lower: float
    upper: float
    y: float


class PointrangeChart(NamedTuple):
    figure: object                                  # matplotlib Figure
    marks: list[PointrangeMark]                     # one per method row
    oracle_band: tuple[float, float, float] | None  # (lower, upper, ate)

# Brand-neutral defaults validated for the light surface.
_SURFACE = "#fcfcfb"
_INK = "#0b0b0b"
_INK_2 = "#52514e"
_GRID = "#e4e3df"
_ESTIMATE = "#2a78d6"   # all estimate marks — one entity class, one hue
_ORACLE = "#eb6834"     # the reference band


def pointrange_figure(
    results: Sequence[EstimatorResult],
    oracle: EstimatorResult | None = None,
    title: str = "ATE estimates vs the RCT oracle",
    path: str | None = None,
    footnote: str | None = None,
):
    """Horizontal pointrange chart of estimate ± CI per method.

    ``oracle`` (the unbiased RCT difference-in-means,
    ``ate_replication.Rmd:130``) renders as a vertical line + CI band
    behind the marks. ``footnote`` annotates the chart bottom-left —
    the resilience layer uses it to name stages a degraded sweep could
    not plot. Returns a :class:`PointrangeChart` carrying the Figure
    plus the plotted arrays; saves PNG when ``path`` is given.
    """
    # Agg canvas bound to this figure only — never touches the process-
    # global backend (a notebook user's interactive backend stays live).
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure

    rows = list(results)
    n = len(rows)
    fig = Figure(figsize=(7.2, 1.1 + 0.52 * n), dpi=150)
    FigureCanvasAgg(fig)
    ax = fig.add_subplot(111)
    fig.patch.set_facecolor(_SURFACE)
    ax.set_facecolor(_SURFACE)

    ys = range(n - 1, -1, -1)  # first method on top
    band = None
    if oracle is not None:
        band = (float(oracle.lower_ci), float(oracle.upper_ci), float(oracle.ate))
        ax.axvspan(band[0], band[1], color=_ORACLE, alpha=0.12, lw=0)
        ax.axvline(band[2], color=_ORACLE, lw=2, label=f"RCT oracle ({band[2]:.3f})")
    marks = []
    for y, r in zip(ys, rows):
        ax.plot([r.lower_ci, r.upper_ci], [y, y], color=_ESTIMATE, lw=2,
                solid_capstyle="round", zorder=3)
        ax.plot([r.ate], [y], "o", color=_ESTIMATE, ms=7, zorder=4)
        marks.append(PointrangeMark(
            method=r.method, ate=float(r.ate),
            lower=float(r.lower_ci), upper=float(r.upper_ci), y=float(y),
        ))
    ax.set_yticks(list(ys))
    ax.set_yticklabels([r.method for r in rows], fontsize=9, color=_INK)
    ax.set_xlabel("ATE (95% CI)", fontsize=9, color=_INK_2)
    ax.set_title(title, fontsize=11, color=_INK, loc="left", pad=12)
    ax.grid(axis="x", color=_GRID, lw=0.8)
    for side in ("top", "right", "left"):
        ax.spines[side].set_visible(False)
    ax.spines["bottom"].set_color(_GRID)
    ax.tick_params(colors=_INK_2, labelsize=8)
    if oracle is not None:
        ax.legend(loc="upper right", frameon=False, fontsize=8, labelcolor=_INK_2)
    fig.tight_layout()
    if footnote:
        fig.subplots_adjust(bottom=max(0.18, fig.subplotpars.bottom + 0.06))
        fig.text(0.02, 0.02, footnote, fontsize=7.5, color=_INK_2)
    if path is not None:
        fig.savefig(path, facecolor=_SURFACE)
    return PointrangeChart(figure=fig, marks=marks, oracle_band=band)


def _plottable(r: EstimatorResult) -> bool:
    return getattr(r, "status", "ok") == "ok" and math.isfinite(r.ate)


def notebook_figures(
    results: Iterable[EstimatorResult],
    oracle: EstimatorResult | None,
    outdir: str,
) -> list[str]:
    """The notebook's three charts, same stage boundaries:
    ``rct_naive_plot`` (oracle + naive), ``compare_regression``
    (through the LASSO family), ``compare_CausalML`` (everything).

    Degraded sweeps (pipeline.py isolation policy) still render:
    ``status="failed"`` rows are dropped from the marks and named in a
    footnote instead, and ``oracle=None`` (a failed oracle stage) skips
    the reference band rather than drawing a NaN span."""
    import os

    rows_all = list(results)
    rows = [r for r in rows_all if _plottable(r)]
    failed = {r.method for r in rows_all if not _plottable(r)}
    by_method = {r.method: r for r in rows}
    paths = []

    def save(name, want_methods, title):
        subset = [by_method[m] for m in want_methods if m in by_method]
        missing = [m for m in want_methods if m in failed]
        note = ("✗ failed, not shown: " + ", ".join(missing)) if missing else None
        p = os.path.join(outdir, f"{name}.png")
        # Render WITHOUT saving, validate, then write: a blank chart
        # must fail loudly — and must not overwrite the last good PNG
        # at this path before the check runs.
        chart = pointrange_figure(subset, oracle=oracle, title=title,
                                  footnote=note)
        drawn = [m.method for m in chart.marks]
        want = [r.method for r in subset]
        if drawn != want or (oracle is not None and chart.oracle_band is None):
            raise RuntimeError(
                f"figure {name!r} did not draw what was requested: "
                f"drawn={drawn} wanted={want} band={chart.oracle_band}"
            )
        chart.figure.savefig(p, facecolor=_SURFACE)
        paths.append(p)

    save("rct_naive_plot", ("naive",),
         "Naive estimate on the biased sample vs RCT oracle")

    regression_methods = (
        "naive", "Direct Method", "Propensity_Weighting", "Propensity_Regression",
        "Propensity_Weighting_LASSOPS", "Single-equation LASSO", "Usual LASSO",
    )
    save("compare_regression", regression_methods,
         "Regression extensions vs RCT oracle")

    save("compare_CausalML", [r.method for r in rows_all],
         "All estimators vs RCT oracle")
    return paths

"""Concurrent sweep scheduler (ISSUE 4).

Three layers, one import surface:

* :mod:`~.dag` — stage/artifact declarations and DAG validation;
* :mod:`~.cache` — the fit-once nuisance artifact cache;
* :mod:`~.engine` — the bounded worker pool with declaration-ordered
  commits (``workers=1`` is the sequential escape hatch);
* :mod:`~.prefetch` — the background compile-prefetch lane.

The L5 driver (``pipeline.py``) is the production consumer; the specs
are plain callables so tests can schedule synthetic DAGs without jax.
"""

from ate_replication_causalml_tpu.scheduler.cache import NuisanceCache
from ate_replication_causalml_tpu.scheduler.dag import (
    ArtifactSpec,
    Dag,
    DagError,
    StageSpec,
    validate,
)
from ate_replication_causalml_tpu.scheduler.engine import (
    SweepEngine,
    default_workers,
)
from ate_replication_causalml_tpu.scheduler.prefetch import (
    CompilePrefetcher,
    default_enabled as prefetch_default_enabled,
)

__all__ = [
    "ArtifactSpec",
    "CompilePrefetcher",
    "Dag",
    "DagError",
    "NuisanceCache",
    "StageSpec",
    "SweepEngine",
    "default_workers",
    "prefetch_default_enabled",
    "validate",
]

"""Fit-once nuisance artifact cache (ISSUE 4, tentpole part 2).

Replaces the driver's ad-hoc ``_p_log`` lazy list: every shared
nuisance (logistic propensity, LASSO PS path, fold masks, RF OOB
propensity, outcome-model mu0/mu1) is an :class:`~.dag.ArtifactSpec`
and is fit at most once per (name, key) — the key carries the data
fingerprint and the config knobs the fit reads, so distinct configs can
never share an artifact even if a cache instance were reused across
runs.

Concurrency contract: the cache is the synchronization point between
stages that race for the same artifact. A per-entry lock serializes the
fit; losers of the race block and then read the winner's value (a
cache *hit* — they never refit). Failures are deliberately NOT
memoized: the sequential sweep refits a failed shared nuisance on the
next consumer (each consumer stage degrades independently), and the
concurrent sweep must behave identically.

Hit/miss traffic lands in the ``nuisance_cache_requests_total`` counter
(labels ``artifact=``, ``status=hit|miss``) and each fit is a
``nuisance_fit`` span — the metrics families
``scripts/check_metrics_schema.py`` validates.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterable

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.scheduler.dag import ArtifactSpec, DagError


class NuisanceCache:
    """Thread-safe fit-once store over a set of artifact specs."""

    def __init__(self, specs: Iterable[ArtifactSpec] = ()):
        self._lock = threading.Lock()
        self._specs: dict[str, ArtifactSpec] = {}
        self._values: dict[tuple, object] = {}
        self._entry_locks: dict[tuple, threading.Lock] = {}
        self._lane_locks: dict[str, threading.RLock] = {}
        self._hits: dict[str, int] = {}
        self._misses: dict[str, int] = {}
        for spec in specs:
            self.register(spec)

    def register(self, spec: ArtifactSpec) -> None:
        with self._lock:
            if spec.name in self._specs:
                raise DagError(f"artifact {spec.name!r} registered twice")
            self._specs[spec.name] = spec

    def spec(self, name: str) -> ArtifactSpec:
        with self._lock:
            return self._specs[name]

    def _entry_lock(self, key: tuple) -> threading.Lock:
        with self._lock:
            lk = self._entry_locks.get(key)
            if lk is None:
                lk = self._entry_locks[key] = threading.Lock()
            return lk

    def lane_lock(self, lane: str) -> threading.RLock:
        """Re-entrant lock shared with the engine for one exclusive lane.

        The engine's scheduling skip keeps two laned *nodes* from
        overlapping, but a failed laned artifact is refit by whichever
        consumer stage requests it next — possibly an unlaned stage body
        on another worker thread. Both the engine (around a laned node's
        execution) and :meth:`get` (around a laned artifact's fit) hold
        this lock, so that refit can never launch its collective
        concurrently with a laned node. Re-entrant because the engine's
        own artifact node reaches the fit through :meth:`get` on the
        same thread; always acquired BEFORE the per-entry lock so the
        two orderings cannot deadlock."""
        with self._lock:
            lk = self._lane_locks.get(lane)
            if lk is None:
                lk = self._lane_locks[lane] = threading.RLock()
            return lk

    def get(self, name: str) -> object:
        """The artifact's value, fitting it on first request.

        Counted as a hit when the value already exists (including when
        this thread blocked on another thread's in-flight fit), a miss
        when this call runs the fit. An exception from the fit
        propagates to THIS caller and leaves no entry behind.
        """
        spec = self.spec(name)
        key = (name, spec.key)
        c = obs.counter(
            "nuisance_cache_requests_total",
            "nuisance artifact cache requests by artifact and hit/miss",
        )
        with self._lock:
            if key in self._values:
                self._hits[name] = self._hits.get(name, 0) + 1
                value = self._values[key]
                c.inc(1, artifact=name, status="hit")
                return value
        guard = (
            self.lane_lock(spec.exclusive)
            if spec.exclusive is not None
            else contextlib.nullcontext()
        )
        with guard:
            with self._entry_lock(key):
                # Double-check: the thread we waited on may have fit it.
                with self._lock:
                    if key in self._values:
                        self._hits[name] = self._hits.get(name, 0) + 1
                        value = self._values[key]
                        c.inc(1, artifact=name, status="hit")
                        return value
                c.inc(1, artifact=name, status="miss")
                with obs.span("nuisance_fit", artifact=name):
                    value = spec.fit(self)
                with self._lock:
                    self._misses[name] = self._misses.get(name, 0) + 1
                    self._values[key] = value
                return value

    def stats(self) -> dict[str, dict[str, int]]:
        """``{"hits": {...}, "misses": {...}}`` by artifact name (tests
        and the engine's end-of-run summary)."""
        with self._lock:
            return {"hits": dict(self._hits), "misses": dict(self._misses)}

"""Fit-once nuisance artifact cache (ISSUE 4, tentpole part 2; ISSUE 8
device-resident artifact plane).

Replaces the driver's ad-hoc ``_p_log`` lazy list: every shared
nuisance (logistic propensity, LASSO PS path, fold masks, RF OOB
propensity, outcome-model mu0/mu1) is an :class:`~.dag.ArtifactSpec`
and is fit at most once per (name, key) — the key carries the data
fingerprint and the config knobs the fit reads, so distinct configs can
never share an artifact even if a cache instance were reused across
runs.

Concurrency contract: the cache is the synchronization point between
stages that race for the same artifact. A per-entry lock serializes the
fit; losers of the race block and then read the winner's value (a
cache *hit* — they never refit). Failures are deliberately NOT
memoized: the sequential sweep refits a failed shared nuisance on the
next consumer (each consumer stage degrades independently), and the
concurrent sweep must behave identically.

Device residency (ISSUE 8): an artifact whose spec declares a
``sharding`` is stored in its device-resident form — the fit's output
is committed onto the declared layout (``parallel/shardio.commit``,
blocked until drained) INSIDE the artifact's lane, replacing PR 4's
host-materialization bounce. Consumers receive the layout their spec's
``consumes_sharding`` declares through :meth:`get`'s ``layout``
argument (the engine binds stage bodies to a :class:`_LayoutView`):

* ``"device"`` — the stored sharded form, a zero-host-byte handoff;
* a sharding object — a compiled device→device reshard;
* ``"host"`` / undeclared — the SAFE default: one compiled all-gather
  + ``device_get`` (a single host crossing), cached per entry so N
  host consumers pay one gather total.

The PR-4 lane rule is preserved structurally: every path that can
launch a collective (the commit, a reshard, the gather) runs inside
``lane_lock(spec.exclusive)``, so a sharded artifact consumed by an
unlaned stage never launches its all-gather concurrently with a
mesh-lane node, and an unlaned consumer only ever holds host data.

Hit/miss traffic lands in the ``nuisance_cache_requests_total`` counter
(labels ``artifact=``, ``status=hit|miss``), each fit is a
``nuisance_fit`` span, and every byte the plane moves is metered into
``artifact_transfer_bytes_total`` / ``artifact_reshard_total``
(parallel/shardio.py) — the families
``scripts/check_metrics_schema.py`` validates.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterable

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.scheduler.dag import ArtifactSpec, DagError

#: lazily imported so this module (and the no-jax scheduler tests) can
#: load without initializing a backend; tests monkeypatch this to drive
#: the layout paths with a fake plane.
_SHARDIO = None


def _shardio():
    global _SHARDIO
    if _SHARDIO is None:
        from ate_replication_causalml_tpu.parallel import shardio

        _SHARDIO = shardio
    return _SHARDIO


class _LayoutView:
    """Consumer-facing resolver bound to one spec's ``consumes_sharding``
    declaration: ``get(name)`` yields the declared layout, undeclared
    names fall back to the cache's safe default (host form for sharded
    artifacts). Bodies keep calling plain ``c.get(...)`` — the layout
    contract lives in the declaration, not the call site."""

    __slots__ = ("_cache", "_consumes")

    def __init__(self, cache: "NuisanceCache", consumes: dict):
        self._cache = cache
        self._consumes = dict(consumes)

    def get(self, name: str):
        return self._cache.get(name, layout=self._consumes.get(name))

    def spec(self, name: str) -> ArtifactSpec:
        return self._cache.spec(name)

    def stats(self):
        return self._cache.stats()


class NuisanceCache:
    """Thread-safe fit-once store over a set of artifact specs."""

    def __init__(self, specs: Iterable[ArtifactSpec] = ()):
        self._lock = threading.Lock()
        self._specs: dict[str, ArtifactSpec] = {}
        self._values: dict[tuple, object] = {}
        self._host_forms: dict[tuple, object] = {}
        self._entry_locks: dict[tuple, threading.Lock] = {}
        self._lane_locks: dict[str, threading.RLock] = {}
        self._hits: dict[str, int] = {}
        self._misses: dict[str, int] = {}
        for spec in specs:
            self.register(spec)

    def register(self, spec: ArtifactSpec) -> None:
        with self._lock:
            if spec.name in self._specs:
                raise DagError(f"artifact {spec.name!r} registered twice")
            self._specs[spec.name] = spec

    def spec(self, name: str) -> ArtifactSpec:
        with self._lock:
            return self._specs[name]

    def view_for(self, spec) -> object:
        """The resolver a node body receives: the cache itself when the
        spec declares no consume layouts (zero overhead, today's object
        identity), else a :class:`_LayoutView` bound to them."""
        consumes = getattr(spec, "consumes_sharding", None)
        if not consumes:
            return self
        return _LayoutView(self, consumes)

    def _entry_lock(self, key: tuple) -> threading.Lock:
        with self._lock:
            lk = self._entry_locks.get(key)
            if lk is None:
                lk = self._entry_locks[key] = threading.Lock()
            return lk

    def lane_lock(self, lane: str) -> threading.RLock:
        """Re-entrant lock shared with the engine for one exclusive lane.

        The engine's scheduling skip keeps two laned *nodes* from
        overlapping, but a failed laned artifact is refit by whichever
        consumer stage requests it next — possibly an unlaned stage body
        on another worker thread. Both the engine (around a laned node's
        execution) and :meth:`get` (around a laned artifact's fit, and
        around every collective the artifact plane launches on its
        behalf — commit, reshard, gather) hold this lock, so none of
        those can ever launch a collective concurrently with a laned
        node. Re-entrant because the engine's own artifact node reaches
        the fit through :meth:`get` on the same thread; always acquired
        BEFORE the per-entry lock so the two orderings cannot
        deadlock."""
        with self._lock:
            lk = self._lane_locks.get(lane)
            if lk is None:
                lk = self._lane_locks[lane] = threading.RLock()
            return lk

    def _lane_guard(self, spec: ArtifactSpec):
        if spec.exclusive is not None:
            return self.lane_lock(spec.exclusive)
        return contextlib.nullcontext()

    def get(self, name: str, *, layout: object = None) -> object:
        """The artifact's value, fitting it on first request, in the
        consumer's declared ``layout`` (see module docstring; ``None``
        is the safe default — the host form for sharded artifacts,
        the plain value otherwise).

        Counted as a hit when the value already exists (including when
        this thread blocked on another thread's in-flight fit), a miss
        when this call runs the fit. An exception from the fit
        propagates to THIS caller and leaves no entry behind.
        """
        spec = self.spec(name)
        value = self.ensure(name)
        return self._deliver(spec, (name, spec.key), value, layout)

    def ensure(self, name: str) -> object:
        """Fit-if-needed and return the STORED form — device-resident
        for sharded artifacts — with no layout delivery and no handoff
        metering. The engine's artifact nodes call this: they PRODUCE
        the artifact; only consumer edges move or meter bytes."""
        spec = self.spec(name)
        key = (name, spec.key)
        c = obs.counter(
            "nuisance_cache_requests_total",
            "nuisance artifact cache requests by artifact and hit/miss",
        )
        with self._lock:
            if key in self._values:
                self._hits[name] = self._hits.get(name, 0) + 1
                value = self._values[key]
                c.inc(1, artifact=name, status="hit")
                return value
        with self._lane_guard(spec):
            with self._entry_lock(key):
                # Double-check: the thread we waited on may have fit it.
                with self._lock:
                    if key in self._values:
                        self._hits[name] = self._hits.get(name, 0) + 1
                        value = self._values[key]
                        c.inc(1, artifact=name, status="hit")
                        return value
                c.inc(1, artifact=name, status="miss")
                with obs.span("nuisance_fit", artifact=name):
                    value = spec.fit(self.view_for(spec))
                    if spec.sharding is not None:
                        # Commit the declared device-resident layout
                        # INSIDE the lane, blocked until drained — the
                        # lane releases only after the artifact's
                        # device work completed, exactly the
                        # materialized() discipline, minus the host
                        # bounce.
                        # graftlint: disable=JGL016 — deliberate: per-key entry lock held across the commit so a second thread can never double-fit the artifact; the lane lock (exempt) serializes the device side
                        value = _shardio().commit(
                            value, spec.sharding, artifact=name
                        )
                with self._lock:
                    self._misses[name] = self._misses.get(name, 0) + 1
                    self._values[key] = value
                return value

    # ── layout delivery (ISSUE 8) ─────────────────────────────────────

    def _deliver(self, spec: ArtifactSpec, key: tuple, value: object,
                 layout: object) -> object:
        """Resolve the stored value into the consumer's declared layout.
        Collective-launching paths (reshard, gather) run inside the
        artifact's lane; the host form is cached per entry so repeated
        host consumers pay one gather total."""
        if spec.sharding is None:
            return value
        if layout == "device" or (
            layout is not None and layout == spec.sharding
        ):
            return _shardio().handoff(value, artifact=spec.name)
        if layout is not None and layout != "host":
            with self._lane_guard(spec):
                return _shardio().reshard(value, layout, artifact=spec.name)
        with self._lock:
            if key in self._host_forms:
                return self._host_forms[key]
        with self._lane_guard(spec):
            with self._entry_lock(("host",) + key):
                with self._lock:
                    if key in self._host_forms:
                        return self._host_forms[key]
                # graftlint: disable=JGL016 — deliberate: the per-key host-form entry lock held across the gather is what makes repeated host consumers pay exactly one gather
                host = _shardio().gather_host(value, artifact=spec.name)
                with self._lock:
                    self._host_forms[key] = host
                return host

    def stats(self) -> dict[str, dict[str, int]]:
        """``{"hits": {...}, "misses": {...}}`` by artifact name (tests
        and the engine's end-of-run summary)."""
        with self._lock:
            return {"hits": dict(self._hits), "misses": dict(self._misses)}

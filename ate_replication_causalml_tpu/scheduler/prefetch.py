"""Compile prefetch lane (ISSUE 4, tentpole part 3).

NEXT.md's open item 3 is a ~25-30 s trace + first-dispatch cold-start
tail at the flagship shape: the host sits in tracing/lowering while the
device idles, stage after stage. The prefetch lane attacks the part
that is cacheable: a single background thread walks the *upcoming*
nodes in schedule order and runs their ``warm`` hooks — AOT
``.lower(...).compile()`` of the stage's jitted entry points on the
run's real shapes — so the persistent compile cache
(``utils/compile_cache.py``) is primed before the stage's turn arrives.
When the foreground stage then calls the same function, XLA's
compilation step is a cache read; only trace+lowering remains.

Policy: prefetch only pays off when compiled executables are reusable
across call sites — i.e. when the persistent compile cache is enabled
(the production ``pipeline.main()`` path) — so :func:`default_enabled`
keys off that, with ``ATE_TPU_SWEEP_PREFETCH=1/0`` as the explicit
override. On a cache-less CPU test run, warming would compile every
executable twice for nothing.

The lane must never affect results: warm hooks compile, they do not
execute estimator numerics, and every failure is swallowed into the
``scheduler_prefetch_total{status=error}`` counter plus a
``prefetch_error`` event (never silently —
graftlint JGL007).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Sequence

from ate_replication_causalml_tpu import observability as obs

_ENV = "ATE_TPU_SWEEP_PREFETCH"


def default_enabled() -> bool:
    """Prefetch default: on when the persistent compile cache is
    configured (compiles are reusable), off otherwise; the env knob
    overrides either way."""
    env = os.environ.get(_ENV, "").strip()
    if env in ("0", "1"):
        return env == "1"
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:  # noqa: BLE001 — no jax / old config: no prefetch
        return False


class CompilePrefetcher:
    """Background thread running ``warm`` hooks in schedule order.

    ``items`` are ``(name, warm)`` pairs in the order the engine expects
    to need them; ``started`` is a callback telling the lane whether the
    foreground already claimed a node (warming it then is wasted work —
    the stage is already tracing it on the hot path, and XLA dedupes
    concurrent identical compiles at the cache layer anyway).
    """

    def __init__(
        self,
        items: Sequence[tuple[str, Callable[[], object] | None]],
        started: Callable[[str], bool] = lambda name: False,
        span_parent: str | None = None,
    ):
        self._items = [(n, w) for n, w in items if w is not None]
        self._started = started
        # Explicit parentage: prefetch spans open on the lane's own
        # thread, where the run's root span is not on the local stack.
        self._span_parent = span_parent
        self._stop = threading.Event()
        # Guards the handle: start() is called from the engine's run
        # thread and stop() from whichever thread finishes the sweep —
        # unguarded, a double start leaks a prefetch lane (JGL019).
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._counter = obs.counter(
            "scheduler_prefetch_total",
            "compile-prefetch lane outcomes by stage and status",
        )
        self._hist = obs.histogram(
            "scheduler_prefetch_seconds", "per-node prefetch compile seconds"
        )

    def start(self) -> None:
        if not self._items:
            return
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="compile-prefetch", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float | None = None) -> None:
        """Signal the lane to stop after the current hook and join.
        Called when the sweep finishes — a leftover warm compile must
        not outlive the run's telemetry export."""
        self._stop.set()
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:  # join outside the lock: never block start()
            thread.join(timeout)

    def _run(self) -> None:
        for name, warm in self._items:
            if self._stop.is_set():
                return
            if self._started(name):
                self._counter.inc(1, node=name, status="skipped")
                continue
            t0 = time.perf_counter()
            try:
                # track="prefetch": the trace's dedicated prefetch-lane
                # track — warm compiles must be visibly overlapped with
                # (not interleaved into) the worker tracks.
                with obs.span("prefetch_compile",
                              parent_id=self._span_parent,
                              node=name, track="prefetch"):
                    warm()
            except Exception as e:  # noqa: BLE001 — a prefetch failure
                # must never fail the sweep; it is recorded, not raised
                # (the foreground stage will compile for itself).
                self._counter.inc(1, node=name, status="error")
                obs.emit("prefetch_error", status="error", node=name,
                         error=f"{type(e).__name__}: {e}")
                continue
            self._hist.observe(time.perf_counter() - t0, node=name)
            self._counter.inc(1, node=name, status="compiled")

"""DAG-scheduled sweep engine (ISSUE 4, tentpole part 1).

A bounded worker pool executes *ready* nodes — nuisance artifacts and
estimator stages — concurrently. JAX releases the GIL during device
execution and XLA compilation, so host threads overlap stage B's
trace/compile with stage A's device compute; that overlap, not
estimator-internal parallelism, is where the sweep's wall-clock goes.

Determinism contract (the hard constraint, asserted in
``tests/test_scheduler.py`` and the resilience sweep tests):

* every stage computes exactly the function it computed sequentially,
  on exactly the same inputs — per-stage fold-in keys
  (``pipeline.key_for``) make stage numerics independent of execution
  order, and the :class:`~.cache.NuisanceCache` guarantees a shared
  artifact is fit once, by one thread, from its declared key;
* **commit order is declaration order**: journal appends, report rows,
  log lines and failure records run through an ordered committer —
  stage k's commit runs only after stages 0..k-1 committed, whatever
  order the bodies finished in. A crash therefore leaves the same
  journal prefix shape a sequential run would (later finished-but-
  uncommitted rows are recomputed on resume — checkpoint semantics
  from ISSUE 3 survive unchanged);
* an abort-class exception (``fail_policy="raise"``, a malformed
  chaos spec) surfaces as the earliest *declared* failing stage. Nodes
  declared *before* that stage keep running to completion so their
  rows commit, and commits flush exactly up to the failing stage —
  byte-for-byte the journal a sequential run leaves behind. Operator
  aborts (^C, SystemExit) stop scheduling immediately instead: the
  committed prefix is best-effort, just as it is sequentially.

``workers=1`` is the ``--sequential`` escape hatch: the same node
graph, executed inline on the calling thread in priority order (an
artifact immediately before its first consumer — the lazy-fit order
the old driver had), with the prefetch lane off. No threads are
created at all, which is exactly what you want under a debugger.
"""

from __future__ import annotations

import contextlib
import heapq
import os
import threading
import time
from typing import Callable, Iterable

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.resilience.watchdog import (
    HeartbeatRegistry,
    lane_bound_s,
)
from ate_replication_causalml_tpu.scheduler.cache import NuisanceCache
from ate_replication_causalml_tpu.scheduler.dag import (
    ArtifactSpec,
    StageSpec,
    validate,
)
from ate_replication_causalml_tpu.scheduler.prefetch import (
    CompilePrefetcher,
    default_enabled,
)

_WORKERS_ENV = "ATE_TPU_SWEEP_WORKERS"


def default_workers() -> int:
    """Worker-pool width: ``ATE_TPU_SWEEP_WORKERS`` if set, else
    ``min(4, cpu_count)`` — the sweep overlaps host trace/compile with
    device compute, so width past a few threads only adds contention."""
    env = os.environ.get(_WORKERS_ENV, "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(4, os.cpu_count() or 1))


class _Node:
    __slots__ = (
        "kind", "name", "priority", "deps", "exec", "stage_idx", "exclusive",
    )

    def __init__(self, kind, name, priority, deps, exec_fn, stage_idx,
                 exclusive=None):
        self.kind = kind            # "artifact" | "stage"
        self.name = name
        self.priority = priority
        self.deps = deps            # tuple of node names
        self.exec = exec_fn
        self.stage_idx = stage_idx  # commit index for stages; the first
        #                             consumer's index for artifacts
        self.exclusive = exclusive  # lane name (see dag.ArtifactSpec)


class SweepEngine:
    """Execute a validated stage DAG over a shared nuisance cache."""

    def __init__(
        self,
        artifacts: Iterable[ArtifactSpec],
        stages: Iterable[StageSpec],
        *,
        commit: Callable[[StageSpec, object], None] | None = None,
        workers: int | None = None,
        prefetch: bool | None = None,
        cache: NuisanceCache | None = None,
        span_parent: str | None = None,
        stall_bound_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        arts = list(artifacts)
        self.dag = validate(arts, stages)
        self.cache = cache if cache is not None else NuisanceCache(arts)
        # Clamp like default_workers clamps the env var: workers<=0 must
        # not spawn zero threads and return an empty result dict.
        self.workers = default_workers() if workers is None else max(1, workers)
        self.prefetch = default_enabled() if prefetch is None else prefetch
        self._commit_fn = commit
        # Explicit span parentage for the trace layer (ISSUE 5): node /
        # commit / prefetch spans open on threads where the caller's
        # root span is not on the thread-local stack.
        self._span_parent = span_parent
        self._mu = threading.Condition()
        # Shared scheduling state — every mutation below happens under
        # self._mu (graftlint JGL008 enforces this).
        self._ready: list[tuple] = []           # heap of (priority, name)
        self._indegree: dict[str, int] = {}
        self._dependents: dict[str, list[str]] = {}
        self._started: set[str] = set()
        self._finished: set[str] = set()
        self._inflight = 0
        self._remaining = 0
        self._results: dict[str, object] = {}
        self._outcomes: dict[int, tuple[StageSpec, object]] = {}
        self._next_commit = 0
        self._commit_busy = False
        self._abort: list[tuple[int, BaseException]] = []
        self._busy_lanes: set[str] = set()
        # Liveness plane (ISSUE 14): workers and the mesh lane stamp
        # heartbeats around every unit of work; a monitor thread (armed
        # by stall_bound_s / ATE_TPU_WATCHDOG_SWEEP_S, 0 = off) watches
        # for "ready or in-flight nodes but no COMPLETION within the
        # bound" — the PR 4 collective-rendezvous deadlock shape — and
        # dumps an attributed stall diagnostic instead of wedging
        # silently. Graceful drain (request_drain) stops scheduling new
        # nodes; in-flight nodes finish and their declared-order commit
        # prefix flushes, so a drained sweep resumes exactly.
        self._clock = clock
        self.heartbeats = HeartbeatRegistry(clock=clock)
        self.stall_bound_s = (
            lane_bound_s("sweep", 0.0)
            if stall_bound_s is None else float(stall_bound_s)
        )
        self._last_completion = clock()
        self._stall_reported = False
        self._draining = False
        self._monitor_stop = threading.Event()
        self._nodes = self._build_nodes()

    # ── graph construction ────────────────────────────────────────────

    def _build_nodes(self) -> dict[str, _Node]:
        dag = self.dag
        nodes: dict[str, _Node] = {}
        # Only artifacts some stage transitively consumes are scheduled:
        # a fully resumed sweep declares no needs and fits nothing.
        needed = set(dag.first_consumer)
        order = {name: i for i, name in enumerate(dag.artifacts)}
        for name in needed:
            spec = dag.artifacts[name]
            prio = (dag.first_consumer[name], 0, -dag.depth[name], order[name])
            nodes[name] = _Node(
                "artifact", name, prio,
                tuple(d for d in spec.needs if d in needed),
                # ensure(), not get(): the artifact node PRODUCES the
                # stored (device-resident) form — layout delivery and
                # its byte metering belong to consumer edges only.
                (lambda nm=name: self.cache.ensure(nm)),
                dag.first_consumer[name],
                exclusive=spec.exclusive,
            )
        for i, spec in enumerate(dag.stages):
            nodes[spec.name] = _Node(
                "stage", spec.name, (i, 1, 0, 0),
                tuple(d for d in spec.needs if d in needed),
                # Stage bodies resolve artifacts through a cache view
                # bound to their consumes_sharding declaration (ISSUE
                # 8): laned consumers take device-resident handoffs,
                # everyone else gets the safe host form.
                (lambda sp=spec: sp.run(self.cache.view_for(sp))),
                i,
                exclusive=spec.exclusive,
            )
        return nodes

    # ── public API ────────────────────────────────────────────────────

    def run(self) -> dict[str, object]:
        """Execute the DAG; returns ``{stage name: value}``.

        Raises the earliest-declared aborting exception, with commits
        flushed exactly up to (not including) that stage.
        """
        with self._mu:
            self._remaining = len(self._nodes)
            for name, node in self._nodes.items():
                self._indegree[name] = len(node.deps)
                for dep in node.deps:
                    self._dependents.setdefault(dep, []).append(name)
            for name, node in self._nodes.items():
                if self._indegree[name] == 0:
                    heapq.heappush(self._ready, (node.priority, name))
        obs.gauge("scheduler_workers", "sweep worker-pool width").set(
            float(self.workers)
        )
        prefetcher = None
        if self.prefetch and self.workers > 1:
            items = sorted(self._nodes.values(), key=lambda n: n.priority)
            warm_of = {
                **{a.name: a.warm for a in self.dag.artifacts.values()},
                **{s.name: s.warm for s in self.dag.stages},
            }
            prefetcher = CompilePrefetcher(
                [(n.name, warm_of.get(n.name)) for n in items],
                started=self._was_started,
                span_parent=self._span_parent,
            )
            prefetcher.start()
        monitor = None
        if self.stall_bound_s > 0:
            monitor = threading.Thread(
                target=self._monitor, name="sweep-watchdog", daemon=True
            )
            monitor.start()
        try:
            if self.workers == 1:
                self._run_inline()
            else:
                threads = [
                    threading.Thread(
                        target=self._worker, name=f"sweep-worker-{i}",
                        daemon=True,
                    )
                    for i in range(self.workers)
                ]
                for t in threads:
                    t.start()
                try:
                    for t in threads:
                        # Bounded joins (JGL012): a wedged worker keeps
                        # the wait visible to ^C and the monitor.
                        while t.is_alive():
                            t.join(0.5)
                except BaseException as e:  # noqa: BLE001 — a real ^C
                    # lands HERE: CPython delivers SIGINT to the main
                    # thread (blocked in join), never to a worker. Flag
                    # the operator abort so workers stop taking nodes,
                    # drain in-flight work, and surface the interrupt
                    # through the normal abort path (commits truncate
                    # before index 0 — the best-effort-prefix contract).
                    self._operator_abort(e)
                    for t in threads:
                        while t.is_alive():
                            t.join(0.5)
        finally:
            self._monitor_stop.set()
            if monitor is not None:
                monitor.join(5.0)
            if prefetcher is not None:
                prefetcher.stop(timeout=60.0)
        self._flush_commits()
        with self._mu:
            if self._abort:
                idx, exc = min(self._abort, key=lambda ae: ae[0])
                obs.emit("scheduler_abort", status="error",
                         stage_index=idx, error=type(exc).__name__)
                raise exc
            return dict(self._results)

    # ── execution ─────────────────────────────────────────────────────

    def _was_started(self, name: str) -> bool:
        with self._mu:
            return name in self._started

    def _operator_abort(self, exc: BaseException) -> None:
        """Record an operator abort delivered OUTSIDE a stage body (a
        real ^C interrupts the main thread's join, not a worker).
        Index −1 sorts before every stage: workers stop taking nodes,
        no further commits flush, and ``run()`` re-raises ``exc``."""
        with self._mu:
            self._abort.append((-1, exc))
            self._mu.notify_all()

    def _take(self) -> _Node | None:
        """Next ready node by priority, or None when drained.
        Blocks while work is in flight that may unlock more nodes.

        Lanes are a SCHEDULING constraint, not a blocking lock: a ready
        node whose lane is occupied is skipped (left queued) and the
        worker takes the next ready node instead — a pool of two must
        not idle one worker behind a long mesh-lane stage while unlaned
        stages sit ready (measured: that turned the 2-worker sweep into
        sequential-plus-overhead).

        After an abort, nodes declared *before* the earliest aborting
        stage keep being scheduled (the committed journal prefix must
        match a sequential run's, and sequentially every earlier stage
        finished before the failing one raised); later nodes are
        skipped. Operator aborts (^C/SystemExit) stop scheduling
        outright."""
        with self._mu:
            while True:
                if self._remaining == 0:
                    return None
                if self._draining:
                    # Graceful drain: no NEW nodes; in-flight ones
                    # finish and commit their declared-order prefix.
                    return None
                stop_at: int | None = None
                if self._abort:
                    if any(
                        isinstance(e, (KeyboardInterrupt, SystemExit))
                        for _, e in self._abort
                    ):
                        return None
                    stop_at = min(a for a, _ in self._abort)
                skipped: list[tuple] = []
                picked: _Node | None = None
                while self._ready:
                    prio, name = heapq.heappop(self._ready)
                    node = self._nodes[name]
                    if stop_at is not None and node.stage_idx >= stop_at:
                        skipped.append((prio, name))
                        continue
                    if (
                        node.exclusive is not None
                        and node.exclusive in self._busy_lanes
                    ):
                        skipped.append((prio, name))
                        continue
                    picked = node
                    break
                for item in skipped:
                    heapq.heappush(self._ready, item)
                if picked is not None:
                    self._started.add(picked.name)
                    self._inflight += 1
                    if picked.exclusive is not None:
                        self._busy_lanes.add(picked.exclusive)
                    return picked
                if stop_at is not None and self._inflight == 0:
                    # Aborted and nothing in flight can unlock an
                    # earlier-declared node — drain the pool.
                    return None
                # Bounded wait (JGL012): the loop re-checks state each
                # pass, so a missed notify can delay a worker by at
                # most the timeout, never wedge it invisibly.
                self._mu.wait(0.5)

    def _finish(self, node: _Node, value, error: BaseException | None) -> None:
        with self._mu:
            self._remaining -= 1
            self._inflight -= 1
            self._finished.add(node.name)
            # Progress instant for the stall monitor: a completion ends
            # any stall episode (the next one re-reports).
            self._last_completion = self._clock()
            self._stall_reported = False
            if node.exclusive is not None:
                self._busy_lanes.discard(node.exclusive)
            for dep_name in self._dependents.get(node.name, ()):
                self._indegree[dep_name] -= 1
                if self._indegree[dep_name] == 0:
                    dep = self._nodes[dep_name]
                    heapq.heappush(self._ready, (dep.priority, dep_name))
            if node.kind == "stage":
                if error is None:
                    self._results[node.name] = value
                    self._outcomes[node.stage_idx] = (
                        self.dag.stages[node.stage_idx], value
                    )
                else:
                    self._abort.append((node.stage_idx, error))
            elif error is not None and isinstance(
                error, (KeyboardInterrupt, SystemExit)
            ):
                # An operator abort inside an artifact fit stops the
                # run; an ordinary artifact failure does not — each
                # consumer stage retries the fit under its own
                # isolation policy, exactly as the lazy sequential
                # driver did.
                self._abort.append((node.stage_idx, error))
            self._mu.notify_all()

    def _exec(self, node: _Node) -> None:
        t0 = time.perf_counter()
        value, error = None, None
        worker_lane = f"worker/{threading.current_thread().name}"
        self.heartbeats.beat(worker_lane)
        if node.exclusive is not None:
            self.heartbeats.beat(f"lane/{node.exclusive}")
        inj = chaos.active()
        if inj is not None:
            # hang: chaos (ISSUE 14) — a deterministic stall INSIDE the
            # stamped unit of work, keyed on the node name. Nothing
            # raises; results stay bit-identical to a stall-free run.
            stall = inj.hang_delay_s("worker", node.name)
            if stall > 0:
                time.sleep(stall)
        # The node's execution interval, with lane/worker/dependency
        # attribution (ISSUE 5): the trace exporter renders these spans
        # as the per-worker timeline tracks, duplicates laned ones onto
        # the lane-occupancy track, and draws artifact->stage flow
        # arrows from the ``needs`` list.
        with obs.span(
            "scheduler_node", parent_id=self._span_parent,
            node=node.name, kind=node.kind, lane=node.exclusive or "",
            worker=threading.current_thread().name,
            stage_idx=node.stage_idx, needs=",".join(node.deps),
        ) as nsp:
            try:
                # Lane exclusivity (multi-device collective launches —
                # see dag.ArtifactSpec.exclusive) is enforced two ways:
                # the scheduling skip in _take/_finish keeps two laned
                # NODES from overlapping, and the re-entrant lane lock
                # below additionally fences the cache's refit path — a
                # consumer stage retrying a FAILED laned artifact
                # (cache.get inside an unlaned stage body) must not
                # launch that collective while a laned node is
                # executing.
                guard = (
                    self.cache.lane_lock(node.exclusive)
                    if node.exclusive is not None
                    else contextlib.nullcontext()
                )
                with guard:
                    value = node.exec()
            except BaseException as e:  # noqa: BLE001 — routed to the
                # declared-order abort/degrade logic in _finish; never
                # swallowed (graftlint JGL007: errors become the run's
                # exception or the consumer stage's failure row).
                error = e
                nsp.set_status("error")
                nsp.set_attr("error_type", type(e).__name__)
                if node.kind == "artifact" and not isinstance(
                    e, (KeyboardInterrupt, SystemExit)
                ):
                    obs.emit("artifact_fit_failed", status="error",
                             artifact=node.name,
                             error=f"{type(e).__name__}: {e}")
        obs.histogram(
            "scheduler_node_seconds", "per-node execution seconds"
        ).observe(time.perf_counter() - t0, kind=node.kind)
        self.heartbeats.beat(worker_lane)
        if node.exclusive is not None:
            self.heartbeats.beat(f"lane/{node.exclusive}")
        self._finish(node, value, error)
        self._flush_commits()

    def _worker(self) -> None:
        while True:
            node = self._take()
            if node is None:
                return
            self._exec(node)

    def _run_inline(self) -> None:
        """The workers=1 path: the identical worker loop run on the
        calling thread — same graph, same commit ordering, zero threads
        (the ``--sequential`` debugging contract)."""
        self._worker()

    # ── liveness & drain (ISSUE 14) ───────────────────────────────────

    def request_drain(self) -> None:
        """Graceful drain: stop scheduling NEW nodes; in-flight nodes
        complete, the declared-order commit prefix flushes, and
        ``run()`` returns the partial results WITHOUT raising — the
        checkpoint journal then holds exactly the prefix a sequential
        run stopped at the same point would, so a resumed run is
        cell-exact (the scenario-matrix SIGTERM contract)."""
        with self._mu:
            if self._draining:
                return
            self._draining = True
            self._mu.notify_all()
        obs.emit("scheduler_drain", status="ok")

    @property
    def draining(self) -> bool:
        with self._mu:
            return self._draining

    def _remaining_critical_path(self) -> list[str]:
        """The would-be critical path through the UNFINISHED nodes:
        the longest dependency chain (by node count — durations are
        unknowable for work that never ran) over declared ``needs``
        edges. Pure and deterministic; the stall diagnostic's "what is
        this run waiting on" line."""
        with self._mu:
            done = set(self._finished)
        remaining = [n for n in self._nodes if n not in done]
        depth: dict[str, tuple[int, tuple[str, ...]]] = {}

        def chain(name: str) -> tuple[int, tuple[str, ...]]:
            got = depth.get(name)
            if got is not None:
                return got
            best = (1, (name,))
            for dep in self._nodes[name].deps:
                if dep in done or dep not in self._nodes:
                    continue
                d, path = chain(dep)
                if d + 1 > best[0]:
                    best = (d + 1, path + (name,))
            depth[name] = best
            return best

        best_path: tuple[str, ...] = ()
        for name in remaining:
            _, path = chain(name)
            if len(path) > len(best_path):
                best_path = path
        return list(best_path)

    def stall_diagnostic(self, now: float | None = None) -> dict:
        """The attributed artifact a detected stall dumps: would-be
        critical path through the unfinished nodes, per-lane
        last-heartbeat ages, held lanes ("locks"), and the in-flight /
        ready node sets. Pure read — callable any time."""
        now = self._clock() if now is None else now
        with self._mu:
            ready = sorted(name for _, name in self._ready)
            inflight = sorted(self._started - self._finished)
            held = sorted(self._busy_lanes)
            since = now - self._last_completion
        return {
            "seconds_since_completion": round(since, 6),
            "ready": ready,
            "started_unfinished": inflight,
            "held_lanes": held,
            "heartbeat_ages": {
                lane: round(age, 6)
                for lane, age in self.heartbeats.ages(now).items()
            },
            "critical_path": self._remaining_critical_path(),
        }

    def _check_stall(self, now: float | None = None) -> bool:
        """One monitor pass: ready-or-inflight nodes but no completion
        within the bound ⇒ dump the diagnostic (event log +
        ``watchdog_stalls_total{lane=sweep}``), once per episode."""
        now = self._clock() if now is None else now
        with self._mu:
            busy = self._remaining > 0 and (
                self._inflight > 0 or bool(self._ready)
            )
            since = now - self._last_completion
            due = (
                busy and not self._stall_reported
                and since > self.stall_bound_s
            )
            if due:
                self._stall_reported = True
        if not due:
            return False
        diag = self.stall_diagnostic(now)
        obs.counter(
            "watchdog_stalls_total",
            "watchdog-detected lane stall episodes",
        ).inc(1, lane="sweep")
        obs.emit("scheduler_stall", status="error", **{
            "since_s": diag["seconds_since_completion"],
            "bound_s": self.stall_bound_s,
            "critical_path": ",".join(diag["critical_path"]),
            "held_lanes": ",".join(diag["held_lanes"]),
            "started_unfinished": ",".join(diag["started_unfinished"]),
            "heartbeat_ages": ",".join(
                f"{k}={v:.3f}" for k, v in diag["heartbeat_ages"].items()
            ),
        })
        return True

    def _monitor(self) -> None:
        poll = max(0.01, min(0.25, self.stall_bound_s / 4.0))
        while not self._monitor_stop.wait(poll):
            self._check_stall()

    # ── ordered commit ────────────────────────────────────────────────

    def _flush_commits(self) -> None:
        """Run pending commits in declaration order. Single committer at
        a time; commits never run while the engine lock is held (they do
        journal I/O and user logging)."""
        while True:
            with self._mu:
                if self._commit_busy:
                    return
                idx = self._next_commit
                if idx not in self._outcomes:
                    return
                if self._abort and idx >= min(a for a, _ in self._abort):
                    return
                spec, value = self._outcomes.pop(idx)
                self._commit_busy = True
            try:
                if self._commit_fn is not None:
                    # track="committer": the trace's dedicated committer
                    # track — ordered-commit stall time must be visible
                    # as its own lane, not buried in a worker's track.
                    with obs.span("commit", parent_id=self._span_parent,
                                  stage=spec.name, stage_idx=idx,
                                  track="committer"):
                        self._commit_fn(spec, value)
            except BaseException as e:  # noqa: BLE001 — a commit
                # failure (disk full mid-journal-append) aborts the run
                # at this stage, like a sequential write failure would.
                with self._mu:
                    self._abort.append((idx, e))
                    self._commit_busy = False
                    self._next_commit = idx + 1
                    self._mu.notify_all()
                return
            with self._mu:
                self._commit_busy = False
                self._next_commit = idx + 1
                self._mu.notify_all()

"""Stage DAG model for the concurrent sweep (ISSUE 4).

Chernozhukov et al. (2018, arXiv:1608.00060) makes the sweep's real
shape explicit: AIPW / DML / Belloni / IPW are different *combinations*
of a small set of shared cross-fit nuisances, so the estimator sweep is
a DAG over nuisance artifacts, not a list of independent blobs. This
module is the declaration layer: estimator stages name the artifacts
they consume, artifacts name the artifacts *they* consume (the LASSO
propensity path consumes its fold masks), and :func:`validate` turns
the declarations into the dependency structure the engine schedules.

Nothing here imports jax or runs work — specs carry plain callables.
The split matters for testing: the adversarial-interleaving tests in
``tests/test_scheduler.py`` drive the engine with synthetic specs, no
estimators involved.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable


class DagError(ValueError):
    """A malformed sweep declaration: duplicate node names, a stage or
    artifact consuming an artifact nobody declared, or an artifact
    dependency cycle. Raised at build time — a bad DAG must fail before
    any estimator runs, not deadlock the worker pool."""


@dataclasses.dataclass(frozen=True)
class ArtifactSpec:
    """One fit-once nuisance artifact.

    ``fit`` receives the :class:`~.cache.NuisanceCache` as a resolver so
    an artifact can consume other artifacts (declared in ``needs``).
    ``key`` is the cache identity *beyond* the name — data fingerprint
    and the config knobs the fit reads — so two sweeps with different
    configs can never share an artifact (ISSUE 4 cache contract).
    """

    name: str
    fit: Callable[[object], object]
    needs: tuple[str, ...] = ()
    key: tuple = ()
    #: optional compile-prefetch hook: AOT lower+compile this artifact's
    #: executables (see prefetch.py). Must be side-effect-free on
    #: numerics.
    warm: Callable[[], object] | None = None
    #: nodes sharing a non-None lane name never execute concurrently.
    #: The sweep uses lane "mesh" for every node that launches a
    #: multi-device collective program: two collective launches racing
    #: from different host threads can interleave their per-device
    #: executions and deadlock the rendezvous (observed on the 8-virtual-
    #: device CPU backend), so collectives keep a single global launch
    #: order while non-collective stages overlap freely.
    exclusive: str | None = None
    #: declared device-resident layout (ISSUE 8): opaque to this
    #: jax-free module — a ``jax.sharding.Sharding`` in practice. When
    #: set, the cache commits the fit's output onto it (inside the
    #: artifact's lane, blocked until drained — parallel/shardio.py)
    #: and stores the device-resident form; consumers receive the
    #: layout they declared via ``consumes_sharding``, defaulting to
    #: the safe host-gathered form (a sharded array held by an unlaned
    #: stage would compile its ops into collectives outside the lane —
    #: the PR-4 rule). None = plain host value, pre-ISSUE-8 semantics.
    sharding: object | None = None
    #: artifact name → layout this fit consumes its ``needs`` inputs
    #: in: ``"device"`` (the stored device-resident form, zero host
    #: bytes), ``"host"`` (explicit host gather), or a sharding object
    #: (reshard to that layout, inside the producer's lane). Keys must
    #: be a subset of ``needs`` and may only name sharded artifacts —
    #: :func:`validate` rejects anything else at build time.
    consumes_sharding: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One sweep stage (estimator, oracle, ...) in declared order.

    ``run`` receives the cache resolver; ``needs`` names the artifacts
    it consumes. The engine guarantees that journal/report/log commit
    order follows declaration order regardless of completion order, so
    the declaration list IS the notebook order contract.
    """

    name: str
    run: Callable[[object], object]
    needs: tuple[str, ...] = ()
    warm: Callable[[], object] | None = None
    #: see ArtifactSpec.exclusive.
    exclusive: str | None = None
    #: see ArtifactSpec.consumes_sharding — the engine binds each stage
    #: body to a cache view resolving ``get(name)`` in the declared
    #: layout; undeclared sharded artifacts arrive host-gathered.
    consumes_sharding: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Dag:
    """Validated DAG: specs by name plus the artifact depth map used to
    order artifact nodes ahead of their first consumer."""

    artifacts: dict[str, ArtifactSpec]
    stages: tuple[StageSpec, ...]
    #: artifact name -> longest chain of artifact-to-artifact deps below
    #: it (leaves are 0). Deeper artifacts must be fit first.
    depth: dict[str, int]
    #: artifact name -> index of the earliest declared stage that
    #: (transitively) consumes it.
    first_consumer: dict[str, int]


def _closure(artifacts: dict[str, ArtifactSpec], roots: Iterable[str]) -> set[str]:
    """All artifacts reachable from ``roots`` through ``needs`` edges."""
    seen: set[str] = set()
    todo = list(roots)
    while todo:
        name = todo.pop()
        if name in seen:
            continue
        seen.add(name)
        todo.extend(artifacts[name].needs)
    return seen


def validate(
    artifacts: Iterable[ArtifactSpec], stages: Iterable[StageSpec]
) -> Dag:
    """Check the declarations and derive scheduling metadata.

    Raises :class:`DagError` on duplicate names, unknown ``needs``
    references, or artifact cycles. Stage-to-stage edges do not exist by
    construction (stages only consume artifacts), so stages can never
    form a cycle.
    """
    arts: dict[str, ArtifactSpec] = {}
    for a in artifacts:
        if a.name in arts:
            raise DagError(f"duplicate artifact {a.name!r}")
        arts[a.name] = a
    stage_list = tuple(stages)
    seen_stages: set[str] = set()
    for s in stage_list:
        if s.name in seen_stages or s.name in arts:
            raise DagError(f"duplicate node name {s.name!r}")
        seen_stages.add(s.name)
    for a in arts.values():
        for dep in a.needs:
            if dep not in arts:
                raise DagError(
                    f"artifact {a.name!r} needs unknown artifact {dep!r}"
                )
    for s in stage_list:
        for dep in s.needs:
            if dep not in arts:
                raise DagError(f"stage {s.name!r} needs unknown artifact {dep!r}")
    # Layout declarations (ISSUE 8) must bind to a consumed, SHARDED
    # artifact: a consumes_sharding key that is not in needs is a typo
    # that would silently fall back to the host form, and a layout for
    # an unsharded artifact has no device-resident form to resolve.
    for kind, spec in (
        [("artifact", a) for a in arts.values()]
        + [("stage", s) for s in stage_list]
    ):
        for dep in spec.consumes_sharding:
            if dep not in spec.needs:
                raise DagError(
                    f"{kind} {spec.name!r} declares consumes_sharding for "
                    f"{dep!r} it does not consume"
                )
            if arts[dep].sharding is None:
                raise DagError(
                    f"{kind} {spec.name!r} declares a consume layout for "
                    f"unsharded artifact {dep!r}"
                )

    # Artifact depth by DFS; a cycle surfaces as revisiting the active
    # path. Iterative (the sweep DAG is tiny, but a declaration bug
    # must produce DagError, not RecursionError).
    depth: dict[str, int] = {}
    state: dict[str, int] = {}  # 1 = on path, 2 = done
    for root in arts:
        if state.get(root) == 2:
            continue
        state[root] = 1
        stack = [(root, iter(arts[root].needs))]
        while stack:
            name, deps = stack[-1]
            for dep in deps:
                st = state.get(dep)
                if st == 2:
                    continue
                if st == 1:
                    path = tuple(n for n, _ in stack)
                    cyc = " -> ".join(path + (dep,))
                    raise DagError(f"artifact dependency cycle: {cyc}")
                state[dep] = 1
                stack.append((dep, iter(arts[dep].needs)))
                break
            else:
                stack.pop()
                state[name] = 2
                depth[name] = max(
                    (depth[d] + 1 for d in arts[name].needs), default=0
                )

    first_consumer: dict[str, int] = {}
    for i, s in enumerate(stage_list):
        for name in _closure(arts, s.needs):
            first_consumer.setdefault(name, i)
            first_consumer[name] = min(first_consumer[name], i)
    return Dag(
        artifacts=arts, stages=stage_list, depth=depth,
        first_consumer=first_consumer,
    )

"""Native host runtime — C++ cores behind the data pipeline.

The reference's host-side native code is R's C internals (the MT19937
RNG behind ``set.seed``/``sample``, ``read.csv``'s parser) plus dplyr's
C++ verbs. This package is their TPU-framework equivalent: a small C++
library (``rcompat.cpp``) compiled on demand with the baked-in ``g++``
and bound via ``ctypes`` (no pybind11 in the image — SURVEY.md §2.3).

Everything here is host-side ingest/sampling; TPU compute never calls
into it. Every entry point has a pure-Python/NumPy fallback, and the
Python implementations double as cross-validation oracles in
``tests/test_native.py``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "rcompat.cpp")
_LIB_PATH = os.path.join(_HERE, "_rcompat.so")
_lock = threading.Lock()
_lib = None
_lib_error: str | None = None


def _build() -> None:
    # Compile to a per-process temp path and os.replace() into place:
    # concurrent builders (pytest-xdist, multi-host launches on a shared
    # filesystem) each produce a complete .so and the rename is atomic,
    # so no process can ever dlopen a half-written file. An flock on a
    # sidecar serializes the (cheap) compiles across processes where the
    # filesystem supports it.
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp_path, _SRC,
    ]
    # Open in append mode (no truncation — another holder may have the
    # fd) and best-effort unlink after release: correctness never rests
    # on the lock (the atomic rename below does that), so a racing
    # unlink/reopen at worst runs one redundant compile.
    lock_path = f"{_LIB_PATH}.lock"
    lockfile = open(lock_path, "a")  # noqa: SIM115 — held across build
    try:
        try:
            import fcntl

            fcntl.flock(lockfile, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # no flock (non-POSIX / NFS quirk): atomic rename still safe
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp_path, _LIB_PATH)
    finally:
        lockfile.close()
        for leftover in (tmp_path, lock_path):
            try:
                os.unlink(leftover)
            except OSError:
                pass


def load_library(rebuild: bool = False):
    """Compile (once, cached as ``_rcompat.so``) and dlopen the native
    library. Returns None — with the reason in :func:`native_status` —
    when no toolchain is available; callers fall back to NumPy."""
    global _lib, _lib_error
    with _lock:
        if _lib is not None and not rebuild:
            return _lib
        if _lib_error is not None and not rebuild:
            return None  # don't re-run g++ on every call after a failed build
        _lib_error = None
        try:
            if rebuild or not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            ):
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.CalledProcessError) as e:
            _lib_error = str(e)
            return None
        lib.rcompat_new.restype = ctypes.c_void_p
        lib.rcompat_new.argtypes = [ctypes.c_uint32, ctypes.c_int]
        lib.rcompat_free.argtypes = [ctypes.c_void_p]
        lib.rcompat_runif.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ]
        lib.rcompat_sample_int.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_dims.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_header.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        lib.csv_read_f64.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64,
        ]
        _lib = lib
        return lib


def native_available() -> bool:
    return load_library() is not None


def native_status() -> str:
    if load_library() is not None:
        return f"native: {_LIB_PATH}"
    return f"fallback (native build failed: {_lib_error})"


class NativeRCompatRNG:
    """C++-backed R-compatible RNG with the same interface as
    :class:`~ate_replication_causalml_tpu.utils.rrandom.RCompatRNG`
    (``runif`` / ``sample_int`` / ``sample_n_rows``)."""

    def __init__(self, seed: int, sample_kind: str = "rounding"):
        if sample_kind not in ("rounding", "rejection"):
            raise ValueError(f"bad sample_kind {sample_kind!r}")
        lib = load_library()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_lib_error}")
        self._lib = lib
        self.sample_kind = sample_kind
        self._h = lib.rcompat_new(
            ctypes.c_uint32(seed & 0xFFFFFFFF),
            0 if sample_kind == "rounding" else 1,
        )

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None):
            self._lib.rcompat_free(h)

    def runif(self, n: int) -> np.ndarray:
        out = np.empty(n, dtype=np.float64)
        self._lib.rcompat_runif(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n
        )
        return out

    def sample_int(self, n: int, size: int | None = None, replace: bool = False) -> np.ndarray:
        if size is None:
            size = n
        if not replace and size > n:
            raise ValueError("cannot take a sample larger than the population without replacement")
        out = np.empty(size, dtype=np.int64)
        self._lib.rcompat_sample_int(
            self._h, n, size, 1 if replace else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out

    def sample_n_rows(self, n_rows: int, size: int) -> np.ndarray:
        return self.sample_int(n_rows, size, replace=False)


def make_rcompat_rng(seed: int, sample_kind: str = "rounding", backend: str = "auto"):
    """R-compatible RNG factory: ``backend='auto'`` prefers the C++ core
    and falls back to the NumPy implementation."""
    from ate_replication_causalml_tpu.utils.rrandom import RCompatRNG

    if backend not in ("auto", "native", "python"):
        raise ValueError(
            f"unknown RNG backend {backend!r}: expected 'auto', 'native' or 'python'"
        )
    if backend == "python":
        return RCompatRNG(seed, sample_kind=sample_kind)
    if backend == "native" or native_available():
        return NativeRCompatRNG(seed, sample_kind=sample_kind)
    return RCompatRNG(seed, sample_kind=sample_kind)


def read_csv_native(path: str) -> tuple[list[str], np.ndarray]:
    """C++ numeric CSV reader (``read.csv`` equivalent): returns
    (header names, row-major float64 matrix with NaN for NA/blank).
    Raises RuntimeError if the native library is unavailable."""
    lib = load_library()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_lib_error}")
    bpath = path.encode()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    if lib.csv_dims(bpath, ctypes.byref(rows), ctypes.byref(cols)) != 0:
        raise FileNotFoundError(path)
    buf = ctypes.create_string_buffer(1 << 20)
    rc = lib.csv_header(bpath, buf, len(buf))
    if rc == -2:
        raise ValueError(f"{path}: header line longer than {len(buf)} bytes")
    if rc != 0:
        raise FileNotFoundError(path)
    header = buf.value.decode().split(",")
    out = np.empty((rows.value, cols.value), dtype=np.float64)
    if lib.csv_read_f64(
        bpath, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        rows.value, cols.value,
    ) != 0:
        raise FileNotFoundError(path)
    return header, out

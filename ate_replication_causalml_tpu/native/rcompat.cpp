// Host-side native core: R-compatible RNG + numeric CSV ingest.
//
// The reference's host runtime is R's C internals: the MT19937 stream
// behind set.seed/runif/sample (R RNG.c semantics; invoked at
// ate_replication.Rmd:41-44 and ate_functions.R:269) and read.csv
// (ate_replication.Rmd:33). This library is the TPU framework's
// equivalent of those native cores: it feeds the host data pipeline;
// the TPU compute path never calls into it.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
// The Python class utils/rrandom.py::RCompatRNG implements the same
// stream and doubles as the cross-validation oracle for this code.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int kN = 624;
constexpr int kM = 397;
constexpr uint32_t kMatrixA = 0x9908b0dfu;
constexpr uint32_t kUpperMask = 0x80000000u;
constexpr uint32_t kLowerMask = 0x7fffffffu;
// R scales MT output by 1/(2^32-1), then nudges endpoints into (0,1).
constexpr double kI2_32m1 = 2.3283064365386963e-10;

struct RCompatState {
  uint32_t mt[kN];
  int mti;            // position in the tempered block; kN => regenerate
  int sample_kind;    // 0 = rounding (R < 3.6), 1 = rejection (R >= 3.6)
};

void set_seed(RCompatState* s, uint32_t seed) {
  // R RNG_Init: 50 LCG warm-ups, then 625 LCG words; word 0 is the
  // position counter which FixupSeeds forces to kN (regenerate first).
  for (int i = 0; i < 50; ++i) seed = 69069u * seed + 1u;
  seed = 69069u * seed + 1u;  // word 0 (dummy position slot)
  for (int j = 0; j < kN; ++j) {
    seed = 69069u * seed + 1u;
    s->mt[j] = seed;
  }
  s->mti = kN;
}

void regenerate(RCompatState* s) {
  uint32_t* mt = s->mt;
  uint32_t y;
  for (int kk = 0; kk < kN - kM; ++kk) {
    y = (mt[kk] & kUpperMask) | (mt[kk + 1] & kLowerMask);
    mt[kk] = mt[kk + kM] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
  }
  for (int kk = kN - kM; kk < kN - 1; ++kk) {
    y = (mt[kk] & kUpperMask) | (mt[kk + 1] & kLowerMask);
    mt[kk] = mt[kk + (kM - kN)] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
  }
  y = (mt[kN - 1] & kUpperMask) | (mt[0] & kLowerMask);
  mt[kN - 1] = mt[kM - 1] ^ (y >> 1) ^ ((y & 1u) ? kMatrixA : 0u);
  s->mti = 0;
}

inline double next_unif(RCompatState* s) {
  if (s->mti >= kN) regenerate(s);
  uint32_t t = s->mt[s->mti++];
  t ^= t >> 11;
  t ^= (t << 7) & 0x9d2c5680u;
  t ^= (t << 15) & 0xefc60000u;
  t ^= t >> 18;
  double u = t * kI2_32m1;
  // R fixup(): open interval.
  if (u <= 0.0) u = 0.5 * kI2_32m1;
  if (1.0 - u <= 0.0) u = 1.0 - 0.5 * kI2_32m1;
  return u;
}

// R_unif_index (R >= 3.6): draw ceil(log2(dn)) random bits in 16-bit
// chunks, reject values >= dn.
inline int64_t unif_index(RCompatState* s, int64_t dn) {
  if (dn <= 0) return 0;
  int bits = (int)std::ceil(std::log2((double)dn));
  int64_t dv;
  do {
    dv = 0;
    for (int nb = 0; nb <= bits; nb += 16)
      dv = 65536 * dv + (int64_t)(next_unif(s) * 65536.0);
    dv &= ((int64_t)1 << bits) - 1;
  } while (dv >= dn);
  return dv;
}

}  // namespace

extern "C" {

void* rcompat_new(uint32_t seed, int sample_kind) {
  RCompatState* s = new RCompatState();
  s->sample_kind = sample_kind;
  set_seed(s, seed);
  return s;
}

void rcompat_free(void* h) { delete static_cast<RCompatState*>(h); }

void rcompat_runif(void* h, double* out, int64_t n) {
  RCompatState* s = static_cast<RCompatState*>(h);
  for (int64_t i = 0; i < n; ++i) out[i] = next_unif(s);
}

// R sample.int(n, size, replace) with 0-based output indices.
void rcompat_sample_int(void* h, int64_t n, int64_t size, int replace,
                        int64_t* out) {
  RCompatState* s = static_cast<RCompatState*>(h);
  if (replace) {
    if (s->sample_kind == 0) {
      for (int64_t i = 0; i < size; ++i)
        out[i] = (int64_t)(n * next_unif(s));
    } else {
      for (int64_t i = 0; i < size; ++i) out[i] = unif_index(s, n);
    }
    return;
  }
  // SampleNoReplace: partial Fisher-Yates over a shrinking pool.
  std::vector<int64_t> x((size_t)n);
  for (int64_t i = 0; i < n; ++i) x[(size_t)i] = i;
  int64_t m = n;
  for (int64_t i = 0; i < size; ++i) {
    int64_t j = (s->sample_kind == 0) ? (int64_t)(m * next_unif(s))
                                      : unif_index(s, m);
    out[i] = x[(size_t)j];
    x[(size_t)j] = x[(size_t)--m];
  }
}

// ---------------------------------------------------------------------
// Numeric CSV ingest (read.csv equivalent for the GGL panel layout:
// one header row, comma-separated numeric fields, empty/NA -> NaN).
// Two-call protocol: csv_dims sizes the output, csv_read_f64 fills a
// row-major (rows x cols) buffer. Header names are returned as one
// comma-joined string for the Python side to split.
// ---------------------------------------------------------------------

int csv_dims(const char* path, int64_t* rows, int64_t* cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t r = 0, c = 1;
  int ch;
  bool first_line = true, line_has_data = false;
  while ((ch = std::fgetc(f)) != EOF) {
    if (first_line && ch == ',') ++c;
    if (ch == '\n') {
      if (first_line) first_line = false;
      else if (line_has_data) ++r;  // blank lines are not rows (genfromtxt semantics)
      line_has_data = false;
    } else if (ch != '\r') {
      line_has_data = true;
    }
  }
  if (line_has_data && !first_line) ++r;  // unterminated last line
  std::fclose(f);
  *rows = r;
  *cols = c;
  return 0;
}

int csv_header(const char* path, char* buf, int64_t buflen) {
  // Returns 0 on success, -1 if unreadable, -2 if the header line did
  // not fit in buflen (truncated output — callers must not trust it).
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t i = 0;
  int ch;
  while ((ch = std::fgetc(f)) != EOF && ch != '\n') {
    if (ch == '\r' || ch == '"') continue;
    if (i >= buflen - 1) {  // would overflow: report truncation
      buf[i] = '\0';
      std::fclose(f);
      return -2;
    }
    buf[i++] = (char)ch;
  }
  buf[i] = '\0';
  std::fclose(f);
  return 0;
}

int csv_read_f64(const char* path, double* out, int64_t rows, int64_t cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  // Short/ragged rows must read as missing, not heap garbage.
  const double nan = std::nan("");
  for (int64_t i = 0; i < rows * cols; ++i) out[i] = nan;
  // Skip header.
  int ch;
  while ((ch = std::fgetc(f)) != EOF && ch != '\n') {
  }
  std::vector<char> field;
  field.reserve(64);
  int64_t r = 0, c = 0;
  bool line_has_data = false;
  auto flush = [&](int64_t rr, int64_t cc) {
    if (rr >= rows || cc >= cols) {
      field.clear();
      return;
    }
    field.push_back('\0');
    const char* p = field.data();
    char* end = nullptr;
    double v = std::strtod(p, &end);
    bool ok = end != p && field.size() > 1;
    // Trailing non-whitespace after the number ("1x") is non-numeric —
    // NaN, matching np.genfromtxt (plain strtod would accept 1.0).
    for (; ok && *end != '\0'; ++end) {
      if (*end != ' ' && *end != '\t') ok = false;
    }
    out[rr * cols + cc] = ok ? v : nan;  // "NA", "", non-numeric -> NaN
    field.clear();
  };
  while ((ch = std::fgetc(f)) != EOF) {
    if (ch == ',') {
      flush(r, c);
      ++c;
      line_has_data = true;  // ",," lines are data (all-missing fields)
    } else if (ch == '\n') {
      if (line_has_data) {   // blank lines are not rows (matches csv_dims)
        flush(r, c);
        ++r;
      }
      c = 0;
      line_has_data = false;
    } else if (ch != '\r' && ch != '"') {
      field.push_back((char)ch);
      line_has_data = true;
    }
  }
  if (line_has_data) flush(r, c);
  std::fclose(f);
  return 0;
}

}  // extern "C"

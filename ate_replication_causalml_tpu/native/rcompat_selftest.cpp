// Sanitizer self-test harness for the native host runtime.
//
// SURVEY.md §5.2: the framework's compute path is functionally pure JAX
// (no data races by construction); the only native code is this
// package's C++ host runtime, which CI exercises under ASan/UBSan via
// this standalone binary (tests/test_native.py::test_sanitizer_clean
// builds and runs it when g++ is available).
//
// Checks, against values cross-validated with R and the NumPy oracle:
//   * set.seed(1991) first runif draws,
//   * sample.int determinism and bounds under both sample kinds,
//   * CSV reader on a temp file with NA/blank/short rows.

#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {
void* rcompat_new(uint32_t seed, int sample_kind);
void rcompat_free(void* h);
void rcompat_runif(void* h, double* out, int64_t n);
void rcompat_sample_int(void* h, int64_t n, int64_t size, int replace, int64_t* out);
int csv_dims(const char* path, int64_t* rows, int64_t* cols);
int csv_header(const char* path, char* buf, int64_t buflen);
int csv_read_f64(const char* path, double* out, int64_t rows, int64_t cols);
}

static int failures = 0;
#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                      \
    }                                                                  \
  } while (0)

int main() {
  // set.seed(1991) first draws, per the NumPy oracle implementation of
  // R's RNG.c semantics (utils/rrandom.py — the ctypes tests prove the
  // two streams bit-match end to end).
  void* h = rcompat_new(1991, 0);
  double u[1000];
  rcompat_runif(h, u, 3);
  CHECK(std::fabs(u[0] - 0.15062308) < 1e-7);
  CHECK(std::fabs(u[1] - 0.23083080) < 1e-7);
  CHECK(std::fabs(u[2] - 0.01348260) < 1e-7);
  // Cross a regeneration boundary.
  rcompat_runif(h, u, 1000);
  for (int i = 0; i < 1000; ++i) CHECK(u[i] > 0.0 && u[i] < 1.0);
  rcompat_free(h);

  for (int kind = 0; kind < 2; ++kind) {
    void* a = rcompat_new(42, kind);
    void* b = rcompat_new(42, kind);
    int64_t sa[500], sb[500];
    rcompat_sample_int(a, 10000, 500, 1, sa);
    rcompat_sample_int(b, 10000, 500, 1, sb);
    CHECK(std::memcmp(sa, sb, sizeof sa) == 0);
    for (int i = 0; i < 500; ++i) CHECK(sa[i] >= 0 && sa[i] < 10000);
    // Without replacement: distinct, in range.
    rcompat_sample_int(a, 600, 500, 0, sa);
    bool seen[600] = {false};
    for (int i = 0; i < 500; ++i) {
      CHECK(sa[i] >= 0 && sa[i] < 600);
      CHECK(!seen[sa[i]]);
      seen[sa[i]] = true;
    }
    rcompat_free(a);
    rcompat_free(b);
  }

  // CSV reader on a temp file with NA, blank line, and a short row.
  char path[] = "/tmp/rcompat_selftest_XXXXXX";
  int fd = mkstemp(path);
  CHECK(fd >= 0);
  FILE* f = fdopen(fd, "w");
  std::fputs("a,b,c\n1,NA,3\n\n4,5\n7,8,9\n", f);
  std::fclose(f);
  int64_t rows = 0, cols = 0;
  CHECK(csv_dims(path, &rows, &cols) == 0);
  CHECK(rows == 3 && cols == 3);
  char hdr[64];
  CHECK(csv_header(path, hdr, sizeof hdr) == 0);
  CHECK(std::strcmp(hdr, "a,b,c") == 0);
  double m[9];
  CHECK(csv_read_f64(path, m, rows, cols) == 0);
  CHECK(m[0] == 1.0 && std::isnan(m[1]) && m[2] == 3.0);
  CHECK(m[3] == 4.0 && m[4] == 5.0 && std::isnan(m[5]));
  CHECK(m[6] == 7.0 && m[7] == 8.0 && m[8] == 9.0);
  std::remove(path);

  if (failures) {
    std::fprintf(stderr, "%d check(s) failed\n", failures);
    return 1;
  }
  std::puts("rcompat_selftest: all checks passed");
  return 0;
}

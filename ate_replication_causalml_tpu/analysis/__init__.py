"""graftlint: JAX-aware static analysis for this repository.

The failure classes that silently break TPU bit-parity — jit caches
keyed on ambient backend state (ADVICE.md r5: ``quantile_bins``), PRNG
keys spent twice, dtype drift against the x64 policy, torn artifact
writes, unlocked telemetry state — enforced mechanically instead of by
review. Run ``python scripts/graftlint.py <paths>`` or call
:func:`lint_paths` / :func:`lint_source` directly. The whole-program
concurrency pass (graftrace, JGL015–JGL019) lives in
:mod:`.concurrency`; its committed artifact is built by
``scripts/graftrace.py``.

The analysis modules themselves import no jax (stdlib ``ast`` +
``tokenize`` only). Note that a plain ``import
ate_replication_causalml_tpu.analysis`` still executes the parent
package's ``__init__`` — which pulls the estimator stack and jax; the
``scripts/graftlint.py`` CLI pre-registers a namespace stub for the
parent so the linter runs jax-free in hooks and CI images without an
accelerator stack.
"""

from ate_replication_causalml_tpu.analysis.core import (
    PARSE_ERROR_ID,
    PROGRAM_RULES,
    RULES,
    Finding,
    LintResult,
    ProgramRule,
    Rule,
    all_rules,
    lint_paths,
    lint_source,
    lint_sources,
    register,
    register_program,
)
from ate_replication_causalml_tpu.analysis import rules as _rules  # noqa: F401 — registers JGL001-014
from ate_replication_causalml_tpu.analysis import concurrency as _concurrency  # noqa: F401 — registers JGL015-019
from ate_replication_causalml_tpu.analysis.cache import ResultCache
from ate_replication_causalml_tpu.analysis.reporters import (
    render_human,
    render_json,
    render_rule_table,
    render_sarif,
)

__all__ = [
    "Finding",
    "LintResult",
    "PARSE_ERROR_ID",
    "PROGRAM_RULES",
    "RULES",
    "ProgramRule",
    "ResultCache",
    "Rule",
    "all_rules",
    "lint_paths",
    "lint_source",
    "lint_sources",
    "register",
    "register_program",
    "render_human",
    "render_json",
    "render_rule_table",
    "render_sarif",
]

"""Shared path-scope specs for graftlint rules.

Every path-scoped rule used to hand-roll its own ``_in_scope`` out of
``endswith``/substring checks — and the checks drifted: PR 4's JGL008
bug (``relpath.endswith("pipeline.py")``) roped ``data/pipeline.py``
into a rule meant for the top-level driver only. :class:`Scope` is the
one implementation all rules share, matching on *path segments* so a
directory named ``xscenarios`` can never satisfy a ``scenarios`` scope
and a nested ``pipeline.py`` can never satisfy a top-level one.

Matching semantics (all against ``/``-normalized relpaths):

* ``dirs`` — the named directory appears as a segment anywhere in the
  dirname (``("scheduler",)`` matches ``pkg/scheduler/engine.py``);
* ``files`` — the relpath's tail segments equal the spec
  (``("observability/slo.py",)`` matches ``pkg/observability/slo.py``
  but not ``pkg/observability/slo.pyx`` or ``myslo.py``);
* ``top_files`` — basename match restricted to package depth ≤ 2
  (``("pipeline.py",)`` matches ``pkg/pipeline.py`` and a bare
  ``pipeline.py``, never ``pkg/data/pipeline.py``);
* ``exclude_files`` — tail-segment matches that veto the above (one
  rule per file: JGL006 hands ``observability/slo.py`` to JGL008).
"""

from __future__ import annotations


def _segments(relpath: str) -> list[str]:
    return relpath.replace("\\", "/").split("/")


def _tail_matches(parts: list[str], spec: str) -> bool:
    tail = spec.split("/")
    return len(parts) >= len(tail) and parts[-len(tail):] == tail


class Scope:
    """A declarative path scope; ``contains(relpath)`` is the single
    membership test every scoped rule uses."""

    def __init__(
        self,
        dirs: tuple[str, ...] = (),
        files: tuple[str, ...] = (),
        top_files: tuple[str, ...] = (),
        exclude_files: tuple[str, ...] = (),
    ):
        self.dirs = tuple(dirs)
        self.files = tuple(files)
        self.top_files = tuple(top_files)
        self.exclude_files = tuple(exclude_files)

    def contains(self, relpath: str) -> bool:
        parts = _segments(relpath)
        for spec in self.exclude_files:
            if _tail_matches(parts, spec):
                return False
        dirnames = parts[:-1]
        if any(d in dirnames for d in self.dirs):
            return True
        if any(_tail_matches(parts, spec) for spec in self.files):
            return True
        return parts[-1] in self.top_files and len(parts) <= 2


# ── the shared scope instances (one definition, no drift) ────────────

#: JGL002 — PRNG discipline applies to the scenario drivers.
SCENARIOS = Scope(dirs=("scenarios",))

#: JGL004 — the numerics contract lives in ops/ and estimators/.
DTYPE = Scope(dirs=("ops", "estimators"))

#: JGL005 — the one module allowed to open files for writing.
EXPORT_MODULE = Scope(files=("observability/export.py",))

#: JGL006 — observability shared state (slo.py belongs to JGL008).
OBSERVABILITY_STATE = Scope(
    dirs=("observability",), exclude_files=("observability/slo.py",)
)

#: JGL008/JGL015..19 driver file — the top-level pipeline only.
SCHEDULER_STATE = Scope(
    dirs=("scheduler", "serving"),
    files=("observability/slo.py",),
    top_files=("pipeline.py",),
)

#: JGL007 exemption — the retry/chaos plane is allowed bare excepts.
RESILIENCE_EXEMPT = Scope(dirs=("resilience",), files=("parallel/retry.py",))

#: JGL009 exemption — telemetry may read wall clocks.
WALLCLOCK_EXEMPT = Scope(dirs=("observability",))

#: JGL010 — host transfers belong to the metered artifact plane.
HOST_TRANSFER = Scope(dirs=("scheduler",), top_files=("pipeline.py",))

#: JGL011 — gather-by-row-id belongs to the model kernels.
MODELS = Scope(dirs=("models",))

#: JGL012 — unbounded joins in the serving/scheduler planes.
UNBOUNDED_JOIN = Scope(
    dirs=("serving", "scheduler"), files=("resilience/watchdog.py",)
)

#: JGL014 — label cardinality in the serving/observability planes.
LABEL_CARDINALITY = Scope(dirs=("serving", "observability"))

#: JGL015–JGL019 — the threaded planes the concurrency analyzer walks.
CONCURRENCY = Scope(
    dirs=("scheduler", "serving", "parallel", "observability", "resilience"),
    top_files=("pipeline.py",),
)

#: JGL021 exemption — where metric families ORIGINATE: the registry
#: primitives themselves and the one sanctioned pre-creation site
#: (``install_jax_monitoring``).
METRIC_FAMILY_ORIGIN = Scope(
    files=("observability/device.py", "observability/registry.py"),
)

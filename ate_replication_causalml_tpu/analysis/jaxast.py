"""Shared AST machinery for the JAX-aware rules: jit detection, the
per-module trace reachability graph, and mutable-global discovery.

Terminology: a function is a *trace root* when it is decorated with
``jax.jit``/``pjit`` (directly or through ``functools.partial``) or
wrapped by a call-form ``jax.jit(fn)``. A function is *traced* when it
is a root or is referenced (called, vmapped, passed to ``lax.map``,
captured…) — transitively — from a traced function's own statements.
Reference-based edges over-approximate calls on purpose: a function
handed to ``jax.vmap``/``lax.scan`` is traced without a direct call
node, and a false edge costs at most one suppressible finding, while a
missed edge silently waives the rule.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from ate_replication_causalml_tpu.analysis.core import ModuleInfo

JIT_NAMES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "pjit.pjit",
}

_PARTIAL_NAMES = {"functools.partial"}

#: In-place container mutators — shared by the mutable-global discovery
#: here and JGL006's unlocked-mutation detection (one list, no drift).
MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popleft", "remove", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popitem",
}

FunctionNode = ast.FunctionDef | ast.AsyncFunctionDef


@dataclasses.dataclass
class FunctionRecord:
    node: FunctionNode
    qualname: str
    parent: str | None  # enclosing function qualname, if nested
    jitted: bool = False
    static_names: set[str] = dataclasses.field(default_factory=set)
    #: bare names referenced (Load context) in this function's own
    #: statements, nested defs excluded — the call-graph edge source.
    refs: set[str] = dataclasses.field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def param_names(self) -> list[str]:
        a = self.node.args
        return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]

    def traced_params(self) -> set[str]:
        """Parameter names that are tracers inside the jitted body."""
        out = set(self.param_names()) - self.static_names
        out.discard("self")
        out.discard("cls")
        return out


def _static_arg_values(call: ast.Call) -> tuple[set[str], set[int]]:
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        vals: list[ast.expr]
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = list(kw.value.elts)
        else:
            vals = [kw.value]
        if kw.arg == "static_argnames":
            names |= {
                v.value
                for v in vals
                if isinstance(v, ast.Constant) and isinstance(v.value, str)
            }
        elif kw.arg == "static_argnums":
            nums |= {
                v.value
                for v in vals
                if isinstance(v, ast.Constant) and isinstance(v.value, int)
            }
    return names, nums


def jit_decorator_statics(
    module: ModuleInfo, deco: ast.expr
) -> tuple[set[str], set[int]] | None:
    """``(static_argnames, static_argnums)`` when ``deco`` is a jit/pjit
    decorator (bare, called, or via functools.partial); None otherwise."""
    if module.resolve(deco) in JIT_NAMES:
        return set(), set()
    if isinstance(deco, ast.Call):
        fr = module.resolve(deco.func)
        if fr in JIT_NAMES:
            return _static_arg_values(deco)
        if fr in _PARTIAL_NAMES and deco.args:
            if module.resolve(deco.args[0]) in JIT_NAMES:
                return _static_arg_values(deco)
    return None


def own_statements(fn: FunctionNode) -> Iterator[ast.AST]:
    """Every node lexically in ``fn`` excluding nested function/class
    bodies (those are analyzed as their own scopes) — but including
    nested lambdas, which stay part of the enclosing scope."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            stack.append(child)


def collect_functions(module: ModuleInfo) -> dict[str, FunctionRecord]:
    """All function defs (any nesting), keyed by dotted qualname.

    Memoized per ModuleInfo: several rules need the table, and the
    records are never mutated after collection (``traced_functions``
    returns its reachability verdicts separately), so sharing is safe.
    """
    cached = getattr(module, "_graftlint_functions", None)
    if cached is not None:
        return cached
    records: dict[str, FunctionRecord] = {}

    def visit(node: ast.AST, prefix: str, parent: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                rec = FunctionRecord(node=child, qualname=qual, parent=parent)
                for deco in child.decorator_list:
                    statics = jit_decorator_statics(module, deco)
                    if statics is not None:
                        rec.jitted = True
                        names, nums = statics
                        params = rec.param_names()
                        rec.static_names |= names
                        rec.static_names |= {
                            params[i] for i in nums if i < len(params)
                        }
                for sub in own_statements(child):
                    if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                        rec.refs.add(sub.id)
                records[qual] = rec
                visit(child, qual + ".", qual)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", parent)
            else:
                visit(child, prefix, parent)

    visit(module.tree, "", None)
    module._graftlint_functions = records
    return records


def call_form_jit_roots(
    module: ModuleInfo, records: dict[str, FunctionRecord]
) -> dict[str, tuple[set[str], set[int]]]:
    """Functions wrapped by call-form ``jax.jit(fn)`` anywhere in the
    module (e.g. ``return jax.jit(run)`` in a cached factory), mapped
    to the ``(static_argnames, static_argnums)`` of the wrapping call."""
    by_name: dict[str, list[str]] = {}
    for qual, rec in records.items():
        by_name.setdefault(rec.name, []).append(qual)
    roots: dict[str, tuple[set[str], set[int]]] = {}
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and module.resolve(node.func) in JIT_NAMES):
            continue
        statics = _static_arg_values(node)
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for qual in by_name.get(arg.id, ()):
                    roots[qual] = statics
    return roots


def traced_functions(
    module: ModuleInfo, records: dict[str, FunctionRecord]
) -> dict[str, str | None]:
    """Reachability verdicts: ``qualname -> None`` for trace roots,
    ``qualname -> root_qualname`` for functions reached transitively.
    Pure — ``records`` (shared via the collect_functions memo) is
    never mutated."""
    by_name: dict[str, list[str]] = {}
    for qual, rec in records.items():
        by_name.setdefault(rec.name, []).append(qual)

    roots = {q for q, r in records.items() if r.jitted}
    roots |= set(call_form_jit_roots(module, records))

    traced: dict[str, str | None] = {}
    frontier: list[tuple[str, str]] = [(q, q) for q in sorted(roots)]
    while frontier:
        qual, root = frontier.pop()
        if qual in traced:
            continue
        traced[qual] = None if qual in roots else root
        for name in records[qual].refs:
            for callee in by_name.get(name, ()):
                if callee not in traced and callee != qual:
                    frontier.append((callee, root))
    return traced


def mutable_globals(module: ModuleInfo) -> set[str]:
    """Module-level names that behave like ambient mutable state:
    rebound more than once at module scope, rebound through ``global``,
    or module-level containers that some code mutates in place."""
    assign_counts: dict[str, int] = {}
    container_names: set[str] = set()
    for node in module.tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets, value = [node.target], getattr(node, "value", None)
        for t in targets:
            if isinstance(t, ast.Name):
                assign_counts[t.id] = assign_counts.get(t.id, 0) + 1
                if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                                      ast.DictComp, ast.SetComp)):
                    container_names.add(t.id)
                elif isinstance(value, ast.Call) and (
                    module.resolve(value.func)
                    in {
                        "dict", "list", "set", "collections.deque",
                        "collections.defaultdict", "collections.OrderedDict",
                    }
                ):
                    container_names.add(t.id)

    mutable = {n for n, c in assign_counts.items() if c > 1}
    mutated_containers: set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Global):
            mutable.update(node.names)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, ast.Assign) else (
                [node.target] if isinstance(node, ast.AugAssign) else node.targets
            )
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                    mutated_containers.add(t.value.id)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and node.func.attr in MUTATOR_METHODS:
                mutated_containers.add(base.id)
    mutable |= container_names & mutated_containers
    return mutable

"""Finding reporters: human-readable text and machine-readable JSON."""

from __future__ import annotations

import json

from ate_replication_causalml_tpu.analysis.core import RULES, LintResult

#: Schema version of the JSON report (mirrors the observability
#: artifact convention: breaking layout changes bump it).
REPORT_SCHEMA_VERSION = 1


def render_human(result: LintResult, show_suppressed: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    if show_suppressed:
        lines += [f"{f.render()} [suppressed]" for f in result.suppressed]
    by_rule: dict[str, int] = {}
    for f in result.findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    breakdown = (
        " (" + ", ".join(f"{r}×{n}" for r, n in sorted(by_rule.items())) + ")"
        if by_rule
        else ""
    )
    lines.append(
        f"graftlint: {len(result.findings)} finding(s){breakdown}, "
        f"{len(result.suppressed)} suppressed, {result.files} file(s) checked"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    payload = {
        "schema_version": REPORT_SCHEMA_VERSION,
        "files": result.files,
        "findings": [f.as_dict() for f in result.findings],
        "suppressed": [f.as_dict() for f in result.suppressed],
        "rules": {
            rule_id: {"name": cls.name, "description": cls.description}
            for rule_id, cls in sorted(RULES.items())
        },
    }
    return json.dumps(payload, indent=1) + "\n"


def render_rule_table() -> str:
    lines = []
    for rule_id, cls in sorted(RULES.items()):
        lines.append(f"{rule_id}  {cls.name:<24} {cls.description}")
    return "\n".join(lines)

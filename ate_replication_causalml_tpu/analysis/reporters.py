"""Finding reporters: human text, machine JSON, and SARIF 2.1.0."""

from __future__ import annotations

import json

from ate_replication_causalml_tpu.analysis.core import (
    Finding,
    LintResult,
    all_rules,
)

#: Schema version of the JSON report (mirrors the observability
#: artifact convention: breaking layout changes bump it).
REPORT_SCHEMA_VERSION = 1

#: SARIF pins its own version; emitted verbatim in the log.
SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_human(result: LintResult, show_suppressed: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    if show_suppressed:
        lines += [f"{f.render()} [suppressed]" for f in result.suppressed]
    by_rule: dict[str, int] = {}
    for f in result.findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    breakdown = (
        " (" + ", ".join(f"{r}×{n}" for r, n in sorted(by_rule.items())) + ")"
        if by_rule
        else ""
    )
    lines.append(
        f"graftlint: {len(result.findings)} finding(s){breakdown}, "
        f"{len(result.suppressed)} suppressed, {result.files} file(s) checked"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    payload = {
        "schema_version": REPORT_SCHEMA_VERSION,
        "files": result.files,
        "findings": [f.as_dict() for f in result.findings],
        "suppressed": [f.as_dict() for f in result.suppressed],
        "rules": {
            rule_id: {"name": cls.name, "description": cls.description}
            for rule_id, cls in all_rules().items()
        },
    }
    return json.dumps(payload, indent=1) + "\n"


def _sarif_result(f: Finding, suppressed: bool) -> dict:
    out = {
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line, "startColumn": f.col},
                }
            }
        ],
    }
    if suppressed:
        # SARIF's native representation of `# graftlint: disable=` —
        # viewers show these greyed out instead of dropping them.
        out["suppressions"] = [{"kind": "inSource"}]
    return out


def render_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 log — one run, the full rule table as driver rules,
    suppressed findings carried with ``suppressions: inSource``."""
    rules = [
        {
            "id": rule_id,
            "name": cls.name,
            "shortDescription": {"text": cls.name},
            "fullDescription": {"text": cls.description},
        }
        for rule_id, cls in all_rules().items()
    ]
    log = {
        "$schema": _SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "rules": rules,
                    }
                },
                "results": (
                    [_sarif_result(f, False) for f in result.findings]
                    + [_sarif_result(f, True) for f in result.suppressed]
                ),
            }
        ],
    }
    return json.dumps(log, indent=1) + "\n"


def render_rule_table() -> str:
    lines = []
    for rule_id, cls in all_rules().items():
        lines.append(f"{rule_id}  {cls.name:<24} {cls.description}")
    return "\n".join(lines)

"""graftlint core: findings, suppressions, module model, rule registry.

An AST-based lint framework for the failure classes that cost TPU runs
silently instead of loudly: jit caches keyed on ambient backend state,
PRNG keys spent twice, dtype drift against the x64 policy, torn file
writes, unlocked shared state. Rules live in
:mod:`ate_replication_causalml_tpu.analysis.rules`; the CLI is
``scripts/graftlint.py``.

Deliberately stdlib-only (``ast`` + ``tokenize``): the linter must run
in CI images and pre-commit hooks without importing jax — importing the
package under analysis could itself initialize a backend.

Suppression syntax (checked by tests/test_graftlint.py):

* ``code  # graftlint: disable=JGL001`` — suppress on this line
  (comma-separated rule ids, or ``all``);
* a comment-only line ``# graftlint: disable=JGL001`` suppresses the
  next line;
* ``# graftlint: disable-file=JGL004`` anywhere — suppress the rule for
  the whole file.

Suppressed findings are retained (``LintResult.suppressed``) so the
reporters can show what the comments are holding back.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Iterator

#: Rule id for files the parser itself rejects — always active, never
#: suppressible (a file that does not parse cannot carry comments we
#: trust).
PARSE_ERROR_ID = "JGL000"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Suppressions:
    """Per-file suppression state parsed from ``# graftlint:`` comments.

    Comments are found with :mod:`tokenize` (not a substring scan) so a
    ``#`` inside a string literal can never disable a rule.
    """

    def __init__(self, source: str):
        self.file_rules: set[str] = set()
        self.line_rules: dict[int, set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                kind = m.group(1)
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if kind == "disable-file":
                    self.file_rules |= rules
                    continue
                line = tok.start[0]
                self.line_rules.setdefault(line, set()).update(rules)
                # A comment-only line shields the line below it.
                if tok.line[: tok.start[1]].strip() == "":
                    self.line_rules.setdefault(line + 1, set()).update(rules)
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparsable files are reported as JGL000 elsewhere

    def covers(self, rule: str, line: int) -> bool:
        if rule == PARSE_ERROR_ID:
            return False
        for rules in (self.file_rules, self.line_rules.get(line, ())):
            if rule in rules or "all" in rules:
                return True
        return False


class ModuleInfo:
    """Parsed module plus the name-resolution context rules share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = Suppressions(source)
        # alias -> canonical dotted prefix, e.g. jnp -> jax.numpy,
        # lax -> jax.lax, environ -> os.environ, partial ->
        # functools.partial. Collected from every import in the module
        # (function-local imports included: resolution is name-based,
        # not scope-exact, which is the right precision for linting).
        self.aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of a Name/Attribute chain, with the
        leading alias expanded (``jnp.float64`` -> ``jax.numpy.float64``),
        or None for non-name expressions."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])


class Rule:
    """Base class: subclasses set ``id``/``name``/``description`` and
    implement :meth:`check`."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=module.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


#: Registered rule classes keyed by id (populated by @register).
RULES: dict[str, type[Rule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or cls.id in RULES:
        raise ValueError(f"rule id {cls.id!r} missing or already registered")
    RULES[cls.id] = cls
    return cls


@dataclasses.dataclass
class LintResult:
    """Aggregate outcome of a lint run."""

    findings: list[Finding] = dataclasses.field(default_factory=list)
    suppressed: list[Finding] = dataclasses.field(default_factory=list)
    files: int = 0

    def extend(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)
        self.files += other.files

    def sort(self) -> None:
        key = lambda f: (f.path, f.line, f.col, f.rule)
        self.findings.sort(key=key)
        self.suppressed.sort(key=key)


def _active_rules(select: Iterable[str] | None) -> list[Rule]:
    ids = list(RULES) if select is None else list(select)
    unknown = [i for i in ids if i not in RULES]
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
    return [RULES[i]() for i in ids]


def lint_source(
    source: str,
    path: str = "<string>",
    relpath: str | None = None,
    select: Iterable[str] | None = None,
) -> LintResult:
    """Lint one source string. ``relpath`` is what path-scoped rules
    (JGL004/005/006) match against; defaults to ``path``."""
    result = LintResult(files=1)
    try:
        module = ModuleInfo(path, relpath if relpath is not None else path, source)
    except (SyntaxError, ValueError) as e:
        result.findings.append(
            Finding(
                rule=PARSE_ERROR_ID,
                path=relpath if relpath is not None else path,
                line=getattr(e, "lineno", None) or 1,
                col=(getattr(e, "offset", None) or 1),
                message=f"file does not parse: {e.msg if isinstance(e, SyntaxError) else e}",
            )
        )
        return result
    for rule in _active_rules(select):
        for f in rule.check(module):
            if module.suppressions.covers(f.rule, f.line):
                result.suppressed.append(f)
            else:
                result.findings.append(f)
    result.sort()
    return result


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into .py files, sorted, skipping
    __pycache__ and hidden directories."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(
    paths: Iterable[str],
    select: Iterable[str] | None = None,
    root: str | None = None,
) -> LintResult:
    """Lint files/directories. ``root`` anchors the relative paths used
    both for reporting and for the path-scoped rules (default: CWD)."""
    root = os.path.abspath(root or os.getcwd())
    result = LintResult()
    paths = list(paths)
    for p in paths:
        if not os.path.exists(p):
            # A vanished path must FAIL the gate, not pass it vacuously
            # (a package rename would otherwise lint zero files and
            # report a clean tree forever).
            result.findings.append(
                Finding(PARSE_ERROR_ID, p, 1, 1, "path does not exist")
            )
    for path in iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root) if ap.startswith(root + os.sep) else path
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            result.findings.append(
                Finding(PARSE_ERROR_ID, rel, 1, 1, f"unreadable file: {e}")
            )
            result.files += 1
            continue
        result.extend(lint_source(source, path=path, relpath=rel, select=select))
    result.sort()
    return result

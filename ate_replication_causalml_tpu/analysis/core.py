"""graftlint core: findings, suppressions, module model, rule registry.

An AST-based lint framework for the failure classes that cost TPU runs
silently instead of loudly: jit caches keyed on ambient backend state,
PRNG keys spent twice, dtype drift against the x64 policy, torn file
writes, unlocked shared state. Rules live in
:mod:`ate_replication_causalml_tpu.analysis.rules`; the CLI is
``scripts/graftlint.py``.

Deliberately stdlib-only (``ast`` + ``tokenize``): the linter must run
in CI images and pre-commit hooks without importing jax — importing the
package under analysis could itself initialize a backend.

Suppression syntax (checked by tests/test_graftlint.py):

* ``code  # graftlint: disable=JGL001`` — suppress on this line
  (comma-separated rule ids, or ``all``);
* a comment-only line ``# graftlint: disable=JGL001`` suppresses the
  next line;
* ``# graftlint: disable-file=JGL004`` anywhere — suppress the rule for
  the whole file.

Suppressed findings are retained (``LintResult.suppressed``) so the
reporters can show what the comments are holding back.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Iterator

#: Rule id for files the parser itself rejects — always active, never
#: suppressible (a file that does not parse cannot carry comments we
#: trust).
PARSE_ERROR_ID = "JGL000"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Suppressions:
    """Per-file suppression state parsed from ``# graftlint:`` comments.

    Comments are found with :mod:`tokenize` (not a substring scan) so a
    ``#`` inside a string literal can never disable a rule.
    """

    def __init__(self, source: str):
        self.file_rules: set[str] = set()
        self.line_rules: dict[int, set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                kind = m.group(1)
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if kind == "disable-file":
                    self.file_rules |= rules
                    continue
                line = tok.start[0]
                self.line_rules.setdefault(line, set()).update(rules)
                # A comment-only line shields the line below it.
                if tok.line[: tok.start[1]].strip() == "":
                    self.line_rules.setdefault(line + 1, set()).update(rules)
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparsable files are reported as JGL000 elsewhere

    def covers(self, rule: str, line: int) -> bool:
        if rule == PARSE_ERROR_ID:
            return False
        for rules in (self.file_rules, self.line_rules.get(line, ())):
            if rule in rules or "all" in rules:
                return True
        return False


class ModuleInfo:
    """Parsed module plus the name-resolution context rules share."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = Suppressions(source)
        # alias -> canonical dotted prefix, e.g. jnp -> jax.numpy,
        # lax -> jax.lax, environ -> os.environ, partial ->
        # functools.partial. Collected from every import in the module
        # (function-local imports included: resolution is name-based,
        # not scope-exact, which is the right precision for linting).
        self.aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of a Name/Attribute chain, with the
        leading alias expanded (``jnp.float64`` -> ``jax.numpy.float64``),
        or None for non-name expressions."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])


class Rule:
    """Base class: subclasses set ``id``/``name``/``description`` and
    implement :meth:`check`."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=module.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )


class Program:
    """All modules of one lint run — the unit the whole-program rules
    (JGL015+) analyze. Interprocedural passes memoize their derived
    state on the instance (``_graftrace_*`` attributes)."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.modules = list(modules)
        self.by_relpath: dict[str, ModuleInfo] = {
            m.relpath: m for m in self.modules
        }


class ProgramRule(Rule):
    """A rule over the whole :class:`Program` instead of one module.

    ``check`` receives the program; findings still carry a module
    relpath (``self.finding(module, node, msg)``) so per-line
    suppression comments keep working."""

    def check(self, program: Program) -> Iterable[Finding]:  # type: ignore[override]
        raise NotImplementedError


#: Registered per-module rule classes keyed by id (populated by
#: @register).
RULES: dict[str, type[Rule]] = {}

#: Registered whole-program rule classes keyed by id (populated by
#: @register_program). Disjoint from RULES — one id, one registry.
PROGRAM_RULES: dict[str, type[ProgramRule]] = {}


def register(cls: type[Rule]) -> type[Rule]:
    if not cls.id or cls.id in RULES or cls.id in PROGRAM_RULES:
        raise ValueError(f"rule id {cls.id!r} missing or already registered")
    RULES[cls.id] = cls
    return cls


def register_program(cls: type[ProgramRule]) -> type[ProgramRule]:
    if not cls.id or cls.id in RULES or cls.id in PROGRAM_RULES:
        raise ValueError(f"rule id {cls.id!r} missing or already registered")
    PROGRAM_RULES[cls.id] = cls
    return cls


def all_rules() -> dict[str, type[Rule]]:
    """Per-module and program rules in one id-sorted map (reporters and
    ``--list-rules`` present a single table)."""
    merged: dict[str, type[Rule]] = {}
    merged.update(RULES)
    merged.update(PROGRAM_RULES)
    return dict(sorted(merged.items()))


@dataclasses.dataclass
class LintResult:
    """Aggregate outcome of a lint run."""

    findings: list[Finding] = dataclasses.field(default_factory=list)
    suppressed: list[Finding] = dataclasses.field(default_factory=list)
    files: int = 0

    def extend(self, other: "LintResult") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)
        self.files += other.files

    def sort(self) -> None:
        key = lambda f: (f.path, f.line, f.col, f.rule)
        self.findings.sort(key=key)
        self.suppressed.sort(key=key)


def _split_rules(
    select: Iterable[str] | None,
) -> tuple[list[Rule], list[ProgramRule]]:
    if select is None:
        ids = list(RULES) + list(PROGRAM_RULES)
    else:
        ids = list(select)
        unknown = [i for i in ids if i not in RULES and i not in PROGRAM_RULES]
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
    return (
        [RULES[i]() for i in ids if i in RULES],
        [PROGRAM_RULES[i]() for i in ids if i in PROGRAM_RULES],
    )


def _active_rules(select: Iterable[str] | None) -> list[Rule]:
    return _split_rules(select)[0]


def _route(module: ModuleInfo | None, f: Finding, result: LintResult) -> None:
    if module is not None and module.suppressions.covers(f.rule, f.line):
        result.suppressed.append(f)
    else:
        result.findings.append(f)


def _run_module_rules(
    module: ModuleInfo, rules: list[Rule], result: LintResult
) -> None:
    for rule in rules:
        for f in rule.check(module):
            _route(module, f, result)


def _run_program_rules(
    modules: list[ModuleInfo], rules: list[ProgramRule], result: LintResult
) -> None:
    if not rules:
        return
    program = Program(modules)
    for rule in rules:
        for f in rule.check(program):
            _route(program.by_relpath.get(f.path), f, result)


def _parse_error(path: str, e: Exception) -> Finding:
    return Finding(
        rule=PARSE_ERROR_ID,
        path=path,
        line=getattr(e, "lineno", None) or 1,
        col=(getattr(e, "offset", None) or 1),
        message=f"file does not parse: {e.msg if isinstance(e, SyntaxError) else e}",
    )


def lint_source(
    source: str,
    path: str = "<string>",
    relpath: str | None = None,
    select: Iterable[str] | None = None,
) -> LintResult:
    """Lint one source string. ``relpath`` is what path-scoped rules
    (JGL004/005/006) match against; defaults to ``path``. Program rules
    (JGL015+) run over the single-module program — known-bad fixtures
    exercise them exactly like the per-module rules."""
    result = LintResult(files=1)
    mod_rules, prog_rules = _split_rules(select)
    try:
        module = ModuleInfo(path, relpath if relpath is not None else path, source)
    except (SyntaxError, ValueError) as e:
        result.findings.append(_parse_error(relpath if relpath is not None else path, e))
        return result
    _run_module_rules(module, mod_rules, result)
    _run_program_rules([module], prog_rules, result)
    result.sort()
    return result


def lint_sources(
    sources: Iterable[tuple[str, str]],
    select: Iterable[str] | None = None,
) -> LintResult:
    """Lint ``(relpath, source)`` pairs as ONE program — the multi-module
    fixture entry point for the interprocedural rules."""
    result = LintResult()
    mod_rules, prog_rules = _split_rules(select)
    modules: list[ModuleInfo] = []
    for relpath, source in sources:
        result.files += 1
        try:
            module = ModuleInfo(relpath, relpath, source)
        except (SyntaxError, ValueError) as e:
            result.findings.append(_parse_error(relpath, e))
            continue
        modules.append(module)
        _run_module_rules(module, mod_rules, result)
    _run_program_rules(modules, prog_rules, result)
    result.sort()
    return result


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into .py files, sorted, skipping
    __pycache__ and hidden directories."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(
    paths: Iterable[str],
    select: Iterable[str] | None = None,
    root: str | None = None,
    cache=None,
) -> LintResult:
    """Lint files/directories. ``root`` anchors the relative paths used
    both for reporting and for the path-scoped rules (default: CWD).

    Per-module rules run file by file; the program rules (JGL015+) run
    once over every module that parsed. ``cache`` is an optional
    :class:`ate_replication_causalml_tpu.analysis.cache.ResultCache`:
    per-file results are keyed on content hashes and the program pass
    on the whole tree's hash, so a warm run re-lints only what changed
    (and a fully warm run parses nothing at all)."""
    root = os.path.abspath(root or os.getcwd())
    result = LintResult()
    mod_rules, prog_rules = _split_rules(select)
    paths = list(paths)
    for p in paths:
        if not os.path.exists(p):
            # A vanished path must FAIL the gate, not pass it vacuously
            # (a package rename would otherwise lint zero files and
            # report a clean tree forever).
            result.findings.append(
                Finding(PARSE_ERROR_ID, p, 1, 1, "path does not exist")
            )
    entries: list[tuple[str, str, str | None]] = []  # (abspath, rel, source)
    for path in iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root) if ap.startswith(root + os.sep) else path
        try:
            with open(path, encoding="utf-8") as f:
                entries.append((path, rel, f.read()))
        except OSError as e:
            result.findings.append(
                Finding(PARSE_ERROR_ID, rel, 1, 1, f"unreadable file: {e}")
            )
            result.files += 1
            continue
    program_cached = (
        cache.get_program(entries) if cache is not None and prog_rules else None
    )
    need_parse_all = bool(prog_rules) and program_cached is None
    modules: list[ModuleInfo] = []
    for path, rel, source in entries:
        result.files += 1
        cached = cache.get_module(rel, source) if cache is not None else None
        if cached is not None and not need_parse_all:
            result.findings.extend(cached[0])
            result.suppressed.extend(cached[1])
            continue
        try:
            module = ModuleInfo(path, rel, source)
        except (SyntaxError, ValueError) as e:
            result.findings.append(_parse_error(rel, e))
            continue
        modules.append(module)
        if cached is not None:
            result.findings.extend(cached[0])
            result.suppressed.extend(cached[1])
            continue
        per_file = LintResult()
        _run_module_rules(module, mod_rules, per_file)
        result.findings.extend(per_file.findings)
        result.suppressed.extend(per_file.suppressed)
        if cache is not None:
            cache.put_module(rel, source, per_file.findings, per_file.suppressed)
    if prog_rules:
        if program_cached is not None:
            result.findings.extend(program_cached[0])
            result.suppressed.extend(program_cached[1])
        else:
            prog_result = LintResult()
            _run_program_rules(modules, prog_rules, prog_result)
            result.findings.extend(prog_result.findings)
            result.suppressed.extend(prog_result.suppressed)
            if cache is not None:
                cache.put_program(
                    entries, prog_result.findings, prog_result.suppressed
                )
    if cache is not None:
        cache.save()
    result.sort()
    return result

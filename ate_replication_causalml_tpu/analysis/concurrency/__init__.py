"""graftrace: the whole-program concurrency analyzer (JGL015–JGL019).

Stdlib-only like the rest of the linter — importing this package never
imports jax. Importing it registers the program rules; the model
builder backs the committed ``CONCURRENCY_MODEL.json`` artifact.
"""

from ate_replication_causalml_tpu.analysis.concurrency.extract import (
    LOCK_FACTORIES,
    LockDef,
    ModuleConc,
    extract,
)
from ate_replication_causalml_tpu.analysis.concurrency.flow import (
    Analysis,
    analyze,
    is_lane_lock,
)
from ate_replication_causalml_tpu.analysis.concurrency.model import (
    MODEL_SCHEMA_VERSION,
    build_model,
    render_markdown,
    to_json,
)
from ate_replication_causalml_tpu.analysis.concurrency import rules as _rules  # noqa: F401  (registers JGL015–JGL019)

__all__ = [
    "Analysis",
    "LOCK_FACTORIES",
    "LockDef",
    "MODEL_SCHEMA_VERSION",
    "ModuleConc",
    "analyze",
    "build_model",
    "extract",
    "is_lane_lock",
    "render_markdown",
    "to_json",
]

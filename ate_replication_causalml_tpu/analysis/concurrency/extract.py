"""Per-module extraction for the graftrace concurrency analyzer.

This pass is purely lexical-per-module: it finds every lock *object*
(module-global ``threading.Lock()``s, ``self._lock``-style instance
locks, and lock *families* — methods that mint or fetch per-key locks
out of a dict, like ``NuisanceCache._entry_lock``), every thread
*entrypoint* (``threading.Thread(target=...)``, ``do_*`` HTTP handler
methods, worker-pool ``submit`` bodies), and the class structure
(attribute types from ``self.x = Cls(...)`` assignments) that the
interprocedural pass in :mod:`.flow` needs to resolve receivers.

Lock identity convention (stable across runs — the committed
``CONCURRENCY_MODEL.json`` keys on it):

* module global — ``<relpath>::<NAME>``
* instance attribute — ``<relpath>::<Class>.<attr>``
* lock family (lock-returning method) — ``<relpath>::<Class>.<method>()``
"""

from __future__ import annotations

import ast
import dataclasses

from ate_replication_causalml_tpu.analysis.core import ModuleInfo
from ate_replication_causalml_tpu.analysis.jaxast import collect_functions, own_statements

#: threading factory → lock kind.
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
}

#: Attribute types that are synchronization-adjacent but NOT locks —
#: extraction records them so the rules can exempt them (an Event is a
#: one-way memory barrier; thread-locals are unshared by construction).
NONLOCK_SYNC_FACTORIES = {
    "threading.Event": "event",
    "threading.local": "thread-local",
    "threading.Thread": "thread",
    "threading.Barrier": "barrier",
}

_HTTP_HANDLER_BASES = {
    "http.server.BaseHTTPRequestHandler",
    "http.server.SimpleHTTPRequestHandler",
}


@dataclasses.dataclass(frozen=True)
class LockDef:
    id: str
    kind: str  # lock | rlock | condition | semaphore | family-<kind>
    file: str
    line: int


@dataclasses.dataclass
class ThreadRef:
    """One ``threading.Thread(target=...)`` / ``pool.submit(fn)`` site,
    unresolved — :mod:`.flow` maps ``target`` onto a function."""

    kind: str  # thread | pool
    target: ast.expr
    file: str
    line: int
    enclosing: str | None  # qualname of the function containing the call
    thread_name: str | None  # the name= constant, when literal


@dataclasses.dataclass
class ClassInfo:
    qualname: str
    node: ast.ClassDef
    file: str
    attr_locks: dict[str, LockDef] = dataclasses.field(default_factory=dict)
    #: self.attr -> resolved dotted type from ``self.attr = Cls(...)``
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    #: method name -> qualname for every def in the class body
    methods: dict[str, str] = dataclasses.field(default_factory=dict)
    is_http_handler: bool = False

    def owns_concurrency(self) -> bool:
        """Whether instances are plausibly shared across threads: the
        class holds a lock or spawns/holds a thread."""
        return bool(self.attr_locks) or any(
            t in ("threading.Thread",) for t in self.attr_types.values()
        )


@dataclasses.dataclass
class ModuleConc:
    module: ModuleInfo
    global_locks: dict[str, LockDef] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    #: function qualname -> LockDef it returns (family or alias)
    lock_returners: dict[str, LockDef] = dataclasses.field(default_factory=dict)
    thread_refs: list[ThreadRef] = dataclasses.field(default_factory=list)
    #: qualnames that are thread entrypoints by construction (do_* HTTP
    #: handler methods).
    handler_entries: list[str] = dataclasses.field(default_factory=list)

    @property
    def relpath(self) -> str:
        return self.module.relpath


def _factory_kind(module: ModuleInfo, value: ast.expr) -> str | None:
    if isinstance(value, ast.Call):
        return LOCK_FACTORIES.get(module.resolve(value.func) or "")
    return None


def _first_param(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


def _self_attr_target(t: ast.expr, self_name: str | None) -> str | None:
    if (
        self_name is not None
        and isinstance(t, ast.Attribute)
        and isinstance(t.value, ast.Name)
        and t.value.id == self_name
    ):
        return t.attr
    return None


def _scan_class_attrs(
    conc: ModuleConc, info: ClassInfo, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> None:
    module = conc.module
    self_name = _first_param(fn)
    for node in own_statements(fn):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            attr = _self_attr_target(t, self_name)
            if attr is None:
                continue
            kind = _factory_kind(module, node.value)
            if kind is not None:
                info.attr_locks.setdefault(
                    attr,
                    LockDef(
                        id=f"{module.relpath}::{info.qualname}.{attr}",
                        kind=kind,
                        file=module.relpath,
                        line=node.lineno,
                    ),
                )
                continue
            if isinstance(node.value, ast.Call):
                ctor = module.resolve(node.value.func)
                if ctor:
                    info.attr_types.setdefault(attr, ctor)


def _returned_lock(
    conc: ModuleConc, qual: str, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> tuple[str, int] | None:
    """``(kind, line)`` when ``fn`` returns a threading-factory product
    (directly, via a local assigned from one, or via ``.setdefault``) —
    the lock-family shape (``_entry_lock``/``lane_lock``)."""
    module = conc.module
    factory_locals: dict[str, str] = {}
    for node in own_statements(fn):
        if isinstance(node, ast.Assign):
            kind = None
            if isinstance(node.value, ast.Call):
                resolved = module.resolve(node.value.func) or ""
                kind = LOCK_FACTORIES.get(resolved)
                if kind is None and (
                    isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "setdefault"
                    and len(node.value.args) == 2
                ):
                    kind = _factory_kind(module, node.value.args[1])
            if kind is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        factory_locals[t.id] = kind
    for node in own_statements(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        kind = _factory_kind(module, v)
        if kind is None and isinstance(v, ast.Call):
            if (
                isinstance(v.func, ast.Attribute)
                and v.func.attr == "setdefault"
                and len(v.args) == 2
            ):
                kind = _factory_kind(module, v.args[1])
        if kind is None and isinstance(v, ast.Name):
            kind = factory_locals.get(v.id)
        if kind is not None:
            return kind, fn.lineno
    return None


def _forwarded_lock(
    conc: ModuleConc, info: ClassInfo | None, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> LockDef | None:
    """A function whose return forwards another lock source:
    ``return self._lock`` (accessor) or ``return self.lane_lock(x)``
    (maybe-guard like ``_lane_guard``) resolves to THAT lock's id."""
    self_name = _first_param(fn)
    for node in own_statements(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        attr = _self_attr_target(v, self_name)
        if attr is not None and info is not None and attr in info.attr_locks:
            return info.attr_locks[attr]
        if isinstance(v, ast.Call):
            cattr = _self_attr_target(v.func, self_name)
            if cattr is not None and info is not None:
                target_qual = info.methods.get(cattr)
                if target_qual is not None and target_qual in conc.lock_returners:
                    return conc.lock_returners[target_qual]
            if isinstance(v.func, ast.Name):
                target = v.func.id
                if target in conc.lock_returners:
                    return conc.lock_returners[target]
    return None


def extract(module: ModuleInfo) -> ModuleConc:
    """Extract the module's concurrency surface (see module docstring)."""
    conc = ModuleConc(module=module)
    rel = module.relpath

    # Module-global locks.
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            kind = _factory_kind(module, node.value)
            if kind is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    conc.global_locks[t.id] = LockDef(
                        id=f"{rel}::{t.id}", kind=kind, file=rel, line=node.lineno
                    )

    # Classes: attr locks/types, methods, HTTP-handler detection.
    def visit_classes(parent: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(parent):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}"
                info = ClassInfo(qualname=qual, node=child, file=rel)
                for base in child.bases:
                    resolved = module.resolve(base) or ""
                    if (
                        resolved in _HTTP_HANDLER_BASES
                        or resolved.endswith("RequestHandler")
                    ):
                        info.is_http_handler = True
                for item in child.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info.methods[item.name] = f"{qual}.{item.name}"
                        _scan_class_attrs(conc, info, item)
                conc.classes[qual] = info
                if info.is_http_handler:
                    conc.handler_entries.extend(
                        q for m, q in sorted(info.methods.items())
                        if m.startswith("do_")
                    )
                visit_classes(child, qual + ".")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_classes(child, prefix)

    visit_classes(module.tree, "")

    # Lock-returning functions: direct factories first, then forwarding
    # accessors/maybe-guards (which may chain onto the former).
    records = collect_functions(module)
    for qual, rec in sorted(records.items()):
        got = _returned_lock(conc, qual, rec.node)
        if got is not None:
            kind, line = got
            conc.lock_returners[qual] = LockDef(
                id=f"{rel}::{qual}()", kind=f"family-{kind}", file=rel, line=line
            )
    for _ in range(2):  # forwarding can chain one level (guard -> family)
        for qual, rec in sorted(records.items()):
            if qual in conc.lock_returners:
                continue
            cls_qual = qual.rsplit(".", 1)[0] if "." in qual else None
            info = conc.classes.get(cls_qual) if cls_qual else None
            fwd = _forwarded_lock(conc, info, rec.node)
            if fwd is not None:
                conc.lock_returners[qual] = fwd

    # Thread spawn / pool submit sites.
    for qual, rec in sorted(records.items()):
        for node in own_statements(rec.node):
            _collect_thread_refs(conc, node, qual)
    for node in module.tree.body:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        else:
            _collect_thread_refs(conc, node, None, deep=True)
    return conc


def _is_executor_receiver(recv: ast.expr) -> bool:
    """Whether ``<recv>.submit(fn)`` plausibly targets a worker pool.
    The serving plane has domain ``submit`` methods (the coalescer, the
    daemon's request API) whose first argument is data, not a callable
    — only executor-shaped receivers count as thread entrypoints."""
    try:
        text = ast.unparse(recv).lower()
    except Exception:
        return False
    return "pool" in text or "executor" in text


def _collect_thread_refs(
    conc: ModuleConc, node: ast.AST, enclosing: str | None, deep: bool = False
) -> None:
    module = conc.module
    nodes = ast.walk(node) if deep else (node,)
    for sub in nodes:
        if not isinstance(sub, ast.Call):
            continue
        resolved = module.resolve(sub.func) or ""
        if resolved == "threading.Thread":
            target = None
            name = None
            for kw in sub.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = str(kw.value.value)
            if target is None and sub.args:
                target = sub.args[0]
            if target is not None:
                conc.thread_refs.append(
                    ThreadRef(
                        kind="thread",
                        target=target,
                        file=module.relpath,
                        line=sub.lineno,
                        enclosing=enclosing,
                        thread_name=name,
                    )
                )
        elif (
            isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "submit"
            and sub.args
            and isinstance(sub.args[0], (ast.Name, ast.Attribute))
            and _is_executor_receiver(sub.func.value)
        ):
            conc.thread_refs.append(
                ThreadRef(
                    kind="pool",
                    target=sub.args[0],
                    file=module.relpath,
                    line=sub.lineno,
                    enclosing=enclosing,
                    thread_name=None,
                )
            )
